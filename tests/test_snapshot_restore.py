"""maybe_restore rejection branches + snapshot commit protocol (ISSUE 3).

Every rejection branch must (a) refuse the restore, (b) log a warning
that names the cause, and (c) leave the store fully usable — a refused
restore is a cold boot, not a crash. The commit-protocol tests pin the
generation-named state files that make a snapshot crash-consistent
(meta.json is the single atomic commit point; see tpu/snapshot.py).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os

import numpy as np
import pytest

from tests.fixtures import lots_of_spans
from zipkin_tpu.parallel.mesh import make_mesh
from zipkin_tpu.tpu import snapshot
from zipkin_tpu.tpu.state import AggConfig
from zipkin_tpu.tpu.store import TpuStorage

CFG = AggConfig(
    max_services=16, max_keys=64, hll_precision=6, digest_centroids=8,
    digest_buffer=512, ring_capacity=512, link_buckets=2,
    bucket_minutes=60, hist_slices=2,
)


def _store(n_devices=1):
    return TpuStorage(config=CFG, mesh=make_mesh(n_devices), pad_to_multiple=64)


def _saved(tmp_path):
    store = _store()
    store.accept(lots_of_spans(120, seed=7, services=4, span_names=6)).execute()
    d = str(tmp_path / "snap")
    snapshot.save(store, d)
    return store, d


def _meta(d):
    return json.load(open(os.path.join(d, snapshot.META_FILE)))


def _write_meta(d, meta):
    json.dump(meta, open(os.path.join(d, snapshot.META_FILE), "w"))


def _assert_usable(store):
    store.accept(lots_of_spans(60, seed=9, services=4, span_names=6)).execute()
    assert store.agg.host_counters["spans"] > 0
    assert store.trace_cardinalities()  # a read round-trips


def _refused(store, d, caplog, needle):
    caplog.clear()
    with caplog.at_level(logging.WARNING):
        assert not snapshot.maybe_restore(store, d)
    assert needle in caplog.text, caplog.text
    _assert_usable(store)


def test_version_mismatch_refused_with_cause(tmp_path, caplog):
    store, d = _saved(tmp_path)
    meta = _meta(d)
    meta["version"] = snapshot.SNAPSHOT_VERSION - 1
    _write_meta(d, meta)
    _refused(store, d, caplog, "format version")


def test_config_mismatch_refused_with_cause(tmp_path, caplog):
    store, d = _saved(tmp_path)
    meta = _meta(d)
    meta["config"] = dict(meta["config"], max_keys=9999)
    _write_meta(d, meta)
    _refused(store, d, caplog, "config changed")


def test_shard_count_mismatch_refused_with_cause(tmp_path, caplog):
    _, d = _saved(tmp_path)  # snapshot taken on a 1-shard mesh
    two = _store(n_devices=2)
    _refused(two, d, caplog, "shards")


def test_leaf_count_mismatch_refused_with_cause(tmp_path, caplog):
    store, d = _saved(tmp_path)
    state_path = os.path.join(d, _meta(d)["state_file"])
    loaded = np.load(state_path)
    arrays = {f"f{i}": loaded[f"f{i}"] for i in range(len(loaded.files) - 1)}
    with open(state_path, "wb") as f:
        np.savez_compressed(f, **arrays)
    _refused(store, d, caplog, "leaf count")


def test_leaf_shape_mismatch_refused_with_cause(tmp_path, caplog):
    store, d = _saved(tmp_path)
    state_path = os.path.join(d, _meta(d)["state_file"])
    loaded = np.load(state_path)
    arrays = {f"f{i}": loaded[f"f{i}"] for i in range(len(loaded.files))}
    # same version + config + leaf count, but one leaf's sizing drifted
    f0 = arrays["f0"]
    arrays["f0"] = np.zeros(tuple(s + 1 for s in f0.shape), f0.dtype)
    with open(state_path, "wb") as f:
        np.savez_compressed(f, **arrays)
    _refused(store, d, caplog, "layout drift")
    # the warning names the drifted leaf, not just "a leaf"
    fields = getattr(type(store.agg.state), "_fields", None)
    assert (fields[0] if fields else "f0") in caplog.text


def test_missing_state_file_refused_with_cause(tmp_path, caplog):
    store, d = _saved(tmp_path)
    os.unlink(os.path.join(d, _meta(d)["state_file"]))
    _refused(store, d, caplog, "missing state file")


def test_intact_snapshot_restores(tmp_path):
    store, d = _saved(tmp_path)
    fresh = _store()
    assert snapshot.maybe_restore(fresh, d)
    assert fresh.agg.host_counters == store.agg.host_counters
    assert fresh.vocab.services._names == store.vocab.services._names


# -- commit protocol -----------------------------------------------------


def test_generations_pruned_and_meta_references_state(tmp_path):
    store, d = _saved(tmp_path)
    snapshot.save(store, d)
    snapshot.save(store, d)
    gens = [n for n in os.listdir(d) if n.startswith("sketch_state-")]
    assert len(gens) == 1, gens  # superseded generations pruned
    assert _meta(d)["state_file"] == gens[0]
    assert not [n for n in os.listdir(d) if n.endswith(".tmp")]


def test_legacy_snapshot_layout_still_restores(tmp_path):
    """Snapshots written before the commit protocol have a fixed-name
    state file and no state_file key in meta; they must keep restoring."""
    store, d = _saved(tmp_path)
    meta = _meta(d)
    os.replace(
        os.path.join(d, meta.pop("state_file")),
        os.path.join(d, snapshot.STATE_FILE),
    )
    _write_meta(d, meta)
    fresh = _store()
    assert snapshot.maybe_restore(fresh, d)
    assert fresh.agg.host_counters == store.agg.host_counters
    # and the next save retires the legacy file for the new protocol
    snapshot.save(fresh, d)
    assert not os.path.exists(os.path.join(d, snapshot.STATE_FILE))
    assert "state_file" in _meta(d)


def test_save_rejects_unknown_future_fields_roundtrip(tmp_path):
    """Config identity is exact: a snapshot taken under the same config
    round-trips dataclasses.asdict comparison through JSON."""
    store, d = _saved(tmp_path)
    want = json.loads(json.dumps(dataclasses.asdict(store.config)))
    assert _meta(d)["config"] == want
