"""Model normalization contract, mirroring SpanTest/EndpointTest upstream."""

import pytest

from zipkin_tpu.model.span import (
    Annotation,
    DependencyLink,
    Endpoint,
    Kind,
    Span,
    merge_links,
    merge_spans,
)


class TestIds:
    def test_trace_id_pads_to_16(self):
        assert Span.create("1234", "1").trace_id == "0000000000001234"

    def test_long_trace_id_pads_to_32(self):
        s = Span.create("48485a3953bb6124" + "1234", "1")
        assert len(s.trace_id) == 32
        assert s.trace_id == "000000000000" + "48485a3953bb61241234"

    def test_trace_id_lowercased(self):
        assert Span.create("48485A3953BB6124", "1").trace_id == "48485a3953bb6124"

    def test_trace_id_low64(self):
        s = Span.create("463ac35c9f6413ad48485a3953bb6124", "1")
        assert s.trace_id_low64 == 0x48485A3953BB6124

    @pytest.mark.parametrize("bad", ["", "g", "x" * 16, "a" * 33, "0" * 32])
    def test_invalid_trace_id_raises(self, bad):
        with pytest.raises(ValueError):
            Span.create(bad, "1")

    def test_span_id_pads(self):
        assert Span.create("1", "2a").id == "000000000000002a"

    def test_all_zero_span_id_raises(self):
        with pytest.raises(ValueError):
            Span.create("1", "0")

    def test_all_zero_parent_is_none(self):
        assert Span.create("1", "2", parent_id="0000000000000000").parent_id is None
        assert Span.create("1", "2", parent_id="").parent_id is None


class TestNormalization:
    def test_name_lowercased_and_empty_is_none(self):
        assert Span.create("1", "2", name="GET /Api").name == "get /api"
        assert Span.create("1", "2", name="").name is None

    def test_kind_parses_from_string(self):
        assert Span.create("1", "2", kind="client").kind is Kind.CLIENT
        with pytest.raises(ValueError):
            Span.create("1", "2", kind="bogus")

    def test_zero_timestamp_duration_become_none(self):
        s = Span.create("1", "2", timestamp=0, duration=0)
        assert s.timestamp is None and s.duration is None
        assert s.timestamp_as_long() == 0 and s.duration_as_long() == 0

    def test_annotations_sorted_and_deduped(self):
        s = Span.create(
            "1", "2", annotations=[(2, "b"), (1, "a"), (2, "b"), (1, "z")]
        )
        assert s.annotations == (
            Annotation(1, "a"),
            Annotation(1, "z"),
            Annotation(2, "b"),
        )

    def test_error_tag_presence_is_error(self):
        assert Span.create("1", "2", tags={"error": ""}).is_error
        assert not Span.create("1", "2", tags={"status": "500"}).is_error

    def test_false_flags_become_none(self):
        s = Span.create("1", "2", debug=False, shared=False)
        assert s.debug is None and s.shared is None


class TestEndpoint:
    def test_service_name_lowercased(self):
        assert Endpoint.create("FavStar").service_name == "favstar"

    def test_all_empty_is_none(self):
        assert Endpoint.create(None, None, None) is None
        assert Endpoint.create("", "", 0) is None

    def test_ip_routes_by_family(self):
        ep = Endpoint.create("x", "192.168.1.1")
        assert ep.ipv4 == "192.168.1.1" and ep.ipv6 is None
        ep = Endpoint.create("x", "2001:db8::1")
        assert ep.ipv6 == "2001:db8::1" and ep.ipv4 is None

    def test_mapped_ipv4_stored_as_ipv4(self):
        ep = Endpoint.create("x", "::ffff:192.168.1.1")
        assert ep.ipv4 == "192.168.1.1" and ep.ipv6 is None

    def test_unparseable_ip_dropped(self):
        assert Endpoint.create("x", "not-an-ip").ipv4 is None

    def test_port_zero_is_none_and_range_checked(self):
        assert Endpoint.create("x", None, 0).port is None
        with pytest.raises(ValueError):
            Endpoint.create("x", None, 65536)


class TestMerge:
    def test_merge_unions_fields(self):
        a = Span.create("1", "2", name="get", timestamp=5, tags={"k": "v"})
        b = Span.create("1", "2", kind="CLIENT", timestamp=3, duration=7,
                        tags={"k": "ignored", "k2": "v2"})
        m = merge_spans(a, b)
        assert m.name == "get"
        assert m.kind is Kind.CLIENT
        assert m.timestamp == 3 and m.duration == 7
        assert m.tags == {"k": "v", "k2": "v2"}

    def test_merge_requires_same_key(self):
        a = Span.create("1", "2")
        b = Span.create("1", "2", shared=True)
        with pytest.raises(ValueError):
            merge_spans(a, b)

    def test_merge_links_sums(self):
        merged = merge_links(
            [
                DependencyLink("a", "b", 1, 0),
                DependencyLink("a", "b", 2, 1),
                DependencyLink("a", "c", 1, 0),
            ]
        )
        assert merged == (
            DependencyLink("a", "b", 3, 1),
            DependencyLink("a", "c", 1, 0),
        )
