"""Resume supervisor (ISSUE 3): degraded-window detection, deadline
trips, and the snapshot→exit→boot→resume round trip with zero
acked-span loss."""

from __future__ import annotations

import threading

import pytest

from tests.test_wal import assert_query_parity, batches, make
from zipkin_tpu.runtime.supervisor import EX_RESTART, ResumeSupervisor


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _drive(sup, clock, rate, seconds, spans_start=0):
    """Advance 1 second per observe at the given spans/s; returns the
    (last reason, final span count)."""
    spans = spans_start
    reason = None
    for _ in range(seconds):
        clock.t += 1.0
        spans += rate
        reason = sup.observe(spans)
        if reason:
            break
    return reason, spans


def test_degraded_windows_trip_against_rolling_baseline():
    clock = FakeClock()
    sup = ResumeSupervisor(
        None, window_s=1.0, warmup_windows=3, degraded_fraction=0.5,
        degraded_windows=3, clock=clock,
    )
    sup.observe(0)  # establishes t0
    reason, spans = _drive(sup, clock, rate=1000, seconds=6)
    assert reason is None
    assert sup.baseline_rate() == pytest.approx(1000.0)

    # one bad window then recovery: no trip, the run counter resets
    reason, spans = _drive(sup, clock, 100, 1, spans)
    assert reason is None
    reason, spans = _drive(sup, clock, 1000, 3, spans)
    assert reason is None

    # a sustained collapse trips after exactly degraded_windows windows
    reason, spans = _drive(sup, clock, 100, 2, spans)
    assert reason is None
    reason, spans = _drive(sup, clock, 100, 1, spans)
    assert reason == "degraded"
    assert sup.tripped == "degraded"
    # degraded windows never fed the baseline
    assert sup.baseline_rate() == pytest.approx(1000.0)
    # sticky: later observations keep reporting the trip
    assert sup.observe(spans + 1000) == "degraded"
    stats = sup.stats()
    assert stats["supervisorTripped"] == "degraded"
    assert stats["supervisorBaselineRate"] == pytest.approx(1000.0)


def test_deadline_trips_regardless_of_rate():
    clock = FakeClock()
    sup = ResumeSupervisor(
        None, window_s=1.0, deadline_s=5.0, clock=clock,
    )
    sup.observe(0)
    reason, _ = _drive(sup, clock, rate=10_000, seconds=4)
    assert reason is None
    reason, _ = _drive(sup, clock, rate=10_000, seconds=1, spans_start=40_000)
    assert reason == "deadline"
    assert EX_RESTART == 75


def test_threaded_driver_invokes_on_trip():
    class StubStore:
        def __init__(self):
            self.spans = 0

        def ingest_counters(self):
            return {"spans": self.spans}

    store = StubStore()
    sup = ResumeSupervisor(store, window_s=0.02, deadline_s=0.05)
    tripped = threading.Event()
    reasons = []
    sup.start(lambda r: (reasons.append(r), tripped.set()))
    assert tripped.wait(5.0)
    sup.stop()
    assert reasons == ["deadline"]


def test_round_trip_snapshot_exit_boot_resume_zero_acked_loss(tmp_path):
    """The acceptance-criteria round trip: a supervised run trips, the
    supervisor drains + snapshots, the process 'exits' (store
    abandoned), a relaunch boots from the same dirs, and the resumed
    run finishes with bit-identical parity vs an uninterrupted oracle —
    zero acked-span loss across the window boundary."""
    bs = batches(6)
    clock = FakeClock()

    # window 1: supervised ingest trips on its deadline mid-run
    victim = make(tmp_path)
    sup = ResumeSupervisor(
        victim, window_s=1.0, deadline_s=3.5, clock=clock,
    )
    sent = 0
    tripped_at = None
    for i, spans in enumerate(bs):
        victim.accept(spans).execute()
        sent = victim.agg.host_counters["spans"]
        clock.t += 1.0
        if sup.observe(sent):
            tripped_at = i
            break
    assert tripped_at is not None and tripped_at < len(bs) - 1
    assert sup.finalize() is not None  # drain + exit snapshot taken
    acked = victim.agg.host_counters["spans"]
    del victim  # exit restartable (EX_RESTART): HBM state gone

    # window 2: relaunch restores flagship state and continues
    resumed = make(tmp_path)
    assert resumed.agg.host_counters["spans"] == acked  # zero acked loss
    assert resumed.resume_offset == acked  # transport offset resume point
    # the exit snapshot covered the WAL, so boot replayed (almost)
    # nothing — restore came from the snapshot itself
    assert resumed.restore_stats["walReplayBatches"] == 0
    for spans in bs[tripped_at + 1:]:
        resumed.accept(spans).execute()

    oracle = make(tmp_path / "oracle", wal=False, checkpoint=False)
    for spans in bs:
        oracle.accept(spans).execute()
    assert_query_parity(oracle, resumed)


def test_finalize_without_snapshot_dir_is_safe(tmp_path):
    store = make(tmp_path, checkpoint=False)
    sup = ResumeSupervisor(store, deadline_s=0.001)
    assert sup.finalize() is None
    store.close()
