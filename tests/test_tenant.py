"""Tenant-isolated admission, budgets, and flood containment (ISSUE 18).

The property under test is CONTAINMENT: one flooding tenant is driven
to per-tenant B2/B3 admission while every other tenant — and the global
brownout ladder — stays at B0. The satellites ride along: bounded
per-tenant key spaces (admission LRU, retained-spans budget table,
tenant-prefixed mirror demand keys), tenant-scoped fault injection, the
per-tenant SLO grammar, and the ``{tenant=}`` prometheus families.
"""

from __future__ import annotations

import pytest

from zipkin_tpu import faults, native
from zipkin_tpu.runtime.overload import B0, B3, CLASS_ERROR, OverloadController
from zipkin_tpu.runtime.tenant import (
    CURRENT_TENANT,
    DEFAULT_TENANT,
    TenantAdmission,
    normalize_tenant,
    tenant_slug,
)
from zipkin_tpu.sampling.controller import TenantBudgetTable


class Clock:
    """Injectable monotonic clock: refill math becomes deterministic."""

    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(autouse=True)
def _always_disarm():
    yield
    faults.disarm()


# -- identity ------------------------------------------------------------


class TestNormalizeTenant:
    def test_valid_ids_pass_through(self):
        for raw in ("acme", "team-a", "a.b_c-9", "X" * 64):
            assert normalize_tenant(raw) == raw

    def test_missing_and_hostile_collapse_to_default(self):
        hostile = [
            None, "", "   ", "a" * 65, 'ten"ant', "ten{ant}", "a/b",
            "a b", "t\nx", "café", "\x00",
        ]
        for raw in hostile:
            assert normalize_tenant(raw) == DEFAULT_TENANT

    def test_whitespace_stripped(self):
        assert normalize_tenant("  acme  ") == "acme"

    def test_slug_is_counter_safe(self):
        assert tenant_slug("team-a.eu") == "team_a_eu"
        assert tenant_slug("simple") == "simple"


# -- TenantAdmission ------------------------------------------------------


class TestTenantAdmission:
    def test_accounting_only_always_admits(self):
        clk = Clock()
        ta = TenantAdmission(bytes_per_s=0.0, clock=clk)
        for _ in range(50):
            ok, retry = ta.admit("a", 10_000)
            assert ok and retry == 0.0
        c = ta.counters()
        assert c["tenantOffered_a"] == 50
        assert c["tenantAdmitted_a"] == 50
        assert c["tenantShedTotal"] == 0

    def test_bucket_shed_with_per_tenant_retry(self):
        clk = Clock()
        ta = TenantAdmission(bytes_per_s=100.0, burst_s=1.0, clock=clk)
        ok, retry = ta.admit("a", 60)
        assert ok and retry == 0.0
        ok, retry = ta.admit("a", 60)  # 40 tokens left < 60
        assert not ok
        # deficit 20B at 100B/s, level-2 scaling: 0.2 * 3 = 0.6s
        assert retry == pytest.approx(0.6)
        assert ta.level_of("a") == 2
        # a fresh tenant's bucket is untouched by a's shed
        ok, _ = ta.admit("b", 60)
        assert ok and ta.level_of("b") == 0

    def test_error_class_lifeline_below_level3(self):
        clk = Clock()
        ta = TenantAdmission(bytes_per_s=100.0, burst_s=1.0, clock=clk)
        assert ta.admit("a", 100)[0]        # drain the bucket
        assert not ta.admit("a", 50)[0]     # bulk: shed, level 2
        assert ta.admit("a", 50, cls="error")[0]  # lifeline rides through

    def test_flood_escalates_to_essential_only(self):
        clk = Clock()
        ta = TenantAdmission(
            bytes_per_s=100.0, burst_s=1.0, flood_ratio=2.0, clock=clk,
        )
        # 16x the budget offered in one tick: pressure EMA (alpha .5)
        # lands at 8 >= 2*flood_ratio -> straight to level 3
        for _ in range(16):
            ta.admit("flood", 100)
        ta.tick(1.0)
        assert ta.level_of("flood") == 3
        # refill, then: bulk is still shed AT level 3, error admitted
        clk.advance(5.0)
        assert not ta.admit("flood", 10)[0]
        assert ta.admit("flood", 10, cls="error")[0]
        # a quiet tenant ticked alongside stays at level 0
        ta.admit("quiet", 10)
        assert ta.level_of("quiet") == 0

    def test_exit_hysteresis_steps_down_one_level_per_dwell(self):
        clk = Clock()
        ta = TenantAdmission(
            bytes_per_s=100.0, burst_s=1.0, flood_ratio=2.0,
            dwell_ticks=1, clock=clk,
        )
        for _ in range(16):
            ta.admit("f", 100)
        ta.tick(1.0)
        assert ta.level_of("f") == 3
        levels = []
        for _ in range(6):  # calm: no offers, bucket refills each tick
            clk.advance(2.0)
            ta.tick(1.0)
            levels.append(ta.level_of("f"))
        # pressure halves each calm tick (8,4,2,1,.5...): two sub-1.0
        # calm ticks walk 3 -> 2 -> 0, never a direct 3 -> 0 jump
        assert levels[-1] == 0
        assert 2 in levels
        assert ta.level_of("f") == 0

    def test_lru_bounded_and_default_never_evicted(self):
        clk = Clock()
        ta = TenantAdmission(bytes_per_s=0.0, max_tenants=4, clock=clk)
        ta.admit(DEFAULT_TENANT, 1)
        for i in range(10):
            ta.admit(f"hostile-{i}", 1)
        c = ta.counters()
        assert c["tenantTableSize"] <= 4
        assert c["tenantEvictions"] >= 7
        assert DEFAULT_TENANT in ta.status()["tenants"]

    def test_retry_for_unknown_tenant_is_floor(self):
        ta = TenantAdmission(bytes_per_s=100.0, clock=Clock())
        assert ta.retry_after_s("never-seen") == 0.05

    def test_retained_budget_gates_next_admission(self):
        clk = Clock()
        table = TenantBudgetTable(
            spans_per_s=10.0, burst_s=1.0, clock=clk,
        )
        ta = TenantAdmission(
            bytes_per_s=10_000.0, burst_s=1.0, clock=clk,
            retained_table=table,
        )
        assert ta.admit("a", 100)[0]
        ta.note_retained("a", 50)   # 5x the burst: bucket deep in debt
        assert table.over_budget("a")
        ok, retry = ta.admit("a", 100)  # plenty of byte-tokens left
        assert not ok and retry > 0.0
        assert ta.status()["tenants"]["a"]["retainedShed"] == 1
        assert ta.status()["tenants"]["a"]["retainedSpans"] == 50
        # error class still rides through retention debt
        assert ta.admit("a", 100, cls="error")[0]

    def test_status_shape_for_statusz(self):
        ta = TenantAdmission(bytes_per_s=100.0, clock=Clock())
        ta.admit("a", 10)
        st = ta.status()
        assert st["enabled"] and st["budgetBytesPerS"] == 100.0
        row = st["tenants"]["a"]
        for key in ("level", "pressure", "offered", "admitted", "shed",
                    "retainedSpans", "retainedShed", "tokens"):
            assert key in row


# -- TenantBudgetTable (sampling tier) -------------------------------------


class TestTenantBudgetTable:
    def test_disabled_tallies_without_enforcing(self):
        t = TenantBudgetTable(spans_per_s=0.0, clock=Clock())
        assert t.charge("a", 1_000_000)
        assert not t.over_budget("a")
        assert t.counters()["tenantRetainedTotal"] == 1_000_000

    def test_debt_then_refill(self):
        clk = Clock()
        t = TenantBudgetTable(spans_per_s=10.0, burst_s=1.0, clock=clk)
        assert t.charge("a", 5)          # 5 tokens left
        assert not t.charge("a", 10)     # -5: in debt
        assert t.over_budget("a")
        clk.advance(1.0)                 # +10 spans refill
        assert not t.over_budget("a")

    def test_over_budget_never_creates_rows(self):
        t = TenantBudgetTable(spans_per_s=10.0, clock=Clock())
        assert not t.over_budget("ghost")
        assert t.counters()["tenantBudgetTableSize"] == 0

    def test_lru_bounded_and_default_kept(self):
        t = TenantBudgetTable(
            spans_per_s=10.0, max_tenants=3, clock=Clock(),
        )
        t.charge("default", 1)
        for i in range(10):
            t.charge(f"hostile-{i}", 1)
        c = t.counters()
        assert c["tenantBudgetTableSize"] <= 3
        assert c["tenantBudgetEvictions"] >= 8
        assert t.retained("default") == 1


# -- containment through the overload controller ---------------------------


class TestOverloadContainment:
    def _controller(self, clk):
        ctl = OverloadController(clock=clk)
        ctl.tenant_admission = TenantAdmission(
            bytes_per_s=100.0, burst_s=1.0, clock=clk,
        )
        return ctl

    def test_flooding_tenant_sheds_alone_global_stays_b0(self):
        clk = Clock()
        ctl = self._controller(clk)
        payload = b"x" * 60
        v = ctl.admit(payload, tenant="B")
        assert v.admitted and v.scope == "none"
        v = ctl.admit(payload, tenant="B")  # B's bucket is dry
        assert not v.admitted
        assert v.scope == "tenant" and v.tenant == "B"
        assert v.retry_after_s > 0.0
        # A and C are untouched by B's shed
        for t in ("A", "C"):
            v = ctl.admit(payload, tenant=t)
            assert v.admitted and v.scope == "none"
        assert ctl.evaluate({"critpathQueueSaturation": 0.0}) == B0
        c = ctl.counters()
        assert c["overloadLevel"] == B0
        assert c["overloadShedTenant"] == 1
        assert c["tenantShed_B"] == 1
        assert c["tenantLevel_B"] == 2
        assert c["tenantLevel_A"] == 0 and c["tenantLevel_C"] == 0

    def test_global_shed_reports_global_scope(self):
        clk = Clock()
        ctl = OverloadController(clock=clk)  # no tenant table
        for _ in range(12):
            if ctl.evaluate({"critpathQueueSaturation": 0.9}) >= B3:
                break
        assert ctl.level == B3
        v = ctl.admit(b"x" * 10, tenant="A")
        assert not v.admitted and v.scope == "global"
        assert v.retry_after_s > 0.0
        # essential class survives global B3, attributed to its tenant
        v = ctl.admit(b"", tenant="A", value_class=CLASS_ERROR)
        assert v.admitted and v.tenant == "A"

    def test_missing_tenant_lands_on_default(self):
        ctl = self._controller(Clock())
        v = ctl.admit(b"x")
        assert v.tenant == DEFAULT_TENANT and v.admitted

    def test_retry_guidance_is_tenant_scoped(self):
        clk = Clock()
        ctl = self._controller(clk)
        ctl.admit(b"x" * 100, tenant="B")
        assert not ctl.admit(b"x" * 100, tenant="B").admitted
        # tenant route: B's own refill horizon, not the global backoff
        assert ctl.retry_after_s("B") > 0.0
        assert ctl.retry_after_s(None) >= 0.0


# -- tenant-scoped fault injection -----------------------------------------


class TestTenantScopedFaults:
    def test_only_the_named_tenant_fires(self):
        faults.arm_resource(
            "feed.latency", nth=1, count=1, latency_ms=1.0, tenant="B",
        )
        for _ in range(5):
            faults.resource_point("feed.latency", tenant="A")
        assert faults.is_resource_armed("feed.latency")  # A never consumed it
        faults.resource_point("feed.latency", tenant="B")
        assert not faults.is_resource_armed("feed.latency")

    def test_nonmatching_tenants_do_not_consume_nth(self):
        faults.arm_resource(
            "feed.latency", nth=2, count=1, latency_ms=1.0, tenant="B",
        )
        for _ in range(5):
            faults.resource_point("feed.latency", tenant="A")
        faults.resource_point("feed.latency", tenant="B")  # 1st traversal
        assert faults.is_resource_armed("feed.latency")
        faults.resource_point("feed.latency", tenant="B")  # 2nd: fires
        assert not faults.is_resource_armed("feed.latency")

    def test_contextvar_fallback_attribution(self):
        faults.arm_resource(
            "feed.latency", nth=1, count=1, latency_ms=1.0, tenant="B",
        )
        tok = CURRENT_TENANT.set("B")
        try:
            faults.resource_point("feed.latency")  # ambient tenant
        finally:
            CURRENT_TENANT.reset(tok)
        assert not faults.is_resource_armed("feed.latency")

    def test_env_grammar_parses_tenant_scope(self, monkeypatch):
        for var in (faults.ENV_VAR, faults.ENV_CORRUPT):
            monkeypatch.delenv(var, raising=False)
        monkeypatch.setenv(
            faults.ENV_RESOURCE, "feed.latency:2:3:tenant=acme",
        )
        monkeypatch.setenv(faults.ENV_RESOURCE_LATENCY, "1")
        faults._arm_from_env()
        spec = faults._resource_armed["feed.latency"]
        assert spec == [2, 3, 0.001, "acme"]


# -- bounded tenant-prefixed mirror demand keys (satellite 3) ---------------


class _Agg:
    write_version = 0


class TestMirrorTenantKeys:
    def _mirror(self, max_keys):
        from zipkin_tpu.tpu.mirror import ReadMirror

        agg = _Agg()
        return ReadMirror(lambda: agg, enabled=True, max_keys=max_keys)

    def test_tenant_keys_overflow_at_cap(self):
        m = self._mirror(max_keys=2)
        assert m.register("ttq:tenant=A:p99", lambda: 1)
        assert m.register("ttq:tenant=B:p99", lambda: 2)
        assert not m.register("ttq:tenant=C:p99", lambda: 3)
        c = m.counters()
        assert c["mirrorDemandKeys"] == 2
        assert c["mirrorDemandOverflow"] == 1
        # an existing key refreshes instead of overflowing
        assert m.register("ttq:tenant=A:p99", lambda: 1)

    def test_tenant_keys_expire_by_publish_ttl(self):
        m = self._mirror(max_keys=8)
        assert m.register("ttq:tenant=A:p99", lambda: 1)
        for _ in range(m.DEMAND_TTL_PUBLISHES + 2):
            assert m.publish(force=True)
        assert m.counters()["mirrorDemandKeys"] == 0
        # expiry freed the slot: a re-register succeeds, no overflow
        assert m.register("ttq:tenant=A:p99", lambda: 1)
        assert m.counters()["mirrorDemandOverflow"] == 0


# -- per-tenant SLO grammar -------------------------------------------------


class TestTenantSlo:
    def test_tenant_specs_bind_to_slugged_counters(self):
        from zipkin_tpu.obs.slo import tenant_specs

        (spec,) = tenant_specs("team-a")
        assert spec.name == "tenant_team_a_shed_ratio"
        assert spec.bad == "tenantShed_team_a"
        assert spec.total == "tenantOffered_team_a"
        assert spec.kind == "ratio"

    def test_add_spec_is_idempotent(self):
        from zipkin_tpu.obs.recorder import StageRecorder
        from zipkin_tpu.obs.slo import SloWatchdog, tenant_specs
        from zipkin_tpu.obs.windows import WindowedTelemetry

        w = WindowedTelemetry(StageRecorder(), dict)
        dog = SloWatchdog(w, subscribe=False)
        n = len(dog.specs)
        (spec,) = tenant_specs("acme")
        dog.add_spec(spec)
        dog.add_spec(spec)
        assert len(dog.specs) == n + 1


# -- {tenant=} prometheus families -----------------------------------------


class TestPromTenantFamilies:
    def test_families_are_labelled_and_format_valid(self):
        from zipkin_tpu.server.app import _prom_tenants

        clk = Clock()
        ctl = OverloadController(clock=clk)
        ctl.tenant_admission = TenantAdmission(
            bytes_per_s=100.0, burst_s=1.0, clock=clk,
        )
        ctl.admit(b"x" * 60, tenant="acme")
        ctl.admit(b"x" * 60, tenant="acme")  # shed
        lines = _prom_tenants(ctl.status())
        text = "\n".join(lines)
        assert 'zipkin_tpu_tenant_level{tenant="acme"} 2' in text
        assert 'zipkin_tpu_tenant_shed_total{tenant="acme"} 1' in text
        assert 'zipkin_tpu_tenant_offered_total{tenant="acme"} 2' in text
        assert "# TYPE zipkin_tpu_tenant_table_size gauge" in text
        # format sanity: every sample line follows HELP/TYPE for its
        # family and parses as name{labels} value
        seen_fams = set()
        for line in lines:
            if line.startswith("# HELP "):
                seen_fams.add(line.split()[2])
            elif not line.startswith("#"):
                fam = line.split("{")[0].split(" ")[0]
                assert fam in seen_fams
                float(line.rsplit(" ", 1)[1])

    def test_empty_status_renders_nothing(self):
        from zipkin_tpu.server.app import _prom_tenants

        assert _prom_tenants(None) == []
        assert _prom_tenants({"tenants": None}) == []


# -- tenant attribution through the MP fan-out tier -------------------------


@pytest.mark.skipif(not native.available(), reason="native codec unavailable")
class TestMpIngestTenantThreading:
    def test_submit_tenant_reaches_ack_accounting_and_sink(self):
        from tests.test_mp_ingest import make_store, payloads
        from zipkin_tpu.tpu.mp_ingest import MultiProcessIngester

        store = make_store(shards=2)
        ing = MultiProcessIngester(store, workers=2)
        sink_calls = []
        ing.tenant_sink = lambda tenant, n: sink_calls.append((tenant, n))
        try:
            ps = payloads(n_payloads=2, spans_each=256)
            ing.submit(ps[0], tenant="acme")
            ing.submit(ps[1])  # legacy: no tenant header
            ing.drain()
            table = ing.stats()["mpTenantTable"]
        finally:
            ing.close()
        assert table["acme"]["payloads"] == 1
        assert table["acme"]["spans"] == 256
        assert table["default"]["payloads"] == 1
        acked = {t: n for t, n in sink_calls}
        assert acked.get("acme") == 256
        assert acked.get("default") == 256
