"""The embedded mock server (ZipkinRule equivalent, SURVEY.md §2.6):
record POSTs, inject failures, assert stored traces."""

import urllib.error
import urllib.request

from tests.fixtures import TRACE
from zipkin_tpu.model import json_v2
from zipkin_tpu.testkit import HttpFailure, ZipkinMock


def _post(url: str, body: bytes) -> int:
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"}
    )
    with urllib.request.urlopen(req) as resp:
        return resp.status


class TestZipkinMock:
    def test_post_then_assert_traces(self):
        with ZipkinMock() as zipkin:
            status = _post(zipkin.http_url, json_v2.encode_span_list(TRACE))
            assert status == 202
            assert zipkin.http_request_count == 1
            assert zipkin.trace_count == 1
            assert len(zipkin.traces()[0]) == len(TRACE)
            assert zipkin.collector_metrics().get("spans", "http") == len(TRACE)

    def test_enqueued_failure_then_recovery(self):
        with ZipkinMock() as zipkin:
            zipkin.enqueue_failure(HttpFailure.send_error_response(503, "go away"))
            try:
                _post(zipkin.http_url, json_v2.encode_span_list(TRACE))
                raised = None
            except urllib.error.HTTPError as e:
                raised = e.code
            assert raised == 503
            assert zipkin.trace_count == 0  # failure consumed, nothing stored
            # next request succeeds (FIFO consumption)
            assert _post(zipkin.http_url, json_v2.encode_span_list(TRACE)) == 202
            assert zipkin.trace_count == 1
            assert zipkin.http_request_count == 2

    def test_store_spans_seeds_query_api(self):
        with ZipkinMock() as zipkin:
            zipkin.store_spans(TRACE)
            url = f"{zipkin.base_url}/api/v2/trace/{TRACE[0].trace_id}"
            with urllib.request.urlopen(url) as resp:
                assert resp.status == 200
                assert b"frontend" in resp.read()
