"""Time-disaggregated sketch tier (ISSUE 15).

The contract under test: a windowed ``[lookback, endTs]`` query answers
from merged time-bucket segments and is BIT-IDENTICAL to a from-scratch
oracle store that ingested only that range's spans — and stays
bit-identical across seal-crash resume (the timetier.seal.* crashpoints
ride the PR 7/8 snapshot+WAL machinery). Bit rot in a sealed segment
must cost coverage (quarantine), never a silently-wrong percentile.
Satellite coverage: bucket-aligned mirror-key canonicalization (1000
distinct endTs values collapse to a handful of ``ttq:`` registrations)
and the windowed shadow-accuracy gauges staying NO-ALERT on an honest
tier.
"""

from __future__ import annotations

import glob
import os
import random

import numpy as np
import pytest

from zipkin_tpu import faults
from zipkin_tpu.model.span import Endpoint, Kind, Span
from zipkin_tpu.obs.accuracy import AccuracyEstimator
from zipkin_tpu.obs.shadow import HostShadow
from zipkin_tpu.storage.tpu import TpuStorage
from zipkin_tpu.tpu.state import AggConfig

G = 5   # time_bucket_minutes
W = 4   # time_buckets (device ring slots)
BASE_MIN = 10_000_000          # minutes; divisible by G
BASE_EP = BASE_MIN // G
N_SVC = 6
N_OPS = 8

CFG = AggConfig(
    max_services=64, max_keys=256, hll_precision=8, digest_centroids=16,
    digest_buffer=4096, ring_capacity=4096, link_buckets=4,
    bucket_minutes=60, hist_slices=2,
    time_buckets=W, time_bucket_minutes=G,
)


@pytest.fixture(autouse=True)
def _always_disarm():
    yield
    faults.disarm()


def make(tmp_path, wal=False, archive=False):
    return TpuStorage(
        config=CFG, num_devices=2, batch_size=512,
        checkpoint_dir=str(tmp_path / "ckpt") if wal else None,
        wal_dir=str(tmp_path / "wal") if wal else None,
        archive_dir=str(tmp_path / "arch") if archive else None,
    )


_SVCS = [Endpoint.create(f"svc{i}", f"10.0.0.{i + 1}") for i in range(N_SVC)]


def warmup_spans():
    """One span per (service, op) pair, in a FIXED order, stamped two
    epochs before the test range. Both the live store and every oracle
    ingest this prefix first, so vocab/key-row id assignment is
    identical regardless of which span subset follows — a precondition
    for comparing raw [K, ...] sketch planes bit-for-bit. The warmup
    epoch falls out of the W-slot device ring before sealing starts and
    is never part of a queried window."""
    t_min = BASE_MIN - 2 * G
    out = []
    for i in range(N_SVC):
        for j in range(N_OPS):
            seq = i * N_OPS + j + 1
            out.append(Span.create(
                trace_id=f"{0xA0000 + seq:016x}",
                id=f"{seq:016x}",
                name=f"op{j}",
                kind=Kind.CLIENT,
                local_endpoint=_SVCS[i],
                remote_endpoint=_SVCS[(i + 1) % N_SVC],
                timestamp=t_min * 60_000_000,
                duration=1000,
            ))
    return out


def epoch_spans(ep_offsets, per=100, seed=0):
    """Client chains (parent->child across services, so link edges
    exist) with timestamps inside the given bucket epochs (offsets from
    BASE_EP). ~2% error tags exercise the errs plane."""
    rng = random.Random(seed)
    spans = []
    seq = 0
    for off in ep_offsets:
        for _ in range(per):
            seq += 1
            trace_id = f"{rng.getrandbits(63) | 1:016x}"
            t_min = BASE_MIN + off * G + rng.randrange(G)
            ts = t_min * 60_000_000 + rng.randrange(1000)
            parent_id = None
            caller = rng.randrange(N_SVC)
            for level in range(rng.randint(1, 3)):
                span_id = f"{(seq << 8 | level) + 1:016x}"
                err = {"error": "boom"} if rng.random() < 0.02 else {}
                spans.append(Span.create(
                    trace_id=trace_id, id=span_id, parent_id=parent_id,
                    name=f"op{rng.randrange(N_OPS)}",
                    kind=Kind.CLIENT,
                    local_endpoint=_SVCS[(caller + level) % N_SVC],
                    remote_endpoint=_SVCS[(caller + level + 1) % N_SVC],
                    timestamp=ts,
                    duration=int(rng.paretovariate(1.2) * 1000) + 50,
                    tags=err,
                ))
                parent_id = span_id
    return spans


def sealer_driver(off):
    """One span in epoch ``off`` — drives the sealer past the epochs
    under test (an epoch seals once ingest touches a NEWER one). Lives
    outside every compared window, so it never contributes to a
    windowed answer; reuses warmup's (svc0, op0) so vocab/key-row id
    assignment stays identical."""
    t_min = BASE_MIN + off * G
    return [Span.create(
        trace_id=f"{0xFEED:016x}", id=f"{0xFEED:016x}",
        name="op0", kind=Kind.CLIENT,
        local_endpoint=_SVCS[0], remote_endpoint=_SVCS[1],
        timestamp=t_min * 60_000_000, duration=777,
    )]


def window_bounds_ms(lo_off, hi_off):
    """(end_ts, lookback) in ms whose epoch_minutes//G round to exactly
    [BASE_EP + lo_off, BASE_EP + hi_off] — the canonicalization the
    store applies to every windowed route."""
    end_ts = (BASE_MIN + (hi_off + 1) * G) * 60_000 - 1
    lookback = (hi_off - lo_off + 1) * G * 60_000 - 60_000
    return end_ts, lookback


def assert_answers_equal(a, b):
    np.testing.assert_array_equal(a.digest, b.digest)
    np.testing.assert_array_equal(a.hll, b.hll)
    np.testing.assert_array_equal(a.calls, b.calls)
    np.testing.assert_array_equal(a.errs, b.errs)
    assert a.covered == b.covered


# -- seal protocol -------------------------------------------------------


def test_seal_protocol_and_counters(tmp_path):
    store = make(tmp_path)
    store.accept(warmup_spans() + epoch_spans([0, 1, 2, 3])).execute()
    assert store.agg.tt_max_epoch == BASE_EP + 3
    assert store.timetier.seal_due(store.agg) == 3
    assert store.tt_seal() == 3
    assert store.timetier.sealed_through == BASE_EP + 2
    assert store.timetier.seal_due(store.agg) == 0
    assert store.tt_seal() == 0  # idempotent: nothing newly due
    c = store.ingest_counters()
    assert c["ttSeals"] == 3
    assert c["ttSegmentsFine"] == 3
    # sealed-only window: no device read; unsealed suffix flags
    sealed = store.timetier.window(store.agg, BASE_EP, BASE_EP + 2)
    assert not sealed.unsealed and sealed.covered == 3
    mixed = store.timetier.window(store.agg, BASE_EP + 2, BASE_EP + 3)
    assert mixed.unsealed and mixed.covered == 2


def test_windowed_counts_are_exact(tmp_path):
    spans = epoch_spans([0, 1, 2], per=80, seed=11)
    store = make(tmp_path)
    store.accept(warmup_spans() + spans).execute()
    store.tt_seal()
    for lo_off, hi_off in [(0, 0), (0, 1), (1, 2), (0, 2)]:
        end_ts, lookback = window_bounds_ms(lo_off, hi_off)
        rows = store.latency_quantiles(
            [0.5, 0.99], end_ts=end_ts, lookback=lookback
        )
        want = sum(
            1 for s in spans
            if lo_off <= (s.timestamp // 60_000_000 - BASE_MIN) // G <= hi_off
        )
        assert sum(r["count"] for r in rows) == want


# -- bit-identity vs a from-scratch oracle (the tentpole acceptance) -----


def test_windowed_answers_match_from_scratch_oracle_fuzz(tmp_path):
    spans = epoch_spans([0, 1, 2, 3], per=90, seed=7)
    live = make(tmp_path / "live")
    live.accept(warmup_spans() + spans).execute()
    assert live.tt_seal() == 3

    rng = random.Random(99)
    ranges = [(0, 0), (1, 2), (0, 2)]
    ranges += [tuple(sorted(rng.sample(range(3), 2))) for _ in range(2)]
    for i, (lo_off, hi_off) in enumerate(ranges):
        sub = [
            s for s in spans
            if lo_off <= (s.timestamp // 60_000_000 - BASE_MIN) // G <= hi_off
        ]
        oracle = make(tmp_path / f"oracle{i}")
        # only the range's spans — same warmup prefix, same relative
        # span order, same (single, seal-time) digest flush position;
        # the driver span in epoch hi+1 lets the oracle seal epoch hi
        # (the live store's later epochs played that role for it)
        oracle.accept(
            warmup_spans() + sub + sealer_driver(hi_off + 1)
        ).execute()
        oracle.tt_seal()
        assert oracle.timetier.sealed_through >= BASE_EP + hi_off
        a = live.timetier.window(live.agg, BASE_EP + lo_off, BASE_EP + hi_off)
        b = oracle.timetier.window(
            oracle.agg, BASE_EP + lo_off, BASE_EP + hi_off
        )
        assert_answers_equal(a, b)
        # and through the public windowed routes
        end_ts, lookback = window_bounds_ms(lo_off, hi_off)
        assert live.latency_quantiles(
            [0.5, 0.95, 0.99], end_ts=end_ts, lookback=lookback
        ) == oracle.latency_quantiles(
            [0.5, 0.95, 0.99], end_ts=end_ts, lookback=lookback
        )
        assert live.trace_cardinalities(
            end_ts=end_ts, lookback=lookback
        ) == oracle.trace_cardinalities(end_ts=end_ts, lookback=lookback)
        got = live.get_dependencies(end_ts, lookback).execute()
        want = oracle.get_dependencies(end_ts, lookback).execute()
        assert sorted(map(str, got)) == sorted(map(str, want))
        oracle.close()
    live.close()


# -- seal crashpoints: durability parity (satellite 3) -------------------


@pytest.mark.parametrize("site,adopted", [
    ("timetier.seal.pre_commit", 0),   # tmp file only: reseal all
    ("timetier.seal.post_commit", 1),  # npz committed: boot adopts it
])
def test_seal_crash_resume_is_bit_identical(tmp_path, site, adopted):
    spans = warmup_spans() + epoch_spans([0, 1, 2, 3], per=70, seed=3)
    oracle = make(tmp_path / "o")
    oracle.accept(spans).execute()
    assert oracle.tt_seal() == 3

    victim = make(tmp_path, wal=True, archive=True)
    victim.accept(spans).execute()
    faults.arm(site, nth=1, action="raise")
    with pytest.raises(faults.CrashpointTriggered):
        victim.tt_seal()
    del victim  # crash: HBM state gone; WAL + committed segments remain

    revived = make(tmp_path, wal=True, archive=True)
    # pre_commit left only a tmp file (cleaned at boot, nothing
    # adopted); post_commit left a committed npz that boot MUST adopt
    assert revived.timetier.sealed_through == (
        BASE_EP + adopted - 1 if adopted else -1
    )
    assert revived.tt_seal() == 3 - adopted
    assert revived.timetier.sealed_through == BASE_EP + 2
    # no stray tmp files survive boot
    tdir = os.path.join(str(tmp_path), "arch", "timetier")
    assert not glob.glob(os.path.join(tdir, "*.tmp"))
    for lo_off, hi_off in [(0, 2), (1, 1), (0, 1)]:
        a = revived.timetier.window(
            revived.agg, BASE_EP + lo_off, BASE_EP + hi_off
        )
        b = oracle.timetier.window(
            oracle.agg, BASE_EP + lo_off, BASE_EP + hi_off
        )
        assert_answers_equal(a, b)
    end_ts, lookback = window_bounds_ms(0, 2)
    assert revived.latency_quantiles(
        [0.5, 0.99], end_ts=end_ts, lookback=lookback
    ) == oracle.latency_quantiles(
        [0.5, 0.99], end_ts=end_ts, lookback=lookback
    )
    oracle.close()
    revived.close()


# -- segment bit rot: quarantine, not garbage (satellite 3) --------------


@pytest.mark.parametrize("mode", ["flip", "zero", "truncate"])
def test_segment_bit_rot_is_quarantined(tmp_path, mode):
    store = make(tmp_path, archive=True)
    store.accept(warmup_spans() + epoch_spans([0, 1, 2, 3], per=60)).execute()
    faults.arm_corrupt("timetier.segment", mode=mode, nth=2)
    assert store.tt_seal() == 3  # middle epoch's npz damaged at rest
    store.close()

    fresh = make(tmp_path, archive=True)  # boot adopts the disk epochs
    tier = fresh.timetier
    assert tier.sealed_through == BASE_EP + 2
    ans = tier.window(fresh.agg, BASE_EP, BASE_EP + 2)
    # the rotted bucket costs coverage — never a silently-wrong answer
    assert ans.missing == 1 and ans.covered == 2
    assert tier.counters["ttSegmentsQuarantined"] == 1
    tdir = os.path.join(str(tmp_path), "arch", "timetier")
    assert glob.glob(os.path.join(tdir, "*.quarantine"))
    # quarantine is sticky: the epoch stays missing on re-read
    again = tier.window(fresh.agg, BASE_EP, BASE_EP + 2)
    assert again.missing == 1 and again.covered == 2
    fresh.close()


# -- mirror-key canonicalization (satellite 2) ---------------------------


def test_thousand_end_ts_values_collapse_to_few_mirror_keys(tmp_path):
    store = make(tmp_path)
    store.accept(warmup_spans() + epoch_spans([0, 1, 2], per=60)).execute()
    store.tt_seal()
    lookback = G * 60_000
    # 1000 DISTINCT endTs values sweeping ~two sealed buckets — a
    # polling client stepping endTs by the second
    start = (BASE_MIN + G) * 60_000
    for i in range(1000):
        end_ts = start + i * 577  # 577 ms steps, all distinct
        store.trace_cardinalities(end_ts=end_ts, lookback=lookback)
    ttq_keys = [k for k in store.mirror._demand if k.startswith("ttq:")]
    # bucket-aligned canonicalization: distinct endTs count is
    # irrelevant; only distinct (lo_ep, hi_ep) pairs register
    assert len(ttq_keys) <= 4
    assert len(ttq_keys) <= store.mirror.max_keys
    assert store.mirror.demand_overflow == 0
    store.close()


# -- windowed shadow accuracy (satellite 1) ------------------------------


def test_windowed_accuracy_gauges_no_alert_on_honest_tier(tmp_path):
    spans = warmup_spans() + epoch_spans([0, 1, 2], per=120, seed=5)
    store = make(tmp_path)
    store.accept(spans).execute()
    store.tt_seal()
    shadow = HostShadow(
        bucket_minutes=G,
        link_rate=0.0,
        seed=2,
        svc_resolver=store.vocab.services.get,
    )
    shadow.offer_spans(spans)
    shadow.drain()
    assert shadow.counters()["shadowWindowEpochs"] >= 3
    acc = AccuracyEstimator(store, shadow, rollup_s=0.0)
    g = acc.rollup()
    # the tier's newest sealed bucket vs that bucket's exact shadow
    # sub-stream: errors bounded, drift gauges quiet (the default
    # windowed SloSpecs watch the drift gauges)
    assert g["accuracyWindowedDigestP99RelErr"] < 0.25
    assert g["accuracyWindowedDigestP99Drift"] < 0.20
    assert g["accuracyWindowedHllRelErr"] < 0.15
    assert g["accuracyWindowedHllDrift"] == pytest.approx(0.0)
    detail = acc.status()["windowed"]
    assert detail["epoch"] <= BASE_EP + 2
    assert "digest" in detail and "distinct" in detail
    store.close()
