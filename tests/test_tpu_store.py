"""TpuStorage: full storage-contract compliance + sketch parity vs oracle.

The rebuild's key test pattern (SURVEY.md §4): one parity suite runs
against {oracle, TPU store} and asserts equal (or ε-bounded for sketches)
answers. Runs on the 8-virtual-device CPU mesh from conftest.py.
"""

import numpy as np
import pytest

from tests.fixtures import TRACE, lots_of_spans
from tests.storage_contract import StorageContract
from zipkin_tpu.storage.memory import InMemoryStorage
from zipkin_tpu.tpu.state import AggConfig
from zipkin_tpu.tpu.store import TpuStorage

SMALL = AggConfig(
    max_services=128, max_keys=512, hll_precision=10,
    digest_centroids=32, ring_capacity=1 << 14,
)


def small_store(**kwargs) -> TpuStorage:
    kwargs.setdefault("config", SMALL)
    kwargs.setdefault("pad_to_multiple", 256)
    return TpuStorage(**kwargs)


class TestTpuStorageContract(StorageContract):
    """The identical suite the oracle passes (ITStorage/ITSpanStore/...)."""

    def make_storage(self, **kwargs) -> TpuStorage:
        return small_store(**kwargs)


class TestTpuStorageContractLenient(StorageContract):
    """The WHOLE contract again with strict_trace_id=False as the default
    — lenient 64/128-bit id collapsing is a different code path through
    grouping and trace reads, and the reference runs its IT suite against
    both flags (StorageComponent.Builder.strictTraceId, SURVEY.md §2.3).
    Tests that pin the flag explicitly keep their pinned value."""

    def make_storage(self, **kwargs) -> TpuStorage:
        kwargs.setdefault("strict_trace_id", False)
        return small_store(**kwargs)


class TestTpuAggregateParity:
    @pytest.fixture(scope="class")
    def loaded(self):
        spans = lots_of_spans(6000, seed=42, services=6, span_names=8)
        oracle = InMemoryStorage(max_span_count=100_000)
        store = small_store(archive_max_span_count=100_000)
        # feed in several batches to exercise streaming merges
        for i in range(0, len(spans), 1000):
            chunk = spans[i : i + 1000]
            oracle.accept(chunk).execute()
            store.accept(chunk).execute()
        return spans, oracle, store

    def test_dependency_link_parity(self, loaded):
        spans, oracle, store = loaded
        end_ts = max(s.timestamp for s in spans) // 1000 + 60_000
        lookback = 7 * 86_400_000
        want = {
            (l.parent, l.child): (l.call_count, l.error_count)
            for l in oracle.get_dependencies(end_ts, lookback).execute()
        }
        got = {
            (l.parent, l.child): (l.call_count, l.error_count)
            for l in store.get_dependencies(end_ts, lookback).execute()
        }
        assert got == want

    def test_quantile_parity_within_epsilon(self, loaded):
        spans, _, store = loaded
        rows = store.latency_quantiles([0.5, 0.99], use_digest=False)
        assert rows, "expected sketch rows"
        # exact per-key durations from the raw spans
        by_key = {}
        for s in spans:
            if s.duration is None:
                continue
            by_key.setdefault((s.local_service_name, s.name), []).append(s.duration)
        checked = 0
        for row in rows:
            durs = np.asarray(by_key[(row["serviceName"], row["spanName"])], np.float64)
            assert row["count"] == len(durs)
            p50, p99 = row["quantiles"][0.5], row["quantiles"][0.99]
            np.testing.assert_allclose(p50, np.quantile(durs, 0.5), rtol=0.10)
            if len(durs) >= 100:
                # the sketch's guarantee: p99 lies between the bracketing
                # order statistics, within the bucket's relative width
                # (heavy-tail gaps between top order stats are estimator
                # variance, not sketch error).
                lo = np.quantile(durs, 0.99, method="lower") * 0.96
                hi = np.quantile(durs, 0.99, method="higher") * 1.04
                assert lo <= p99 <= hi, (p99, lo, hi)
                checked += 1
        assert checked > 5

    def test_digest_quantiles_tighter_tail(self, loaded):
        spans, _, store = loaded
        rows = store.latency_quantiles([0.5, 0.99], use_digest=True)
        by_key = {}
        for s in spans:
            if s.duration is None:
                continue
            by_key.setdefault((s.local_service_name, s.name), []).append(s.duration)
        for row in rows:
            durs = np.asarray(by_key[(row["serviceName"], row["spanName"])], np.float64)
            if len(durs) < 50:
                continue
            np.testing.assert_allclose(
                row["quantiles"][0.5], np.quantile(durs, 0.5), rtol=0.15
            )

    def test_cardinality_parity(self, loaded):
        spans, _, store = loaded
        est = store.trace_cardinalities()
        true_global = len({s.trace_id for s in spans})
        assert abs(est["_global"] - true_global) / true_global < 0.1
        by_svc = {}
        for s in spans:
            by_svc.setdefault(s.local_service_name, set()).add(s.trace_id)
        for svc, tids in by_svc.items():
            if len(tids) < 100:
                continue
            assert abs(est[svc] - len(tids)) / len(tids) < 0.15, svc

    def test_ingest_counters(self, loaded):
        spans, _, store = loaded
        counters = store.ingest_counters()
        assert counters["spans"] == len(spans)
        assert counters["spansWithDuration"] == sum(
            1 for s in spans if s.duration is not None
        )

    def test_aggregates_survive_archive_eviction(self):
        """The point of the sketch tier: aggregate reads outlive raw
        retention (SURVEY.md §5 long-context row)."""
        store = small_store(archive_max_span_count=50)
        spans = lots_of_spans(500, seed=9)
        store.accept(spans).execute()
        assert store._archive.span_count <= 50
        counters = store.ingest_counters()
        assert counters["spans"] == 500
        end_ts = max(s.timestamp for s in spans) // 1000 + 60_000
        links = store.get_dependencies(end_ts, 7 * 86_400_000).execute()
        assert links  # still answerable from device
