"""SpanNode tree assembly edge cases, mirroring SpanNodeTest upstream."""

from tests.fixtures import TRACE
from zipkin_tpu.internal.span_node import build_tree, merge_trace
from zipkin_tpu.model.span import Endpoint, Span


def ids(node):
    return node.span.id if node.span else None


class TestBuildTree:
    def test_empty_is_none(self):
        assert build_tree([]) is None

    def test_single_span(self):
        root = build_tree([Span.create("1", "a")])
        assert ids(root) == "000000000000000a" and not root.children

    def test_parent_child(self):
        spans = [Span.create("1", "a"), Span.create("1", "b", parent_id="a")]
        root = build_tree(spans)
        assert ids(root) == "000000000000000a"
        assert [ids(c) for c in root.children] == ["000000000000000b"]

    def test_shared_span_parents_under_client_half(self):
        client = Span.create("1", "b", parent_id="a", kind="CLIENT")
        server = Span.create("1", "b", parent_id="a", kind="SERVER", shared=True)
        root_span = Span.create("1", "a", kind="SERVER")
        root = build_tree([root_span, client, server])
        assert ids(root) == "000000000000000a"
        (child,) = root.children
        assert child.span is client
        (grandchild,) = child.children
        assert grandchild.span is server

    def test_child_of_shared_span_attaches_below_server_half(self):
        # downstream instrumentation references the shared id as parent,
        # and the client half of that id was never reported
        server = Span.create("1", "b", parent_id="a", kind="SERVER", shared=True)
        downstream = Span.create("1", "c", parent_id="b", kind="CLIENT")
        root_span = Span.create("1", "a", kind="SERVER")
        root = build_tree([root_span, server, downstream])
        # b has no client half; it dangles under synthetic or attaches via parent a
        found = {ids(n): [ids(c) for c in n.children] for n in root.traverse()}
        assert "000000000000000c" in found["000000000000000b"]

    def test_missing_parent_dangles_under_synthetic_root(self):
        spans = [
            Span.create("1", "a"),
            Span.create("1", "c", parent_id="fefe"),  # parent never reported
        ]
        root = build_tree(spans)
        assert root.is_synthetic_root
        assert sorted(filter(None, (ids(c) for c in root.children))) == [
            "000000000000000a",
            "000000000000000c",
        ]

    def test_multiple_roots_adopted(self):
        spans = [Span.create("1", "a"), Span.create("1", "b")]
        root = build_tree(spans)
        assert root.is_synthetic_root and len(root.children) == 2

    def test_traverse_is_breadth_first(self):
        spans = [
            Span.create("1", "a"),
            Span.create("1", "b", parent_id="a"),
            Span.create("1", "c", parent_id="a"),
            Span.create("1", "d", parent_id="b"),
        ]
        order = [ids(n) for n in build_tree(spans).traverse()]
        assert order.index("000000000000000d") == 3

    def test_duplicate_reports_merged(self):
        spans = [
            Span.create("1", "a", name="get"),
            Span.create("1", "a", duration=10),
        ]
        root = build_tree(spans)
        assert root.span.name == "get" and root.span.duration == 10
        assert not root.children


class TestMergeTrace:
    def test_dedups_and_sorts(self):
        dup = TRACE + [TRACE[1]]
        merged = merge_trace(dup)
        assert len(merged) == len(TRACE)
        timestamps = [s.timestamp for s in merged]
        assert timestamps == sorted(timestamps)

    def test_client_and_shared_server_stay_distinct(self):
        merged = merge_trace(TRACE)
        same_id = [s for s in merged if s.id == "0000000000000002"]
        assert len(same_id) == 2
        assert {bool(s.shared) for s in same_id} == {True, False}


class TestReviewRegressions:
    def test_same_id_different_services_without_shared_flag(self):
        # v2 instrumentation that forgot the shared flag: same id, two services
        spans = [
            Span.create("1", "a", kind="CLIENT",
                        local_endpoint=Endpoint.create("front")),
            Span.create("1", "a", kind="SERVER",
                        local_endpoint=Endpoint.create("back")),
        ]
        root = build_tree(spans)  # must not raise
        assert root is not None
        assert len(list(root.traverse())) == 2

    def test_lenient_mode_unifies_trace_id_renditions(self):
        long_form = Span.create(
            "463ac35c9f6413ad48485a3953bb6124", "a", name="get",
            local_endpoint=Endpoint.create("svc"),
        )
        short_form = Span.create(
            "48485a3953bb6124", "a", duration=10,
            local_endpoint=Endpoint.create("svc"),
        )
        merged = merge_trace([long_form, short_form])
        assert len(merged) == 1
        assert merged[0].trace_id == "463ac35c9f6413ad48485a3953bb6124"
        assert merged[0].name == "get" and merged[0].duration == 10
