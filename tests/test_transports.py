"""Transport collectors: queue/replay sources, offset resume, at-least-once
commit discipline (SURVEY.md §2.2, §3.3)."""

import threading
import time

from tests.fixtures import TRACE, lots_of_spans
from zipkin_tpu.collector.core import Collector, InMemoryCollectorMetrics
from zipkin_tpu.collector.transports import (
    QueueSource,
    ReplayFileSource,
    TransportCollector,
    append_replay,
)
from zipkin_tpu.model import json_v2
from zipkin_tpu.storage.memory import InMemoryStorage


def _collector(storage, metrics=None, transport="queue"):
    m = (metrics or InMemoryCollectorMetrics()).for_transport(transport)
    return Collector(storage, metrics=m)


class TestQueueSource:
    def test_roundtrip_via_worker_threads(self):
        storage = InMemoryStorage()
        source = QueueSource()
        metrics = InMemoryCollectorMetrics()
        tc = TransportCollector(
            source, _collector(storage, metrics), transport="queue", workers=2,
            poll_timeout=0.05,
        )
        tc.start()
        try:
            for _ in range(5):
                source.send(json_v2.encode_span_list(TRACE))
            deadline = time.monotonic() + 5
            while storage.span_count < 5 * len(TRACE) and time.monotonic() < deadline:
                time.sleep(0.02)
            # raw rows keep duplicates (reference multimap); reads dedup
            assert storage.span_count == 5 * len(TRACE)
            trace = storage.get_trace(TRACE[0].trace_id).execute()
            assert len(trace) == len(TRACE)
            assert metrics.get("messages", "queue") == 5
        finally:
            tc.close()

    def test_malformed_payload_counted_dropped(self):
        storage = InMemoryStorage()
        source = QueueSource()
        metrics = InMemoryCollectorMetrics()
        tc = TransportCollector(
            source, _collector(storage, metrics), transport="queue",
        )
        source.send(b"\xff\xffnot a span")
        tc.drain(2.0)
        assert metrics.get("messages_dropped", "queue") == 1
        assert storage.span_count == 0
        tc.close()


class TestReplayFile:
    def test_replay_and_offset_resume(self, tmp_path):
        path = str(tmp_path / "spans.replay")
        spans = lots_of_spans(300, seed=5)
        for lo in range(0, 300, 100):
            append_replay(path, [json_v2.encode_span_list(spans[lo : lo + 100])])

        storage = InMemoryStorage()
        src = ReplayFileSource(path)
        tc = TransportCollector(src, _collector(storage), transport="replay")
        tc.drain()
        assert storage.span_count == 300
        assert src.committed == 2
        tc.close()

        # resume: nothing re-delivered
        storage2 = InMemoryStorage()
        src2 = ReplayFileSource(path)
        tc2 = TransportCollector(src2, _collector(storage2), transport="replay")
        tc2.drain(1.0)
        assert storage2.span_count == 0
        tc2.close()

        # append more; only the new message is delivered
        append_replay(path, [json_v2.encode_span_list(TRACE)])
        storage3 = InMemoryStorage()
        src3 = ReplayFileSource(path)
        tc3 = TransportCollector(src3, _collector(storage3), transport="replay")
        tc3.drain()
        assert storage3.span_count == len(TRACE)
        tc3.close()

    def test_check_reports_closed(self, tmp_path):
        path = str(tmp_path / "x.replay")
        append_replay(path, [b"[]"])
        src = ReplayFileSource(path)
        assert src.check().ok
        src.close()
        assert not src.check().ok


class TestKafkaGated:
    def test_kafka_source_unavailable_raises_clearly(self):
        import pytest

        from zipkin_tpu.collector.transports import KafkaSource

        with pytest.raises(RuntimeError, match="kafka-python is not installed"):
            KafkaSource("broker:9092")


class TestCommitWatermark:
    """A fast worker must not commit past a slower worker's unstored
    offsets (cumulative-commit sources would mark them consumed)."""

    def test_watermark_holds_below_outstanding(self):
        storage = InMemoryStorage()
        source = QueueSource()
        tc = TransportCollector(source, _collector(storage), transport="queue")
        # worker A polled 0-4 but hasn't stored them; worker B polled 5-9
        tc._outstanding.update(range(10))
        for off in range(5, 10):
            tc._mark_stored(off)
        assert source.committed == -1  # 0-4 still outstanding
        for off in range(5):
            tc._mark_stored(off)
        assert source.committed == 9  # everything stored -> full commit

    def test_poison_pill_advances_watermark(self):
        storage = InMemoryStorage()
        source = QueueSource()
        tc = TransportCollector(source, _collector(storage), transport="queue")
        source.send(b"\xff\xff garbage")
        source.send(json_v2.encode_span_list(TRACE))
        tc.drain(2.0)
        assert source.committed == 1  # pill consumed, not stuck
        assert storage.span_count == len(TRACE)
