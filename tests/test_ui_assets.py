"""Static checks over the UI bundle (zipkin_tpu/server/static/).

There is no JS engine on this box (no node/deno, no browser), so the
app cannot be executed in CI. These tests catch the authoring errors a
parse would: unbalanced brackets outside strings/comments, unterminated
strings/templates, references to API routes the server doesn't serve,
and regressions in the escaping discipline the security comments in
app.js promise.
"""

import re

from zipkin_tpu.server import ui


def _read(name: str) -> str:
    body, _ = ui.asset(name)
    return body.decode("utf-8")


def _strip_js(src: str) -> str:
    """Remove string literals, template literals, comments and regex
    literals, leaving structural characters. A tiny lexer, not a parser:
    enough to make bracket-balance checking meaningful."""
    out = []
    i, n = 0, len(src)
    mode = None  # None | "'" | '"' | '`' | '//' | '/*' | 're'
    prev_significant = ""
    while i < n:
        c = src[i]
        nxt = src[i + 1] if i + 1 < n else ""
        if mode is None:
            if c in "'\"`":
                mode = c
                if c == "`":
                    out.append("`")
            elif c == "/" and nxt == "/":
                mode = "//"
                i += 1
            elif c == "/" and nxt == "*":
                mode = "/*"
                i += 1
            elif c == "/" and prev_significant in "=(,:;![&|?+{}>":
                # '>' covers arrow bodies: `s => /^[0-9a-f]+$/.test(s)`
                # (an operand before '/' ends in an identifier/digit, so
                # comparison followed by division still lexes as division)
                mode = "re"
            else:
                out.append(c)
                if not c.isspace():
                    prev_significant = c
        elif mode in ("'", '"'):
            if c == "\\":
                i += 1
            elif c == mode:
                mode = None
                prev_significant = "x"  # a value ended
        elif mode == "`":
            if c == "\\":
                i += 1
            elif c == "$" and nxt == "{":
                # template interpolation: recurse structurally by
                # emitting the braces so balance still checks
                out.append("${")
                i += 1
                depth = 1
                while i + 1 < n and depth:
                    i += 1
                    ch = src[i]
                    if ch in "'\"":  # nested plain string inside ${}
                        q = ch
                        while i + 1 < n:
                            i += 1
                            if src[i] == "\\":
                                i += 1
                            elif src[i] == q:
                                break
                        continue
                    if ch == "{":
                        depth += 1
                    elif ch == "}":
                        depth -= 1
                    if depth:
                        out.append(ch)
                out.append("}")
            elif c == "`":
                out.append("`")
                mode = None
                prev_significant = "x"
        elif mode == "//":
            if c == "\n":
                out.append("\n")
                mode = None
        elif mode == "/*":
            if c == "*" and nxt == "/":
                mode = None
                i += 1
        elif mode == "re":
            if c == "\\":
                i += 1
            elif c == "[":
                # regex char class: '/' inside is literal
                while i + 1 < n and src[i] != "]":
                    i += 1
                    if src[i] == "\\":
                        i += 1
            elif c == "/":
                mode = None
                prev_significant = "x"
            elif c == "\n":  # not a regex after all (division); bail
                mode = None
        i += 1
    assert mode in (None, "//"), f"unterminated {mode} literal at EOF"
    return "".join(out)


class TestBundleParses:
    def test_app_js_brackets_balance(self):
        js = _read("app.js")
        stripped = _strip_js(js)
        assert stripped.count("`") % 2 == 0, "unbalanced template literal"
        stack = []
        pairs = {")": "(", "]": "[", "}": "{"}
        line = 1
        for ch in stripped:
            if ch == "\n":
                line += 1
            elif ch in "([{":
                stack.append((ch, line))
            elif ch in ")]}":
                assert stack, f"unmatched {ch!r} at line ~{line}"
                top, at = stack.pop()
                assert top == pairs[ch], (
                    f"bracket mismatch: {top!r} (line {at}) closed by "
                    f"{ch!r} (line ~{line})"
                )
        assert not stack, f"unclosed {stack[-1]!r}"

    def test_css_braces_balance(self):
        css = re.sub(r"/\*.*?\*/", "", _read("style.css"), flags=re.S)
        assert css.count("{") == css.count("}")
        assert css.count("{") > 20  # a real stylesheet, not a stub

    def test_index_references_resolve(self):
        html = _read("index.html")
        for ref in re.findall(r"/zipkin/static/(\w+\.\w+)", html):
            assert ui.asset(ref) is not None, ref


class TestApiSurfaceMatchesServer:
    def test_every_fetched_path_is_a_registered_route(self):
        from zipkin_tpu.server.app import ZipkinServer
        from zipkin_tpu.server.config import ServerConfig

        js = _read("app.js")
        wanted = set(re.findall(r"['\"(](/(?:api/v2|info|metrics|prometheus)[\w/]*)", js))
        assert "/api/v2/traces" in wanted and "/api/v2/dependencies" in wanted
        # TPU routes are registered when storage_type=tpu; use the
        # route table of a tpu-configured app without starting storage
        app = ZipkinServer(
            ServerConfig(storage_type="mem"), storage=_FakeTpuStorage()
        ).make_app()
        routes = {r.resource.canonical for r in app.router.routes()}
        for path in sorted(wanted):
            hit = any(
                path == route or route.startswith(path + "/{")
                or path.startswith(route.split("{")[0].rstrip("/"))
                and "{" in route
                for route in routes
            ) or path in routes
            assert hit, f"app.js fetches {path} but no route serves it"


class _FakeTpuStorage:
    """Duck-typed enough for make_app's route registration: the TPU
    extension routes register when the storage exposes the sketch
    reads."""

    def latency_quantiles(self, *a, **k):
        return []

    def trace_cardinalities(self):
        return {}

    def ingest_counters(self):
        return {}

    def span_consumer(self):
        class _Consumer:
            def accept(self, spans):  # pragma: no cover - not exercised
                raise NotImplementedError

        return _Consumer()

    def check(self):
        from zipkin_tpu.utils.component import CheckResult

        return CheckResult.ok()

    def close(self):
        pass


class TestEscapingDiscipline:
    # Template interpolations that do NOT start with one of the escaping
    # helpers, each hand-reviewed. Categories, for the next reviewer:
    #   number   — arithmetic over our own locals / .length / toFixed
    #   prebuilt — HTML strings assembled above the use site from
    #              already-escaped pieces (caret, grid, segs, chips,
    #              table(), vs)
    #   hex      — ids that passed hexOnly() at construction (r.id)
    #   static   — ternaries whose branches are literal strings
    #   textonly — lands in .textContent / SVG <title>, never innerHTML
    #              (l.parent, l.child, l.callCount in the dep-graph tip)
    # A new interpolation fails this test until it is reviewed and added.
    REVIEWED = {
        "6 + pad", "H", "W", "Math.max(sw, 0.4)", "Math.max(w, 0.4)",
        "Math.round(n).toLocaleString()", "Number(ctr[k]).toLocaleString()",
        "all.length - names.length", "c[0]", "c[1]", "caret",
        "chips.join('')", "depth + 1", "err ? 'err' : ''",
        "errs ? ` · <span class=\"err\">${errs} error spans</span>` : ''",
        "errs(inbound)", "errs(outbound)", "f * 100",
        "folded ? '▸' : '▾'",
        "folded ? `<span class=\"hiddenkids\">+${nkids} hidden</span>` : ''",
        "grid", "i", "idx", "inbound.length",
        "k === 'error' ? 'err' : ''", "l.callCount", "l.child",
        "l.errorCount ? 'err' : ''", "l.errorCount ? 'err' : 'muted'",
        "l.errorCount || 0", "l.parent", "mx", "my", "n",
        "name === '_global' ? '<b>' + esc(label) + '</b>' : esc(label)",
        "off", "outbound.length", "p", "p[0]", "p[1]",
        "r.err ? '<span class=\"badge-err\">error</span>' : ''", "r.id",
        "r.share.length > 4 ? '<span class=\"muted\"> +' + (r.share.length - 4) + '</span>' : ''",
        "r.spans.length", "r.toFixed(1)", "rate > 1 ? 'err' : 'muted'",
        "rate.toFixed(rate && rate < 10 ? 1 : 0)", "rows.length - 500",
        "s.shared ? ' shared' : ''", "segs.join('')", "spans.length",
        "sum(inbound)", "sum(outbound)", "svcHue(name)", "svcs.length",
        "table(inbound, 'parent')", "table(outbound, 'child')", "vs", "w",
    }

    @staticmethod
    def _interpolations(js: str):
        """Every ${...} expression, extracted with brace counting — a
        regex like ``\\$\\{[^{}]+\\}`` silently SKIPS interpolations
        containing nested braces (object literals, arrow bodies), which
        are exactly the complex expressions most needing review."""
        out = []
        i = 0
        while True:
            i = js.find("${", i)
            if i < 0:
                return out
            depth, j = 1, i + 2
            while j < len(js) and depth:
                if js[j] == "{":
                    depth += 1
                elif js[j] == "}":
                    depth -= 1
                j += 1
            assert depth == 0, f"unterminated ${{ at offset {i}"
            out.append(js[i + 2:j - 1].strip())
            i = j

    def test_every_interpolation_is_escaped_or_reviewed(self):
        """Every ${...} in app.js either starts with one of the escaping
        helpers (esc/hexOnly/svcColor/fmtDur/encodeURIComponent) or is
        in the hand-reviewed REVIEWED set above. Anything new fails
        until reviewed — the cheap, honest version of a DOM-XSS lint on
        a box with no JS tooling."""
        js = _read("app.js")
        safe = re.compile(
            r"^(esc|hexOnly|svcColor|svcColorSoft|fmtDur|encodeURIComponent)\("
        )
        suspicious = []
        for expr in self._interpolations(js):
            if safe.match(expr) or expr in self.REVIEWED:
                continue
            suspicious.append(expr)
        assert not suspicious, (
            "unreviewed template interpolations (review for XSS, then "
            f"add to REVIEWED): {suspicious}"
        )

    def test_reviewed_set_has_no_dead_entries(self):
        exprs = set(self._interpolations(_read("app.js")))
        dead = self.REVIEWED - exprs
        assert not dead, f"REVIEWED entries no longer in app.js: {dead}"

    def test_svg_labels_use_textcontent(self):
        js = _read("app.js")
        assert "label.textContent = n" in js
        assert "tip.textContent" in js
