"""Executable spec of the waterfall/dependency-graph invariants in
``zipkin_tpu/server/static/app.js`` (ISSUE 5 satellite).

There is no JS engine on this box (test_ui_assets.py documents the
descope), so the UI's two load-bearing algorithms are mirrored here in
Python and asserted over the same Lens-conformance fixtures the server
tests use:

- ``treeOrder``: Lens SpanNode-style waterfall DFS — shared SERVER
  spans nest under their same-id client half, parentId resolution
  prefers the shared rendition, children sort by timestamp (missing
  timestamps last), orphans surface as roots, cycles cannot hang it;
- ``subtreeEnd``: the contiguous depth-run a collapse fold covers;
- ``depGraph``: volume-ranked top-48 node cut, circle layout radius
  and angles, log-scaled edge widths, error coloring, and the
  direction tick sitting at t=0.7 of the quadratic edge curve.

A final test pins the mirrored constants against the shipped app.js
source text, so editing the JS without updating this spec (or vice
versa) fails loudly instead of silently diverging.
"""

from __future__ import annotations

import json
import math

import pytest

from tests.fixtures import TRACE
from zipkin_tpu.model import json_v2
from zipkin_tpu.server import ui


def _approx(x):
    return pytest.approx(x, rel=1e-12, abs=1e-9)


# ---------------------------------------------------------------- mirrors
# Line-for-line Python renditions of app.js treeOrder/subtreeEnd/depGraph.
# Spans are the JSON-v2 dicts the UI receives; identity (id()) stands in
# for JS object identity in the kids map and visited set.


def tree_order(spans):
    by_id = {}
    for s in spans:
        by_id.setdefault(s["id"], []).append(s)

    def parent_of(s):
        if s.get("shared"):  # server half: parent is the client half
            mates = [
                m
                for m in by_id.get(s["id"], ())
                if m is not s and not m.get("shared")
            ]
            if mates:
                return mates[0]
        pid = s.get("parentId")
        if pid and pid in by_id:
            # prefer the SHARED rendition (SpanNode's index preference)
            c = by_id[pid]
            return next((m for m in c if m.get("shared")), c[0])
        return None

    kids, roots = {}, []
    for s in spans:
        p = parent_of(s)
        if p is not None:
            kids.setdefault(id(p), []).append(s)
        else:
            roots.append(s)

    def ts(s):
        return s.get("timestamp") or 1e18

    roots.sort(key=ts)
    out, seen = [], set()

    def walk(s, d):
        if id(s) in seen:
            return
        seen.add(id(s))
        out.append((s, d))
        for k in sorted(kids.get(id(s), ()), key=ts):
            walk(k, d + 1)

    for r in roots:
        walk(r, 0)
    for s in spans:  # cycle leftovers
        if id(s) not in seen:
            out.append((s, 0))
    return out


def subtree_end(tree, i):
    d = tree[i][1]
    j = i + 1
    while j < len(tree) and tree[j][1] > d:
        j += 1
    return j


def dep_graph_layout(links):
    vol = {}
    for l in links:
        vol[l["parent"]] = vol.get(l["parent"], 0) + (l.get("callCount") or 0)
        vol[l["child"]] = vol.get(l["child"], 0) + (l.get("callCount") or 0)
    all_names = sorted(vol.keys(), key=lambda n: -vol[n])
    names = all_names[:48]
    if not names:
        return {"names": [], "dropped": 0, "radius": 0, "pos": {}, "edges": []}
    cx, cy = 400, 250
    radius = min(200, 60 + len(names) * 8)
    pos = {}
    for i, n in enumerate(names):
        a = 2 * math.pi * i / len(names) - math.pi / 2
        pos[n] = (cx + radius * math.cos(a), cy + radius * math.sin(a))
    max_c = 1
    for l in links:
        max_c = max(max_c, l.get("callCount") or 1)
    edges = []
    for l in links:
        p, c = pos.get(l["parent"]), pos.get(l["child"])
        if p is None or c is None:
            continue  # endpoint fell below the volume cut: edge dropped
        w = 0.8 + 3 * math.log(1 + (l.get("callCount") or 1)) / math.log(
            1 + max_c
        )
        mx = (p[0] + c[0]) / 2 + (cy - (p[1] + c[1]) / 2) * 0.25
        my = (p[1] + c[1]) / 2 + ((p[0] + c[0]) / 2 - cx) * 0.25
        edges.append(
            {
                "parent": l["parent"],
                "child": l["child"],
                "width": w,
                "stroke": "#b71c1c" if l.get("errorCount") else "#7986cb",
                "tick_fill": "#b71c1c" if l.get("errorCount") else "#3f51b5",
                "p": p,
                "c": c,
                "ctrl": (mx, my),
                "tick": (
                    0.09 * p[0] + 0.42 * mx + 0.49 * c[0],
                    0.09 * p[1] + 0.42 * my + 0.49 * c[1],
                ),
            }
        )
    return {
        "names": names,
        "dropped": len(all_names) - len(names),
        "radius": radius,
        "pos": pos,
        "edges": edges,
    }


def _trace_dicts():
    return json.loads(json_v2.encode_span_list(TRACE))


def _span(id, parent=None, ts=None, shared=False, name="s"):
    d = {"traceId": "1" * 16, "id": id, "name": name}
    if parent is not None:
        d["parentId"] = parent
    if ts is not None:
        d["timestamp"] = ts
    if shared:
        d["shared"] = True
    return d


# ----------------------------------------------------------- waterfall DFS


class TestTreeOrder:
    def test_canonical_trace_nests_shared_server_under_client(self):
        """The fixture TRACE is the exact shape the shared-span rules
        exist for: root -> client half -> shared server half -> the
        server's downstream call, one depth step each."""
        tree = tree_order(_trace_dicts())
        got = [(s["id"], s.get("shared", False), d) for s, d in tree]
        assert got == [
            ("0000000000000001", False, 0),
            ("0000000000000002", False, 1),  # client half
            ("0000000000000002", True, 2),  # server half nests under it
            ("0000000000000003", False, 3),  # prefers the shared rendition
        ]

    def test_child_prefers_shared_rendition_of_its_parent(self):
        # client and shared-server renditions of span "b"; child "c"
        # names b as parent -> must nest under the SERVER half
        a = _span("a", ts=1)
        b_client = _span("b", parent="a", ts=2)
        b_server = _span("b", parent="a", ts=3, shared=True)
        c = _span("c", parent="b", ts=4)
        tree = tree_order([c, b_server, a, b_client])  # order-insensitive
        depth = {id(s): d for s, d in tree}
        assert depth[id(c)] == depth[id(b_server)] + 1
        order = [id(s) for s, _ in tree]
        assert order.index(id(c)) == order.index(id(b_server)) + 1

    def test_orphans_surface_as_roots_sorted_by_timestamp(self):
        late = _span("x", parent="missing", ts=900)
        early = _span("y", parent="also-missing", ts=100)
        untimed = _span("z", parent="gone")  # ts -> 1e18, sorts last
        tree = tree_order([late, untimed, early])
        assert [(s["id"], d) for s, d in tree] == [
            ("y", 0),
            ("x", 0),
            ("z", 0),
        ]

    def test_children_sort_by_timestamp_missing_last(self):
        root = _span("r", ts=1)
        kids = [
            _span("k3", parent="r", ts=30),
            _span("k_untimed", parent="r"),
            _span("k1", parent="r", ts=10),
            _span("k2", parent="r", ts=20),
        ]
        tree = tree_order([root] + kids)
        assert [s["id"] for s, _ in tree] == [
            "r",
            "k1",
            "k2",
            "k3",
            "k_untimed",
        ]
        assert [d for _, d in tree] == [0, 1, 1, 1, 1]

    def test_parent_cycle_cannot_hang_and_loses_no_span(self):
        a = _span("a", parent="b", ts=1)
        b = _span("b", parent="a", ts=2)
        solo = _span("s", ts=3)
        tree = tree_order([a, b, solo])
        assert len(tree) == 3  # every span rendered exactly once
        assert sorted(s["id"] for s, _ in tree) == ["a", "b", "s"]
        # the cycle's leftover (whichever member the DFS never reached)
        # appends at depth 0, after the real roots
        assert {d for s, d in tree if s["id"] in ("a", "b")} <= {0, 1}
        assert {d for s, d in tree if s["id"] == "s"} == {0}

    def test_subtree_end_covers_contiguous_deeper_run(self):
        root = _span("r", ts=1)
        a = _span("a", parent="r", ts=2)
        a1 = _span("a1", parent="a", ts=3)
        a2 = _span("a2", parent="a", ts=4)
        b = _span("b", parent="r", ts=5)
        tree = tree_order([root, a, a1, a2, b])
        assert [s["id"] for s, _ in tree] == ["r", "a", "a1", "a2", "b"]
        assert subtree_end(tree, 0) == 5  # whole trace
        assert subtree_end(tree, 1) == 4  # a + its two kids
        assert subtree_end(tree, 2) == 3  # leaf covers only itself
        assert subtree_end(tree, 4) == 5


# -------------------------------------------------------- dep-graph layout


def _links(n_services=4, calls=lambda i: 10 * (i + 1), errors=lambda i: 0):
    out = []
    for i in range(n_services - 1):
        out.append(
            {
                "parent": f"svc{i}",
                "child": f"svc{i + 1}",
                "callCount": calls(i),
                "errorCount": errors(i),
            }
        )
    return out


class TestDepGraphLayout:
    def test_volume_ranked_top48_cut_reports_dropped(self):
        # 60 services in a chain: volume(svc_i) = calls in + calls out
        links = _links(60, calls=lambda i: 1000 - i)
        g = dep_graph_layout(links)
        assert len(g["names"]) == 48
        assert g["dropped"] == 12
        vol = {}
        for l in links:
            vol[l["parent"]] = vol.get(l["parent"], 0) + l["callCount"]
            vol[l["child"]] = vol.get(l["child"], 0) + l["callCount"]
        kept = set(g["names"])
        assert all(
            vol[k] >= vol[d] for k in kept for d in set(vol) - kept
        )
        # edges touching a dropped endpoint are skipped, not misdrawn
        assert all(
            e["parent"] in kept and e["child"] in kept for e in g["edges"]
        )

    def test_circle_layout_radius_and_angles(self):
        g = dep_graph_layout(_links(6))
        n = len(g["names"])
        assert g["radius"] == min(200, 60 + n * 8)
        for i, name in enumerate(g["names"]):
            x, y = g["pos"][name]
            assert math.hypot(x - 400, y - 250) == _approx(
                g["radius"]
            )
            a = 2 * math.pi * i / n - math.pi / 2
            assert x == _approx(400 + g["radius"] * math.cos(a))
            assert y == _approx(250 + g["radius"] * math.sin(a))
        # node 0 (highest volume) sits at 12 o'clock
        x0, y0 = g["pos"][g["names"][0]]
        assert x0 == _approx(400)
        assert y0 == _approx(250 - g["radius"])

    def test_radius_saturates_at_200(self):
        assert dep_graph_layout(_links(50))["radius"] == 200

    def test_edge_width_is_log_scaled_and_bounded(self):
        links = _links(5, calls=lambda i: [1, 10, 100, 1000][i])
        g = dep_graph_layout(links)
        widths = {
            (e["parent"], e["child"]): e["width"] for e in g["edges"]
        }
        ordered = [widths[(l["parent"], l["child"])] for l in links]
        assert ordered == sorted(ordered)  # monotone in callCount
        assert ordered[-1] == _approx(3.8)  # maxC edge
        assert all(0.8 < w <= 3.8 + 1e-9 for w in ordered)

    def test_error_edges_paint_red(self):
        links = _links(3, errors=lambda i: i)  # first clean, second errors
        g = dep_graph_layout(links)
        by_pair = {(e["parent"], e["child"]): e for e in g["edges"]}
        clean = by_pair[("svc0", "svc1")]
        bad = by_pair[("svc1", "svc2")]
        assert (clean["stroke"], clean["tick_fill"]) == (
            "#7986cb",
            "#3f51b5",
        )
        assert (bad["stroke"], bad["tick_fill"]) == ("#b71c1c", "#b71c1c")

    def test_direction_tick_sits_at_t07_of_the_curve(self):
        g = dep_graph_layout(_links(7))
        t = 0.7
        for e in g["edges"]:
            for axis in (0, 1):
                bez = (
                    (1 - t) ** 2 * e["p"][axis]
                    + 2 * (1 - t) * t * e["ctrl"][axis]
                    + t * t * e["c"][axis]
                )
                assert e["tick"][axis] == _approx(bez)

    def test_empty_links_collapse_to_nothing(self):
        g = dep_graph_layout([])
        assert g["names"] == [] and g["edges"] == []


# ------------------------------------------------- pin against the source
# The mirrors above are only a spec while they match the shipped JS; pin
# the literal expressions they transcribe so either side failing to move
# in lockstep breaks the build.

PINNED_SNIPPETS = [
    # treeOrder
    "return c.find(m => m.shared) || c[0];",
    "const ts = s => s.timestamp || 1e18;",
    "if (!seen.has(s)) out.push([s, 0]); // cycle leftovers",
    # subtreeEnd
    "while (j < curTree.length && curTree[j][1] > d) j++;",
    # depGraph
    "const names = all.slice(0, 48);",
    "const cx = 400, cy = 250, R = Math.min(200, 60 + names.length * 8);",
    "const a = 2 * Math.PI * i / names.length - Math.PI / 2;",
    "const w = 0.8 + 3 * Math.log(1 + (l.callCount || 1)) / Math.log(1 + maxC);",
    "stroke: l.errorCount ? '#b71c1c' : '#7986cb',",
    "const tx = 0.09 * p[0] + 0.42 * mx + 0.49 * c[0],",
    "fill: l.errorCount ? '#b71c1c' : '#3f51b5',",
]


def test_mirrors_pinned_to_shipped_app_js():
    body, _ = ui.asset("app.js")
    src = body.decode("utf-8")
    for snippet in PINNED_SNIPPETS:
        assert snippet in src, f"app.js drifted from spec mirror: {snippet!r}"


