"""Host WAL: crash recovery between snapshots (VERDICT r2 order 6).

The gate ordered: "a kill-mid-ingest test where restore + WAL replay
reaches exact host-counter and link parity with an uninterrupted oracle
run." The crash is simulated by abandoning the store object (device
state in HBM is lost by definition — a fresh store starts empty) and
booting a new one from checkpoint_dir + wal_dir.
"""

from __future__ import annotations

import glob
import os

import numpy as np

from tests.fixtures import lots_of_spans
from zipkin_tpu.storage.tpu import TpuStorage
from zipkin_tpu.tpu.state import AggConfig

CFG = AggConfig(
    max_services=64, max_keys=256, hll_precision=8, digest_centroids=16,
    digest_buffer=4096, ring_capacity=4096, link_buckets=4,
    bucket_minutes=60, hist_slices=2,
)


def make(tmp_path, wal=True, checkpoint=True):
    return TpuStorage(
        config=CFG, num_devices=2, batch_size=512,
        checkpoint_dir=str(tmp_path / "ckpt") if checkpoint else None,
        wal_dir=str(tmp_path / "wal") if wal else None,
    )


def batches(n_batches, per=400):
    return [
        lots_of_spans(per, seed=50 + b, services=8, span_names=12)
        for b in range(n_batches)
    ]


def assert_query_parity(a: TpuStorage, b: TpuStorage):
    """Query-level parity: counters, sketches, links. (Raw state can
    differ benignly: restore schedules a conservative early rollup,
    which moves links from ring lanes into rollup buckets — a
    semantics-preserving transformation the retention tests cover.)"""
    assert a.agg.host_counters == b.agg.host_counters
    ha, la, _ = a.agg.merged_sketches()
    hb, lb, _ = b.agg.merged_sketches()
    np.testing.assert_array_equal(ha, hb)
    np.testing.assert_array_equal(la, lb)
    ca, ea = a.agg.dependency_matrices(0, 1 << 31)
    cb, eb = b.agg.dependency_matrices(0, 1 << 31)
    np.testing.assert_array_equal(ca, cb)
    np.testing.assert_array_equal(ea, eb)
    assert a.trace_cardinalities() == b.trace_cardinalities()


def test_kill_mid_ingest_replays_to_parity(tmp_path):
    bs = batches(6)
    # uninterrupted oracle run (no WAL, no checkpoint)
    oracle = make(tmp_path / "oracle", wal=False, checkpoint=False)
    for spans in bs:
        oracle.accept(spans).execute()

    # crashing run: snapshot after batch 3, crash after batch 6
    victim = make(tmp_path)
    for spans in bs[:3]:
        victim.accept(spans).execute()
    victim.snapshot()
    for spans in bs[3:]:
        victim.accept(spans).execute()
    assert victim.agg.wal_seq > 0
    del victim  # crash: HBM state gone

    revived = make(tmp_path)  # restore + WAL replay in boot
    assert_query_parity(oracle, revived)
    # the vocab must have been reconstructed in the same id order
    assert revived.vocab.services._names == oracle.vocab.services._names
    assert revived.vocab._key_list == oracle.vocab._key_list


def test_crash_without_snapshot_replays_everything(tmp_path):
    bs = batches(4)
    oracle = make(tmp_path / "oracle", wal=False, checkpoint=False)
    for spans in bs:
        oracle.accept(spans).execute()
    victim = make(tmp_path)
    for spans in bs:
        victim.accept(spans).execute()
    del victim
    revived = make(tmp_path)
    assert_query_parity(oracle, revived)


def test_torn_tail_record_stops_cleanly(tmp_path):
    bs = batches(4)
    victim = make(tmp_path)
    for spans in bs:
        victim.accept(spans).execute()
    spans_before_last = victim.agg.host_counters["spans"] - len(bs[-1])
    del victim

    # tear the tail: chop bytes off the newest segment (mid-write crash)
    seg = sorted(glob.glob(str(tmp_path / "wal" / "wal-*.log")))[-1]
    size = os.path.getsize(seg)
    with open(seg, "ab") as f:
        f.truncate(size - 1000)

    revived = make(tmp_path)
    # the last (torn) batch is lost; everything before it replayed
    assert revived.agg.host_counters["spans"] == spans_before_last

    oracle = make(tmp_path / "oracle", wal=False, checkpoint=False)
    for spans in bs[:-1]:
        oracle.accept(spans).execute()
    assert_query_parity(oracle, revived)


def test_torn_segment_does_not_block_later_segments(tmp_path):
    """code-review r3: a torn tail in segment 0 must not stop replay of
    segments appended by a post-crash process — those batches were acked
    AFTER the first recovery and their vocab deltas build on exactly the
    at-tear replay state."""
    bs = batches(5)
    victim = make(tmp_path)
    for spans in bs[:3]:
        victim.accept(spans).execute()
    del victim
    # crash 1: tear the tail record of segment 0 (batch 3 lost)
    seg = sorted(glob.glob(str(tmp_path / "wal" / "wal-*.log")))[-1]
    with open(seg, "ab") as f:
        f.truncate(os.path.getsize(seg) - 500)

    survivor = make(tmp_path)  # recovery 1: replays batches 1-2
    for spans in bs[3:]:       # new acked traffic -> NEW segment
        survivor.accept(spans).execute()
    del survivor  # crash 2

    revived = make(tmp_path)  # recovery 2 must see batches 1-2 AND 4-5
    oracle = make(tmp_path / "oracle", wal=False, checkpoint=False)
    for spans in bs[:2] + bs[3:]:
        oracle.accept(spans).execute()
    assert_query_parity(oracle, revived)


def test_snapshot_truncates_covered_segments(tmp_path):
    victim = make(tmp_path)
    # rotate segments aggressively so truncation has something to delete
    victim.wal.max_segment_bytes = 64 * 1024
    for spans in batches(5):
        victim.accept(spans).execute()
    segs_before = glob.glob(str(tmp_path / "wal" / "wal-*.log"))
    assert len(segs_before) > 1
    victim.snapshot()
    segs_after = glob.glob(str(tmp_path / "wal" / "wal-*.log"))
    assert len(segs_after) < len(segs_before)
    del victim
    # boot after truncation: snapshot + remaining tail still consistent
    revived = make(tmp_path)
    oracle = make(tmp_path / "oracle", wal=False, checkpoint=False)
    for spans in batches(5):
        oracle.accept(spans).execute()
    assert_query_parity(oracle, revived)


def test_mp_ingest_batches_are_wal_logged(tmp_path):
    """The WAL hook sits at ingest_fused, so batches arriving via the
    multi-process tier must replay after a crash exactly like
    synchronous ones (vocab deltas flow through the dispatcher's global
    interning before the hook fires)."""
    from zipkin_tpu import native

    if not native.available():
        import pytest

        pytest.skip("native codec unavailable")
    from zipkin_tpu.model.json_v2 import encode_span_list
    from zipkin_tpu.tpu.mp_ingest import MultiProcessIngester

    bs = batches(3)
    payloads = [encode_span_list(spans) for spans in bs]

    oracle = make(tmp_path / "oracle", wal=False, checkpoint=False)
    for p in payloads:
        oracle.ingest_json_fast(p)

    victim = make(tmp_path)
    ing = MultiProcessIngester(victim, workers=1)
    try:
        for p in payloads:
            ing.submit(p)
        ing.drain()
    finally:
        ing.close()
    assert victim.agg.wal_seq > 0
    del victim  # crash

    revived = make(tmp_path)
    assert_query_parity(oracle, revived)
    assert revived.vocab.services._names == oracle.vocab.services._names


def test_server_periodic_snapshot_bounds_wal(tmp_path):
    """The server's snapshot loop persists state on a cadence and
    truncates covered WAL segments — without it the WAL grows without
    bound (snapshots previously only happened via the manual POST)."""
    import asyncio
    import glob as _glob

    from zipkin_tpu.server.app import ZipkinServer
    from zipkin_tpu.server.config import ServerConfig

    async def scenario():
        storage = make(tmp_path)
        storage.wal.max_segment_bytes = 64 * 1024  # rotate aggressively
        server = ZipkinServer(
            ServerConfig(
                storage_type="tpu", tpu_snapshot_interval_s=0.3,
            ),
            storage=storage,
        )
        # start() would bind a real port; drive the loop directly
        server._snapshot_task = asyncio.create_task(
            server._snapshot_loop(0.3)
        )
        for spans in batches(4):
            storage.accept(spans).execute()
        await asyncio.sleep(0.8)  # at least one snapshot fires
        server._snapshot_task.cancel()
        try:
            await server._snapshot_task
        except asyncio.CancelledError:
            pass
        assert (tmp_path / "ckpt" / "meta.json").exists()
        import json as _json

        meta = _json.load(open(tmp_path / "ckpt" / "meta.json"))
        assert meta["wal_seq"] > 0
        # the WAL-bounding half of the loop: every segment fully covered
        # by the snapshot's wal_seq was deleted — only the live segment
        # (and at most one covered-but-open predecessor) may remain
        segs = _glob.glob(str(tmp_path / "wal" / "wal-*.log"))
        assert len(segs) <= 2, segs
        return storage

    storage = asyncio.run(scenario())
    # the snapshot is usable: a fresh boot restores + replays
    del storage
    revived = make(tmp_path)
    oracle = make(tmp_path / "oracle", wal=False, checkpoint=False)
    for spans in batches(4):
        oracle.accept(spans).execute()
    assert_query_parity(oracle, revived)


def test_snapshot_races_concurrent_ingest(tmp_path):
    """Snapshots taken WHILE another thread ingests must stay exact:
    the device-side clone + wal_seq are captured atomically under the
    aggregator lock, and the WAL tail replays whatever each snapshot
    missed — so crash recovery reaches full parity no matter where the
    snapshots landed relative to the writes (r3: the host pull moved
    outside the lock so a full-size snapshot no longer stalls ingest)."""
    import threading

    bs = batches(8)
    victim = make(tmp_path)
    errors = []

    def writer():
        try:
            for spans in bs:
                victim.accept(spans).execute()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    t = threading.Thread(target=writer)
    t.start()
    for _ in range(4):  # snapshots interleave arbitrarily with writes
        victim.snapshot()
    t.join()
    assert not errors
    del victim  # crash without a final snapshot

    revived = make(tmp_path)
    oracle = make(tmp_path / "oracle", wal=False, checkpoint=False)
    for spans in bs:
        oracle.accept(spans).execute()
    assert_query_parity(oracle, revived)


def test_truncate_after_reopen_keeps_seq_watermark(tmp_path):
    """ISSUE 3 satellite: truncating a reopened-but-not-yet-written WAL
    must keep the newest segment — it is the only carrier of the seq
    high-water mark. The old guard only protected a segment while a
    writer held it open, so this truncation deleted everything and the
    NEXT boot restarted numbering at 1 ≤ snapshot wal_seq, making
    replay silently skip post-truncate appends."""
    from zipkin_tpu.tpu.wal import WriteAheadLog

    wal = WriteAheadLog(str(tmp_path / "wal"))
    for _ in range(3):
        wal.append(np.zeros((1, 2, 4), np.uint32), {"n_spans": 1})
    wal.close()

    wal2 = WriteAheadLog(str(tmp_path / "wal"))  # reopened, no writes
    assert wal2._seq == 3
    wal2.truncate_covered(3)  # a snapshot covers everything
    wal2.close()
    assert glob.glob(str(tmp_path / "wal" / "wal-*.log")), (
        "truncate-after-reopen deleted the newest segment"
    )
    wal3 = WriteAheadLog(str(tmp_path / "wal"))
    seq = wal3.append(np.zeros((1, 2, 4), np.uint32), {"n_spans": 1})
    assert seq == 4  # numbering continues past the covered records
    wal3.close()


def test_truncate_after_reboot_does_not_lose_later_batches(tmp_path):
    """Storage-level regression for the same hole: snapshot on a
    maintenance reboot (restore, snapshot, exit — no new traffic), then
    normal traffic, then crash. The post-truncate batches must replay."""
    bs = batches(5)
    victim = make(tmp_path)
    for spans in bs[:3]:
        victim.accept(spans).execute()
    victim.snapshot()
    del victim
    maint = make(tmp_path)  # maintenance reboot: snapshot, no ingest
    maint.snapshot()
    del maint
    survivor = make(tmp_path)
    for spans in bs[3:]:
        survivor.accept(spans).execute()
    del survivor  # crash
    revived = make(tmp_path)
    oracle = make(tmp_path / "oracle", wal=False, checkpoint=False)
    for spans in bs:
        oracle.accept(spans).execute()
    assert_query_parity(oracle, revived)


def test_records_seeks_past_covered_payloads(tmp_path):
    """ISSUE 3 satellite: records(from_seq) must seek past covered
    record bodies instead of reading + CRC-checking them. Observable
    behavior: corrupting a COVERED payload no longer stops the segment
    when resuming past it (while a full scan still stops there)."""
    import struct as _struct

    from zipkin_tpu.tpu import wal as wal_mod

    wal = wal_mod.WriteAheadLog(str(tmp_path / "wal"))
    for i in range(3):
        wal.append(np.full((1, 2, 4), i, np.uint32), {"n_spans": 1})
    wal.close()

    seg = sorted(glob.glob(str(tmp_path / "wal" / "wal-*.log")))[0]
    data = bytearray(open(seg, "rb").read())
    hdr = wal_mod._HEADER
    _, seq, meta_len, _, _ = hdr.unpack(data[: hdr.size])
    assert seq == 1
    off = hdr.size + meta_len  # first payload byte of record 1
    data[off] ^= 0xFF
    open(seg, "wb").write(bytes(data))

    reader = wal_mod.WriteAheadLog(str(tmp_path / "wal"))
    # full scan: the corrupt record is seq 1 -> crc fails, segment stops
    assert [s for s, _, _ in reader.records(0)] == []
    # resume past it: the body is skipped unverified, later records flow
    assert [s for s, _, _ in reader.records(1)] == [2, 3]
    reader.close()


def test_append_after_close_raises(tmp_path):
    """A hook captured by a racing thread before close() detached it must
    FAIL on append, not silently reopen the segment and log a batch past
    the final snapshot (double-replay on next boot)."""
    import pytest

    from zipkin_tpu.tpu.wal import WriteAheadLog

    wal = WriteAheadLog(str(tmp_path / "wal"))
    wal.append(np.zeros((1, 2, 4), np.uint32), {"n_spans": 0})
    wal.close()
    with pytest.raises(RuntimeError, match="closed"):
        wal.append(np.zeros((1, 2, 4), np.uint32), {"n_spans": 0})
    wal.close()  # idempotent
