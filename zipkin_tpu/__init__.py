"""zipkin-tpu: a TPU-native distributed-tracing backend.

A ground-up rebuild of the capabilities of Zipkin (reference:
``llinder/zipkin``, a fork of ``openzipkin/zipkin``) designed TPU-first:

- host tier: span model, codecs, collectors, Zipkin v2 HTTP API (aiohttp),
  an exact in-memory storage oracle;
- device tier: columnar span batches streamed into JAX arrays; per-(service,
  spanName) latency t-digests, HyperLogLog cardinalities, and service
  dependency-link counts maintained as sharded device state updated by
  jit-compiled ingest steps and merged across chips with ``lax.psum``.

Layering mirrors the reference (see SURVEY.md §1):

- L0 model/codecs    -> :mod:`zipkin_tpu.model`
- L1 storage SPI     -> :mod:`zipkin_tpu.storage.spi`, oracle in
                        :mod:`zipkin_tpu.storage.memory`
- L2 TPU backend     -> :mod:`zipkin_tpu.storage.tpu` (+ :mod:`zipkin_tpu.ops`)
- L3 collectors      -> :mod:`zipkin_tpu.collector`
- L4 server          -> :mod:`zipkin_tpu.server`
- L6 test kit        -> :mod:`zipkin_tpu.testkit`
"""

__version__ = "0.1.0"

from zipkin_tpu.model.span import (  # noqa: F401
    Annotation,
    DependencyLink,
    Endpoint,
    Kind,
    Span,
)
