"""L3: the collector framework — decode, sample, count, hand to storage."""

from zipkin_tpu.collector.core import (  # noqa: F401
    Collector,
    CollectorComponent,
    CollectorMetrics,
    CollectorSampler,
    InMemoryCollectorMetrics,
)
