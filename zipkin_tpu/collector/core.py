"""Collector core: the decode -> sample -> store pipeline every transport uses.

Reference semantics: ``zipkin2/collector/Collector.java``,
``CollectorComponent.java``, ``CollectorSampler.java``,
``CollectorMetrics.java``, ``InMemoryCollectorMetrics.java`` (SURVEY.md
§2.2, §3.2). The counter taxonomy (messages, messagesDropped, bytes, spans,
spansDropped) is kept name-for-name so dashboards translate.

Sampling is **boundary sampling**: the decision is a pure function of the
trace id's low 64 bits, so every collector node makes the same call for
every span of a trace without coordination — the property that lets the
ingest tier scale out statelessly (and lets the TPU ingest shard by trace
id without resampling).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from zipkin_tpu import faults, obs
from zipkin_tpu.model import codec
from zipkin_tpu.obs import critpath
from zipkin_tpu.model.span import Span
from zipkin_tpu.storage.spi import StorageComponent
from zipkin_tpu.utils.component import Component

logger = logging.getLogger(__name__)

_MAX_I64 = (1 << 63) - 1


class CollectorSampler:
    """Samples traces at a fixed rate keyed on trace-id low-64 bits.

    ``is_sampled`` compares ``abs(signed_low64(traceId))`` against
    ``rate * 2^63`` — the same arithmetic as the reference, so a mixed
    fleet of reference and rebuild collectors samples identically.
    Debug spans always pass.
    """

    def __init__(self, rate: float = 1.0) -> None:
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"rate should be between 0 and 1: {rate}")
        self.rate = rate
        self._boundary = int(_MAX_I64 * rate)

    def is_sampled(self, trace_id_low64: int, debug: bool = False) -> bool:
        if debug:
            return True
        signed = trace_id_low64 - (1 << 64) if trace_id_low64 >= (1 << 63) else trace_id_low64
        # Java parity: CollectorSampler explicitly maps Long.MIN_VALUE to
        # Long.MAX_VALUE before comparing (abs() alone would overflow), so
        # that one id is dropped at rates < 1.0 like any max-magnitude id.
        t = _MAX_I64 if signed == -(1 << 63) else abs(signed)
        return t <= self._boundary

    def test(self, span: Span) -> bool:
        return self.is_sampled(span.trace_id_low64, bool(span.debug))


class CollectorMetrics:
    """Counter hooks; subclass or use :class:`InMemoryCollectorMetrics`."""

    def increment_messages(self) -> None: ...

    def increment_messages_dropped(self) -> None: ...

    def increment_bytes(self, quantity: int) -> None: ...

    def increment_spans(self, quantity: int) -> None: ...

    def increment_spans_dropped(self, quantity: int) -> None: ...

    def for_transport(self, transport: str) -> "CollectorMetrics":
        return self


class InMemoryCollectorMetrics(CollectorMetrics):
    """Thread-safe counters, partitionable per transport.

    Reference: ``InMemoryCollectorMetrics.java``.
    """

    def __init__(self, transport: Optional[str] = None, _counters: Optional[Dict[str, int]] = None) -> None:
        self.transport = transport
        self._counters: Dict[str, int] = _counters if _counters is not None else {}
        self._lock = threading.Lock()

    def _inc(self, name: str, by: int = 1) -> None:
        key = f"{self.transport}.{name}" if self.transport else name
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + by

    def increment_messages(self) -> None:
        self._inc("messages")

    def increment_messages_dropped(self) -> None:
        self._inc("messages_dropped")

    def increment_bytes(self, quantity: int) -> None:
        self._inc("bytes", quantity)

    def increment_spans(self, quantity: int) -> None:
        self._inc("spans", quantity)

    def increment_spans_dropped(self, quantity: int) -> None:
        self._inc("spans_dropped", quantity)

    def for_transport(self, transport: str) -> "InMemoryCollectorMetrics":
        child = InMemoryCollectorMetrics(transport, self._counters)
        child._lock = self._lock
        return child

    def get(self, name: str, transport: Optional[str] = None) -> int:
        key = f"{transport}.{name}" if transport else name
        with self._lock:
            return self._counters.get(key, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)


class Collector:
    """The shared ingest pipeline: bytes or spans in, storage writes out.

    Reference: ``Collector.java#acceptSpans``. Errors while storing are
    counted as dropped spans and logged, never raised to the transport —
    at-least-once transports redeliver, lossy ones move on.
    """

    def __init__(
        self,
        storage: StorageComponent,
        *,
        sampler: Optional[CollectorSampler] = None,
        metrics: Optional[CollectorMetrics] = None,
        fast_ingest: bool = False,
        mp_ingester=None,
        shadow=None,
    ) -> None:
        self.storage = storage
        self.sampler = sampler or CollectorSampler(1.0)
        self.metrics = metrics or CollectorMetrics()
        # opt-in line-rate path: JSON v2 bytes go straight to the TPU
        # store's native columnar parser, skipping Span objects and the
        # raw-span archive (aggregates only — the v5e ingest headline)
        self.fast_ingest = fast_ingest and hasattr(storage, "ingest_json_fast")
        # optional multi-process parse tier (tpu/mp_ingest.py): payloads
        # are handed to worker processes and acked immediately — the
        # reference's 202-on-enqueue semantics (SURVEY.md §3.2)
        self.mp_ingester = mp_ingester
        # accuracy-observatory tap (obs/shadow.py): the object path
        # offers its post-sampling batches so the shadow sees the same
        # stream the device plane aggregates. O(1) bounded append.
        self.shadow = shadow
        # overload control plane (runtime/overload.py, ISSUE 13): the
        # server wires its brownout controller here so B2/B3 admission
        # verdicts gate payloads BEFORE any parse or queue hand-off. A
        # shed surfaces as IngestBackpressure — the transports already
        # map that to 429 / RESOURCE_EXHAUSTED with backoff guidance —
        # never as a silent ack.
        self.overload = None
        self._consumer = storage.span_consumer()

    def accept_spans_bytes(
        self, data: bytes, encoding: Optional[codec.Encoding] = None
    ) -> int:
        """Decode one transport message and ingest it.

        Returns the number of spans accepted (post-sampling). Raises
        ``ValueError`` on malformed payloads (the transport decides whether
        that is an HTTP 400 or a poison-pill skip) — after counting the
        dropped message.
        """
        # zt-tenant-admission: the collector chokepoint — tenant budget
        # first (scope tenant), then the global brownout ladder (scope
        # global), before any parse or device dispatch
        self.metrics.increment_messages()
        self.metrics.increment_bytes(len(data))
        from zipkin_tpu.runtime.tenant import CURRENT_TENANT

        tenant = CURRENT_TENANT.get()
        ctl = self.overload
        if ctl is not None:
            # admission (ISSUEs 13/18): the tenant's own token bucket is
            # consulted first — a flooding tenant sheds alone while
            # everyone else rides B0 — then the global ladder (B2 sheds
            # bulk payloads probabilistically, B3 admits the error class
            # only). The verdict precedes every parse/queue path so a
            # shed costs one substring probe, and the refusal is
            # explicit — the sender gets a retryable rejection carrying
            # scope + per-scope backoff guidance, never a dropped ack.
            from zipkin_tpu.tpu.mp_ingest import IngestBackpressure

            v = ctl.admit(data, tenant=tenant)
            if not v.admitted:
                self.metrics.increment_messages_dropped()
                if v.scope == "tenant":
                    msg = (
                        f"tenant {v.tenant!r} over ingest budget: "
                        f"{v.cls} payload shed; retry after the "
                        "advertised backoff"
                    )
                else:
                    msg = (
                        f"overload {ctl.level_name}: {v.cls} payload "
                        "shed; retry after the advertised backoff"
                    )
                raise IngestBackpressure(
                    msg, scope=v.scope, tenant=v.tenant,
                    retry_after_s=v.retry_after_s or None,
                )
        try:
            # resource-exhaustion injection (faults.py): an allocation
            # failure at the ingest boundary degrades to backpressure —
            # the sender retries against a tier that is telling the
            # truth about its memory — instead of crashing the server.
            faults.resource_point("alloc")
        except MemoryError as e:
            from zipkin_tpu.tpu.mp_ingest import IngestBackpressure

            self.metrics.increment_messages_dropped()
            raise IngestBackpressure(f"allocation failure: {e}") from e
        _MP = (codec.Encoding.JSON_V2, codec.Encoding.PROTO3)
        if (
            self.mp_ingester is not None
            # MP is the fast path's scale-out: it keeps the fast path's
            # sampled-archive semantics, so it must never preempt the
            # full-fidelity object path when fast ingest is off
            and self.fast_ingest
            and (encoding is None or encoding in _MP)
        ):
            if encoding is not None or codec.detect(data) in _MP:
                # span/drop counters are incremented by the dispatcher as
                # batches land (the ingester holds this collector's
                # metrics); 0 = accepted asynchronously. A malformed
                # payload is counted + logged by the dispatcher instead
                # of HTTP-400'd — the at-least-once transports share
                # this poison-pill semantic (SURVEY.md §3.3). proto3
                # rides the same fan-out: the workers' native parser
                # sniffs the wire format (ISSUE 8).
                from zipkin_tpu.tpu.mp_ingest import IngestBackpressure

                tok = None
                if critpath.WIRE_T0_NS.get() == 0:
                    # direct submitters (tests, benches driving the
                    # collector without a server boundary) still get
                    # wire-to-durable timelines, measured from collector
                    # entry; token-reset so a long-lived caller thread
                    # stamps fresh per payload
                    tok = critpath.WIRE_T0_NS.set(time.perf_counter_ns())
                try:
                    # non-blocking at the boundary: a full tier must
                    # surface as 429/RESOURCE_EXHAUSTED, not as the
                    # event loop's to_thread pool silently queueing
                    self.mp_ingester.submit(
                        data, block=False, tenant=tenant
                    )
                except IngestBackpressure:
                    self.metrics.increment_messages_dropped()
                    raise
                finally:
                    if tok is not None:
                        critpath.WIRE_T0_NS.reset(tok)
                return 0
        # the native tier parses JSON v2 AND proto3 ListOfSpans (r4:
        # gRPC/proto3 ingest was the one first-class hot codec still on
        # the ~30k/s object path — VERDICT r3 order 6)
        _FAST = (codec.Encoding.JSON_V2, codec.Encoding.PROTO3)
        if self.fast_ingest and (encoding is None or encoding in _FAST):
            from zipkin_tpu.storage.throttle import RejectedExecutionError

            try:
                if encoding is not None or codec.detect(data) in _FAST:
                    result = self.storage.ingest_json_fast(data, self.sampler)
                    if result is not None:
                        accepted, sample_dropped = result
                        self.metrics.increment_spans(accepted + sample_dropped)
                        if sample_dropped:
                            self.metrics.increment_spans_dropped(sample_dropped)
                        return accepted
            except RejectedExecutionError:
                # load shed on the fast path must show up on the same drop
                # counters the object path maintains, or dashboards go blind
                self.metrics.increment_messages_dropped()
                raise
            except ValueError:
                pass  # fall through: the python codec owns error reporting
        try:
            t0 = time.perf_counter()
            spans = codec.decode_spans(data, encoding)
            obs.record("parse", time.perf_counter() - t0)
        except Exception as e:
            self.metrics.increment_messages_dropped()
            raise ValueError(f"cannot decode spans: {e}") from e
        return self.accept(spans)

    def accept(self, spans: Sequence[Span]) -> int:
        """Sample + store already-decoded spans; returns count accepted."""
        if not spans:
            return 0
        self.metrics.increment_spans(len(spans))
        sampled: List[Span] = [s for s in spans if self.sampler.test(s)]
        dropped = len(spans) - len(sampled)
        if dropped:
            self.metrics.increment_spans_dropped(dropped)
        if not sampled:
            return 0
        if self.shadow is not None:
            self.shadow.offer_spans(sampled)
        try:
            self._consumer.accept(sampled).execute()
        except Exception as e:
            from zipkin_tpu.storage.throttle import RejectedExecutionError

            self.metrics.increment_spans_dropped(len(sampled))
            if isinstance(e, RejectedExecutionError):
                # backpressure must reach the transport so senders back off
                # (the reference maps RejectedExecutionException to 503)
                raise
            logger.exception("cannot store %d spans", len(sampled))
            return 0
        return len(sampled)


@dataclasses.dataclass
class CollectorComponent(Component):
    """Lifecycle contract for transports (start/check/close).

    Reference: ``CollectorComponent.java``. Concrete transports:
    HTTP (in the server), gRPC, and the queue consumers in
    :mod:`zipkin_tpu.collector.transports`.
    """

    collector: Collector

    def start(self) -> "CollectorComponent":
        return self
