"""Scribe collector: the legacy Twitter-era thrift transport.

Reference semantics: ``zipkin-collector/scribe`` —
``ScribeCollector.java`` / ``ScribeSpanConsumer.java`` (SURVEY.md §2.2):
a thrift RPC service ``scribe.Log(List<LogEntry>)`` where each entry of
category ``zipkin`` carries ONE base64-encoded thrift v1 span in its
``message``. Replies ``ResultCode.OK`` (0) once the batch is handed to
the collector, ``TRY_LATER`` (1) on storage rejection.

Implemented as an asyncio TCP server speaking TBinaryProtocol over
TFramedTransport (4-byte length prefix) — hand-rolled like the rest of
the codecs; no thrift runtime dependency.
"""

from __future__ import annotations

import asyncio
import base64
import logging
import struct
from typing import List, Optional, Tuple

from zipkin_tpu.collector.core import Collector
from zipkin_tpu.model.span import Span
from zipkin_tpu.model.json_v1 import convert_v1_spans
from zipkin_tpu.model.thrift import _Reader, _read_v1_span  # codec internals
from zipkin_tpu.utils.component import CheckResult, Component

logger = logging.getLogger(__name__)

_T_STRUCT = 12
_T_STRING = 11
_T_LIST = 15
_T_I32 = 8
_T_STOP = 0

_CALL = 1
_REPLY = 2
_EXCEPTION = 3
_VERSION_1 = 0x80010000

OK, TRY_LATER = 0, 1


def _parse_log_call(frame: bytes) -> Tuple[int, List[Tuple[str, bytes]]]:
    """Parse a thrift binary ``Log`` call; returns (seqid, [(category,
    message)]). Raises ValueError on anything malformed."""
    r = _Reader(frame)
    first = r.i32()
    if first & 0xFFFF0000 == _VERSION_1 & 0xFFFF0000:
        mtype = first & 0xFF
        name = r.binary().decode("utf-8", "replace")
        seqid = r.i32()
    else:  # old-style unversioned: name length first
        r = _Reader(frame)
        name = r.binary().decode("utf-8", "replace")
        mtype = r.u8()
        seqid = r.i32()
    if mtype != _CALL or name != "Log":
        raise ValueError(f"unsupported scribe call {name!r} type {mtype}")

    entries: List[Tuple[str, bytes]] = []
    while True:
        ftype = r.u8()
        if ftype == _T_STOP:
            break
        fid = r.i16()
        if fid == 1 and ftype == _T_LIST:
            etype = r.u8()
            count = r.i32()
            if etype != _T_STRUCT:
                raise ValueError("messages field must be list<LogEntry>")
            for _ in range(count):
                category, message = "", b""
                while True:
                    et = r.u8()
                    if et == _T_STOP:
                        break
                    eid = r.i16()
                    if eid == 1 and et == _T_STRING:
                        category = r.binary().decode("utf-8", "replace")
                    elif eid == 2 and et == _T_STRING:
                        message = r.binary()
                    else:
                        r.skip(et)
                entries.append((category, message))
        else:
            r.skip(ftype)
    return seqid, entries


def _reply(seqid: int, code: int) -> bytes:
    """Encode ``Log_result{0: ResultCode}`` as a versioned REPLY frame."""
    name = b"Log"
    body = struct.pack(">I", (_VERSION_1 | _REPLY) & 0xFFFFFFFF)
    body += struct.pack(">i", len(name)) + name
    body += struct.pack(">i", seqid)
    body += bytes([_T_I32]) + struct.pack(">hi", 0, code) + bytes([_T_STOP])
    return struct.pack(">I", len(body)) + body


def decode_scribe_message(message: bytes) -> List[Span]:
    """One LogEntry message -> spans: base64 (MIME or raw) thrift v1 span."""
    raw = base64.b64decode(message, validate=False)
    r = _Reader(raw)
    return convert_v1_spans([_read_v1_span(r)])


class ScribeCollector(Component):
    """Lifecycle wrapper over the asyncio scribe server (port 9410)."""

    def __init__(
        self, collector: Collector, host: str = "0.0.0.0", port: int = 9410,
        category: str = "zipkin",
    ) -> None:
        self.collector = collector
        self.host = host
        self.port = port
        self.category = category
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> "ScribeCollector":
        self._server = await asyncio.start_server(
            self._serve_connection, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("scribe collector listening on %s", self.port)
        return self

    async def _serve_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            while True:
                header = await reader.readexactly(4)
                (length,) = struct.unpack(">I", header)
                if length > 64 * 1024 * 1024:
                    raise ValueError("scribe frame too large")
                frame = await reader.readexactly(length)
                writer.write(await self._handle_frame(frame))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass  # client hung up
        except Exception:
            logger.exception("scribe connection error")
        finally:
            writer.close()

    async def _handle_frame(self, frame: bytes) -> bytes:
        seqid, entries = _parse_log_call(frame)
        spans: List[Span] = []
        metrics = self.collector.metrics
        for category, message in entries:
            metrics.increment_messages()
            metrics.increment_bytes(len(message))
            if category.lower() != self.category:
                continue
            try:
                spans.extend(decode_scribe_message(message))
            except Exception:
                metrics.increment_messages_dropped()
        try:
            if spans:
                await asyncio.to_thread(self.collector.accept, spans)
        except Exception:
            return _reply(seqid, TRY_LATER)  # storage rejection: retryable
        return _reply(seqid, OK)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    def check(self) -> CheckResult:
        if self._server is not None and self._server.is_serving():
            return CheckResult.OK
        return CheckResult.failed(RuntimeError("scribe server not running"))

    def close(self) -> None:
        pass  # async stop() is the real teardown
