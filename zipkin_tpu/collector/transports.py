"""Broker transports: the poll->decode->sample->store loops.

Reference semantics: ``zipkin-collector/{kafka,rabbitmq,activemq}``
(SURVEY.md §2.2, §3.3) — N workers polling a source, handing raw bytes to
``Collector.accept_spans_bytes`` (format auto-detection + sampling +
storage), committing offsets only after accept so delivery is
at-least-once (duplicates possible; storage dedups or bounded
double-count, SURVEY.md §3.3).

Because this image has no broker clients installed, the transport seam is
a tiny :class:`MessageSource` protocol with three in-repo sources:

- :class:`QueueSource` — in-process queue (the unit-test broker, playing
  the role the reference's testcontainers play).
- :class:`ReplayFileSource` — length-prefixed message log with a durable
  offset marker: both the replay-benchmark feed (BASELINE config[4]) and
  the crash-resume story (Kafka-offset analog, SURVEY.md §5).
- ``KafkaSource`` — real Kafka via kafka-python **if importable**;
  otherwise construction raises with a clear message. The collector
  structure (workers, commit discipline) is identical either way.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import time
from typing import List, Optional, Sequence

from zipkin_tpu.collector.core import (
    Collector,
    CollectorComponent,
    CollectorMetrics,
    InMemoryCollectorMetrics,
)
from zipkin_tpu.utils.component import CheckResult

logger = logging.getLogger(__name__)

# -- the transport seam ---------------------------------------------------


class Message:
    """One opaque payload plus its resume offset (and optional transport
    metadata, e.g. a STOMP ack id)."""

    __slots__ = ("payload", "offset", "meta")

    def __init__(self, payload: bytes, offset: int, meta=None) -> None:
        self.payload = payload
        self.offset = offset
        self.meta = meta


class MessageSource:
    """Minimal consumer contract: poll / commit / close."""

    def poll(self, max_messages: int, timeout: float) -> List[Message]:
        raise NotImplementedError

    def commit(self, offset: int) -> None:
        """Mark everything up to ``offset`` (inclusive) as consumed."""

    def check(self) -> CheckResult:
        return CheckResult.OK

    def close(self) -> None: ...


class QueueSource(MessageSource):
    """In-process broker stand-in (bounded, drop-oldest-never: put blocks)."""

    def __init__(self, maxsize: int = 10_000) -> None:
        import queue

        self._q: "queue.Queue[bytes]" = __import__("queue").Queue(maxsize)
        self._seq = 0
        self.committed = -1

    def send(self, payload: bytes) -> None:
        self._q.put(payload)

    def poll(self, max_messages: int, timeout: float) -> List[Message]:
        import queue

        out: List[Message] = []
        deadline = time.monotonic() + timeout
        while len(out) < max_messages:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                payload = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            out.append(Message(payload, self._seq))
            self._seq += 1
        return out

    def commit(self, offset: int) -> None:
        self.committed = max(self.committed, offset)


class ReplayFileSource(MessageSource):
    """Length-prefixed message log (``u32 big-endian length + payload``)*
    with a sidecar ``.offset`` marker for resume.

    Writer half (:func:`append_replay`) + reader half in one class: the
    file format doubles as the pre-tokenized ingest corpus for replay
    benchmarks and as a write-ahead log for crash recovery (SURVEY.md §5
    failure-detection row).
    """

    def __init__(self, path: str, *, resume: bool = True) -> None:
        self.path = path
        self.offset_path = path + ".offset"
        self._file = open(path, "rb")
        self._index = 0
        self.committed = -1
        if resume and os.path.exists(self.offset_path):
            with open(self.offset_path) as f:
                committed = int(f.read().strip() or -1)
            self.committed = committed
            # skip already-consumed messages
            while self._index <= committed:
                if self._read_one() is None:
                    break

    def _read_one(self) -> Optional[bytes]:
        header = self._file.read(4)
        if len(header) < 4:
            return None
        (length,) = struct.unpack(">I", header)
        payload = self._file.read(length)
        if len(payload) < length:
            return None
        self._index += 1
        return payload

    def poll(self, max_messages: int, timeout: float) -> List[Message]:
        out: List[Message] = []
        for _ in range(max_messages):
            payload = self._read_one()
            if payload is None:
                break
            out.append(Message(payload, self._index - 1))
        return out

    def commit(self, offset: int) -> None:
        if offset <= self.committed:
            return
        self.committed = offset
        tmp = self.offset_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(offset))
        os.replace(tmp, self.offset_path)

    def check(self) -> CheckResult:
        return (
            CheckResult.OK
            if not self._file.closed
            else CheckResult.failed(RuntimeError("replay file closed"))
        )

    def close(self) -> None:
        self._file.close()


def append_replay(path: str, payloads: Sequence[bytes]) -> None:
    """Append messages to a replay log (writer half of ReplayFileSource)."""
    with open(path, "ab") as f:
        for p in payloads:
            f.write(struct.pack(">I", len(p)))
            f.write(p)


class KafkaSource(MessageSource):
    """Kafka consumer over kafka-python, if installed.

    Mirrors ``KafkaCollectorWorker``'s poll loop. Kafka offsets are per
    partition, but the collector's watermark is a single cumulative
    sequence — so this source numbers polled records with its own
    monotonic sequence and, on ``commit(watermark)``, commits per
    partition the highest record offset at or below the watermark
    (+1 = Kafka's next-to-consume convention). At-least-once: nothing
    commits until the collector marks the message stored.
    """

    def __init__(
        self,
        bootstrap_servers: str,
        topic: str = "zipkin",
        group_id: str = "zipkin",
    ) -> None:
        try:
            from kafka import KafkaConsumer, OffsetAndMetadata  # type: ignore
        except ImportError as e:  # pragma: no cover - not in this image
            raise RuntimeError(
                "kafka-python is not installed; use ReplayFileSource or "
                "QueueSource, or install kafka-python"
            ) from e
        # kafka-python >= 2.1 added a required leader_epoch field to the
        # OffsetAndMetadata namedtuple; construct compatibly with both.
        def _om(offset):
            try:
                return OffsetAndMetadata(offset, None, -1)
            except TypeError:
                return OffsetAndMetadata(offset, None)

        self._offset_meta = _om
        self._consumer = KafkaConsumer(
            topic,
            bootstrap_servers=bootstrap_servers.split(","),
            group_id=group_id,
            enable_auto_commit=False,
        )
        self._seq = 0
        self._pending: dict = {}  # seq -> (TopicPartition, kafka offset)

    def poll(self, max_messages, timeout):
        records = self._consumer.poll(
            timeout_ms=int(timeout * 1000), max_records=max_messages
        )
        out = []
        for tp, batch in records.items():
            for r in batch:
                self._pending[self._seq] = (tp, r.offset)
                out.append(Message(r.value, self._seq, meta=(tp, r.offset)))
                self._seq += 1
        return out

    def commit(self, offset) -> None:
        ready = [s for s in self._pending if s <= offset]
        if not ready:
            return
        per_tp: dict = {}
        for s in ready:
            tp, koff = self._pending[s]
            per_tp[tp] = max(per_tp.get(tp, -1), koff)
        # commit BEFORE dropping from _pending: a failed commit (routine on
        # rebalance) must leave the offsets re-committable by a later
        # watermark, not silently forgotten.
        self._consumer.commit(
            {tp: self._offset_meta(koff + 1) for tp, koff in per_tp.items()}
        )
        for s in ready:
            del self._pending[s]

    def close(self) -> None:
        self._consumer.close()


class RabbitMQSource(MessageSource):
    """RabbitMQ basic-consume on queue ``zipkin`` via pika, if installed.

    Mirrors ``RabbitMQCollector.java``: basic_get polling with explicit
    acks after storage accept (at-least-once).
    """

    def __init__(self, uri: str, queue: str = "zipkin") -> None:
        try:
            import pika  # type: ignore
        except ImportError as e:  # pragma: no cover - not in this image
            raise RuntimeError(
                "pika is not installed; use ReplayFileSource or QueueSource, "
                "or install pika"
            ) from e
        self._connection = pika.BlockingConnection(  # pragma: no cover
            pika.URLParameters(uri)
        )
        self._channel = self._connection.channel()  # pragma: no cover
        self._queue = queue
        self._committed = 0  # highest delivery tag already acked

    def poll(self, max_messages, timeout):
        out = []
        for _ in range(max_messages):
            method, _props, body = self._channel.basic_get(self._queue)
            if method is None:
                break
            out.append(Message(body, method.delivery_tag))
        return out

    def commit(self, offset) -> None:
        # Delivery tags are 1-based and multiple-acks are cumulative, so:
        # tag 0 must never reach basic_ack (AMQP reads it as "ack ALL
        # outstanding", which would ack unstored deliveries), and a
        # repeated watermark must not re-ack an already-acked tag (the
        # broker closes the channel with PRECONDITION_FAILED).
        if offset <= self._committed or offset < 1:
            return
        self._channel.basic_ack(offset, multiple=True)
        self._committed = offset

    def close(self) -> None:  # pragma: no cover
        self._connection.close()


class ActiveMQSource(MessageSource):
    """ActiveMQ queue consume via stomp.py, if installed.

    Mirrors ``ActiveMQCollector.java`` (JMS consume -> accept); STOMP is
    the broker protocol available to Python.
    """

    def __init__(self, host: str, port: int = 61613, queue: str = "zipkin") -> None:
        try:
            import stomp  # type: ignore
        except ImportError as e:  # pragma: no cover - not in this image
            raise RuntimeError(
                "stomp.py is not installed; use ReplayFileSource or "
                "QueueSource, or install stomp.py"
            ) from e
        import queue as pyqueue  # pragma: no cover

        self._buffer = pyqueue.Queue()  # pragma: no cover
        self._conn = stomp.Connection([(host, port)])  # pragma: no cover

        outer = self

        class _Listener(stomp.ConnectionListener):  # pragma: no cover
            def on_message(self, frame):
                outer._buffer.put((frame.body.encode(), frame.headers))

        self._conn.set_listener("zipkin", _Listener())  # pragma: no cover
        self._conn.connect(wait=True)  # pragma: no cover
        self._conn.subscribe(f"/queue/{queue}", id=1, ack="client-individual")  # pragma: no cover
        self._seq = 0
        self._unacked: dict = {}  # offset -> stomp ack id

    def poll(self, max_messages, timeout):  # pragma: no cover
        import queue as pyqueue

        out = []
        deadline = time.monotonic() + timeout
        while len(out) < max_messages:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                body, headers = self._buffer.get(timeout=remaining)
            except pyqueue.Empty:
                break
            ack_id = headers.get("ack") or headers.get("message-id")
            self._unacked[self._seq] = ack_id
            out.append(Message(body, self._seq, meta=ack_id))
            self._seq += 1
        return out

    def commit(self, offset) -> None:  # pragma: no cover
        # client-individual ack mode: ack every delivered frame <= offset
        for off in sorted(k for k in self._unacked if k <= offset):
            self._conn.ack(self._unacked.pop(off))

    def close(self) -> None:  # pragma: no cover
        self._conn.disconnect()


# -- the collector component ---------------------------------------------


class TransportCollector(CollectorComponent):
    """N worker threads draining a MessageSource into the Collector.

    The generalization of ``KafkaCollector``/``RabbitMQCollector``/
    ``ActiveMQCollector``: the broker specifics live in the source; the
    decode→sample→store→commit discipline lives here, once.
    """

    def __init__(
        self,
        source: MessageSource,
        collector: Collector,
        *,
        transport: str = "replay",
        workers: int = 1,
        poll_batch: int = 64,
        poll_timeout: float = 0.2,
    ) -> None:
        self.source = source
        self.collector = collector  # owns ALL metric counting
        self.transport = transport
        self._workers = workers
        self._poll_batch = poll_batch
        self._poll_timeout = poll_timeout
        self._threads: List[threading.Thread] = []
        self._running = threading.Event()
        # guards poll/commit + watermark bookkeeping (single-poller
        # sources); decode+store run OUTSIDE it so workers > 1 actually
        # parallelize (reference: N KafkaCollectorWorker streams). Each
        # worker keeps its own retry list of polled-but-unstored messages
        # (transient storage failure), so a rejection loses nothing
        # in-process; crash durability remains the committed offset.
        self._lock = threading.Lock()
        # Sources commit CUMULATIVELY (replay marker, kafka group offset,
        # rabbit multiple-ack), so with several workers a fast worker must
        # not commit past a slower worker's still-unstored offsets:
        # track outstanding offsets and only commit below their minimum.
        self._outstanding: set = set()
        self._stored_high = -1

    def start(self) -> "TransportCollector":
        self._running.set()
        for i in range(self._workers):
            t = threading.Thread(
                target=self._run, name=f"{self.transport}-collector-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)
        return self

    def _poll(self, timeout: float) -> List[Message]:
        with self._lock:
            messages = self.source.poll(self._poll_batch, timeout)
            self._outstanding.update(m.offset for m in messages)
            return messages

    def _mark_stored(self, offset: int) -> None:
        """Record one stored message and commit the safe watermark: the
        highest stored offset with nothing unstored at or below it."""
        with self._lock:
            self._outstanding.discard(offset)
            self._stored_high = max(self._stored_high, offset)
            floor = min(self._outstanding) - 1 if self._outstanding else self._stored_high
            watermark = min(self._stored_high, floor)
            if watermark >= 0:
                try:
                    self.source.commit(watermark)  # after accept: at-least-once
                except Exception:
                    # A failed commit (broker rebalance, transient I/O) must
                    # not kill the worker: the spans ARE stored, and the
                    # next stored message retries with >= this watermark.
                    # Worst case is redelivery — the at-least-once contract.
                    logger.warning(
                        "%s commit(%d) failed; will retry on next store",
                        self.transport, watermark, exc_info=True,
                    )

    def _process(self, messages: List[Message]) -> List[Message]:
        """Store a batch; returns the unstored tail on storage failure
        (empty when the batch finished)."""
        for i, m in enumerate(messages):
            try:
                self.collector.accept_spans_bytes(m.payload)
            except ValueError:
                # poison pill: counted dropped by the collector; it is
                # terminally consumed, so it still advances the watermark
                pass
            except Exception:
                return messages[i:]  # retried before the next poll
            self._mark_stored(m.offset)
        return []

    def _run(self) -> None:
        retry: List[Message] = []
        while self._running.is_set():
            if retry:
                messages, retry = retry, []
            else:
                messages = self._poll(self._poll_timeout)
            if messages:
                retry = self._process(messages)
                if retry:
                    time.sleep(self._poll_timeout)  # back off before retry

    def drain(self, deadline: float = 5.0) -> None:
        """Test helper: poll inline until the source stops yielding."""
        end = time.monotonic() + deadline
        idle = 0
        retry: List[Message] = []
        while time.monotonic() < end and idle < 3:
            if retry:
                messages, retry = retry, []
            else:
                messages = self._poll(0.05)
            if messages:
                idle = 0
                retry = self._process(messages)
            else:
                idle += 1

    def check(self) -> CheckResult:
        return self.source.check()

    def close(self) -> None:
        self._running.clear()
        for t in self._threads:
            t.join(timeout=2.0)
        self.source.close()


def kafka_collector(
    bootstrap_servers: str,
    collector: Collector,
    *,
    topic: str = "zipkin",
    group_id: str = "zipkin",
    streams: int = 1,
) -> TransportCollector:
    """KAFKA_BOOTSTRAP_SERVERS autoconfig entry point (KafkaCollector)."""
    return TransportCollector(
        KafkaSource(bootstrap_servers, topic, group_id),
        collector,
        transport="kafka",
        workers=streams,
    )
