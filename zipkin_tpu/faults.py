"""Crashpoint fault injection for the durability plane (ISSUE 3).

The durability claims in ARCHITECTURE.md ("every 202-acked batch
replays after kill -9") are only as good as the crash *timing* they
were tested under. This registry names the exact instants inside the
write path where a crash is most likely to tear on-disk state, so the
chaos driver (tests/test_chaos_recovery.py, benchmarks/chaos_soak.py)
can kill the process AT each of them instead of at whatever instant a
timer happens to land on:

- ``wal.append.mid``       header+meta of a WAL record written, payload not
- ``wal.append.pre_fsync`` record fully written+flushed, fsync still pending
- ``snapshot.post_state``  state ``.npz`` renamed in, meta.json not yet
- ``snapshot.post_meta``   meta.json renamed in, covered WAL not yet truncated
- ``archive.mid_segment``  archive frame header+index written, payload not

Arming is either programmatic (``arm(site, nth=..., action=...)`` from
an in-process test) or via the environment for subprocess drivers:
``ZT_CRASHPOINT=<site>[:nth]`` fires on the nth pass through the site
(default 1st); ``ZT_CRASHPOINT_ACTION`` picks ``kill`` (SIGKILL —
maximum realism, buffered bytes are lost), ``exit`` (``os._exit`` —
kills the process but buffered C-level file writes already made are
kept), or ``raise`` (``CrashpointTriggered`` — in-process simulation;
the caller must abandon the store object, exactly like the existing
``del victim`` crash idiom in tests/test_wal.py).

The disarmed fast path is two comparisons, so production code keeps
the hooks compiled in; a site is one-shot — it disarms itself as it
fires so crash *handling* code can re-enter the same path.
"""

from __future__ import annotations

import logging
import os
import signal
from typing import Optional

logger = logging.getLogger(__name__)

# the site catalog is static so drivers can randomize over it
SITES = (
    "wal.append.mid",
    "wal.append.pre_fsync",
    "snapshot.post_state",
    "snapshot.post_meta",
    "archive.mid_segment",
)

ENV_VAR = "ZT_CRASHPOINT"
ENV_ACTION = "ZT_CRASHPOINT_ACTION"
EXIT_CODE = 137  # what a SIGKILL'd child reports; `exit` mimics it

_ACTIONS = ("kill", "exit", "raise")


class CrashpointTriggered(RuntimeError):
    """Raised by a crashpoint armed with action="raise". The process is
    notionally dead at this instant: the owning store/WAL/archive object
    must be abandoned, not used further."""


_site: Optional[str] = None
_nth = 0
_action = "kill"


def arm(site: str, nth: int = 1, action: str = "kill") -> None:
    """Arm one site to fire on its ``nth`` traversal."""
    if site not in SITES:
        raise ValueError(f"unknown crashpoint site {site!r} (see faults.SITES)")
    if action not in _ACTIONS:
        raise ValueError(f"unknown crashpoint action {action!r}")
    global _site, _nth, _action
    _site, _nth, _action = site, max(1, int(nth)), action


def disarm() -> None:
    global _site, _nth
    _site, _nth = None, 0


def armed_site() -> Optional[str]:
    return _site


def is_armed(site: str) -> bool:
    return _site == site


def crashpoint(site: str) -> None:
    """Hot-path hook. No-op (two comparisons) unless ``site`` is armed."""
    global _site, _nth
    if _site is None or site != _site:
        return
    _nth -= 1
    if _nth > 0:
        return
    _site = None  # one-shot: recovery code may re-enter this same path
    logger.warning("crashpoint %s firing (action=%s)", site, _action)
    if _action == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
    if _action == "exit":
        os._exit(EXIT_CODE)
    raise CrashpointTriggered(site)


def _arm_from_env() -> None:
    raw = os.environ.get(ENV_VAR)
    if not raw:
        return
    site, _, nth = raw.partition(":")
    try:
        arm(
            site.strip(),
            int(nth) if nth.strip() else 1,
            os.environ.get(ENV_ACTION, "kill").strip() or "kill",
        )
    except ValueError as e:
        # a typo'd env var must not brick a production boot
        logger.warning("ignoring %s=%r: %s", ENV_VAR, raw, e)


_arm_from_env()
