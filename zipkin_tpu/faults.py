"""Crashpoint + corruption fault injection for the durability plane.

The durability claims in ARCHITECTURE.md ("every 202-acked batch
replays after kill -9") are only as good as the crash *timing* they
were tested under. This registry names the exact instants inside the
write path where a crash is most likely to tear on-disk state, so the
chaos driver (tests/test_chaos_recovery.py, benchmarks/chaos_soak.py)
can kill the process AT each of them instead of at whatever instant a
timer happens to land on:

- ``wal.append.mid``       header+meta of a WAL record written, payload not
- ``wal.append.pre_fsync`` record fully written+flushed, fsync still pending
- ``snapshot.post_state``  state ``.npz`` renamed in, meta.json not yet
- ``snapshot.post_meta``   meta.json renamed in, covered WAL not yet truncated
- ``archive.mid_segment``  archive frame header+index written, payload not

Arming is either programmatic (``arm(site, nth=..., action=...)`` from
an in-process test) or via the environment for subprocess drivers:
``ZT_CRASHPOINT=<site>[:nth][,<site>[:nth]...]`` fires each listed
site on its nth pass (default 1st); ``ZT_CRASHPOINT_ACTION`` picks
``kill`` (SIGKILL — maximum realism, buffered bytes are lost), ``exit``
(``os._exit`` — kills the process but buffered C-level file writes
already made are kept), or ``raise`` (``CrashpointTriggered`` —
in-process simulation; the caller must abandon the store object,
exactly like the existing ``del victim`` crash idiom in
tests/test_wal.py). Multiple sites arm at once so the corruption soak
can combine a corrupt site with a kill site in one child run.

The ``corrupt`` action family (ISSUE 7) models silent media bit-rot
rather than a crash: a corrupt site names an artifact the write path
just made durable, and firing it damages those bytes ON DISK — the
process keeps running, exactly like rot that happens at rest:

- ``snapshot.state``  the newest committed snapshot generation's .npz
- ``wal.record``      the payload of the WAL record just appended
- ``archive.frame``   the payload of the archive frame just appended

Damage modes are deterministic (position derived from the artifact's
byte range, no RNG): ``flip`` XORs one mid-range byte, ``zero`` zeroes
a mid-range run, ``truncate`` cuts the file mid-artifact. Armed via
``arm_corrupt(site, mode=..., nth=...)`` or
``ZT_CORRUPT=<site>[:mode[:nth]]`` (comma-separated like
ZT_CRASHPOINT). Restore-time digest verification, generation fallback,
and the background scrubber (runtime/scrub.py) are the recovery story
these sites exist to prove.

The ``resource`` family (ISSUE 13) models exhaustion rather than a
crash or rot: the process keeps running but an operation fails (or
slows) the way it does when a machine runs out of something. Sites
name the operation whose resource ran out:

- ``wal.append``   ENOSPC on the WAL record write
- ``snapshot``     ENOSPC on the snapshot state/meta write
- ``archive``      ENOSPC on the archive segment append
- ``feed.latency`` injected latency on the device-feed dispatch
- ``alloc``        allocation failure (MemoryError) on ingest staging

Unlike crashpoints a resource fault is usually *sustained* — a full
disk stays full — so arming takes a ``count``: the site starts firing
on its ``nth`` traversal and keeps firing for ``count`` consecutive
traversals before auto-clearing (space freed). ``count=0`` means fire
until ``disarm()``. Armed via ``arm_resource(site, nth=..., count=...,
latency_ms=...)`` or ``ZT_RESOURCE=<site>[:nth[:count]],...`` (plus
``ZT_RESOURCE_LATENCY_MS`` for the latency site). The handling
contract these sites exist to prove (tests/test_overload.py): disk
exhaustion degrades to an explicitly-flagged at-risk mode with an SLO
page — never a crash, never a silent ack — and clearing the fault
restores normal operation with bit-identical query state.

A resource site can additionally target ONE tenant (ISSUE 18):
``arm_resource(site, tenant="B")`` or
``ZT_RESOURCE=feed.latency:tenant=B`` fires only on traversals
attributed to that tenant — either the explicit ``tenant=`` argument
the call site passes (the fan-out dispatcher knows its chunk's
tenant), or the ambient ``CURRENT_TENANT`` contextvar at boundary
sites. Non-matching traversals do NOT consume ``nth``/``count``, so a
fault armed for tenant B stays armed through any amount of A/C
traffic — the deterministic per-tenant injection the isolation tests
(tests/test_tenant.py, EVALS config9) are built on.

The disarmed fast path is one dict probe, so production code keeps the
hooks compiled in; a site is one-shot — it disarms itself as it fires
so crash/scrub *handling* code can re-enter the same path.
"""

from __future__ import annotations

import errno
import logging
import os
import signal
import time
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

# the site catalogs are static so drivers can randomize over them
SITES = (
    "wal.append.mid",
    "wal.append.pre_fsync",
    "snapshot.post_state",
    "snapshot.post_meta",
    "archive.mid_segment",
    # time-tier bucket seal (tpu/timetier.py): pre_commit fires after
    # the segment tmp file is written but BEFORE the atomic rename
    # (crash leaves no segment — reseal on resume); post_commit fires
    # after the rename but before sealed_through advances (crash leaves
    # a committed segment the resume must adopt idempotently)
    "timetier.seal.pre_commit",
    "timetier.seal.post_commit",
)
CORRUPT_SITES = (
    "snapshot.state",
    "wal.record",
    "archive.frame",
    "timetier.segment",
)
CORRUPT_MODES = ("flip", "truncate", "zero")
RESOURCE_SITES = (
    "wal.append",
    "snapshot",
    "archive",
    "feed.latency",
    "alloc",
)

ENV_VAR = "ZT_CRASHPOINT"
ENV_ACTION = "ZT_CRASHPOINT_ACTION"
ENV_CORRUPT = "ZT_CORRUPT"
ENV_RESOURCE = "ZT_RESOURCE"
ENV_RESOURCE_LATENCY = "ZT_RESOURCE_LATENCY_MS"
EXIT_CODE = 137  # what a SIGKILL'd child reports; `exit` mimics it

_ACTIONS = ("kill", "exit", "raise")


class CrashpointTriggered(RuntimeError):
    """Raised by a crashpoint armed with action="raise". The process is
    notionally dead at this instant: the owning store/WAL/archive object
    must be abandoned, not used further."""


# site -> [remaining_nth, action]; mutated in place by crashpoint()
_armed: Dict[str, List] = {}
# site -> [remaining_nth, mode]; mutated in place by corrupt_point()
_corrupt_armed: Dict[str, List] = {}
# site -> [remaining_nth, remaining_count, latency_s, tenant|None];
# mutated in place by resource_point()
_resource_armed: Dict[str, List] = {}


def arm(site: str, nth: int = 1, action: str = "kill") -> None:
    """Arm one site to fire on its ``nth`` traversal. Arming a second
    site keeps the first armed (multi-site soaks)."""
    if site not in SITES:
        raise ValueError(f"unknown crashpoint site {site!r} (see faults.SITES)")
    if action not in _ACTIONS:
        raise ValueError(f"unknown crashpoint action {action!r}")
    _armed[site] = [max(1, int(nth)), action]


def arm_corrupt(site: str, mode: str = "flip", nth: int = 1) -> None:
    """Arm a corruption site to damage its ``nth`` written artifact."""
    if site not in CORRUPT_SITES:
        raise ValueError(
            f"unknown corrupt site {site!r} (see faults.CORRUPT_SITES)"
        )
    if mode not in CORRUPT_MODES:
        raise ValueError(
            f"unknown corrupt mode {mode!r} (see faults.CORRUPT_MODES)"
        )
    _corrupt_armed[site] = [max(1, int(nth)), mode]


def arm_resource(site: str, nth: int = 1, count: int = 1,
                 latency_ms: float = 25.0,
                 tenant: Optional[str] = None) -> None:
    """Arm a resource site: starts failing on its ``nth`` traversal and
    keeps failing for ``count`` consecutive traversals (0 = until
    ``disarm()``), modeling sustained exhaustion that later clears.
    ``tenant`` scopes the fault to one tenant's traversals (ISSUE 18);
    other tenants pass through without consuming nth/count."""
    if site not in RESOURCE_SITES:
        raise ValueError(
            f"unknown resource site {site!r} (see faults.RESOURCE_SITES)"
        )
    _resource_armed[site] = [
        max(1, int(nth)), max(0, int(count)), max(0.0, latency_ms) / 1000.0,
        tenant or None,
    ]


def disarm() -> None:
    _armed.clear()
    _corrupt_armed.clear()
    _resource_armed.clear()


def armed_site() -> Optional[str]:
    """First armed crashpoint site (None when disarmed). With several
    sites armed, drivers that need the full set should consult their
    own arming calls; this keeps the single-site API working."""
    return next(iter(_armed), None)


def is_armed(site: str) -> bool:
    return site in _armed


def is_corrupt_armed(site: str) -> bool:
    return site in _corrupt_armed


def is_resource_armed(site: str) -> bool:
    return site in _resource_armed


def crashpoint(site: str) -> None:
    """Hot-path hook. No-op (one dict probe) unless ``site`` is armed."""
    spec = _armed.get(site)
    if spec is None:
        return
    spec[0] -= 1
    if spec[0] > 0:
        return
    del _armed[site]  # one-shot: recovery code may re-enter this same path
    action = spec[1]
    logger.warning("crashpoint %s firing (action=%s)", site, action)
    if action == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
    if action == "exit":
        os._exit(EXIT_CODE)
    raise CrashpointTriggered(site)


def corrupt_point(site: str, path: str, start: int, length: int) -> bool:
    """Write-path hook: the caller just made ``length`` bytes at
    ``start`` of ``path`` durable. If ``site`` is armed, damage them in
    place (deterministically) and return True; the caller continues
    normally — rot is silent. One-shot like crashpoints."""
    spec = _corrupt_armed.get(site)
    if spec is None or length <= 0:
        return False
    spec[0] -= 1
    if spec[0] > 0:
        return False
    del _corrupt_armed[site]
    mode = spec[1]
    mid = start + length // 2
    logger.warning(
        "corrupt point %s firing (mode=%s) on %s [%d:+%d]",
        site, mode, path, start, length,
    )
    if mode == "truncate":
        os.truncate(path, mid)
        return True
    with open(path, "r+b") as fh:
        if mode == "flip":
            fh.seek(mid)
            b = fh.read(1)
            fh.seek(mid)
            fh.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")
        else:  # zero
            run = min(256, max(1, length // 3))
            fh.seek(start + length // 3)
            fh.write(b"\x00" * run)
        fh.flush()
        os.fsync(fh.fileno())
    return True


def resource_point(site: str, tenant: Optional[str] = None) -> None:
    """Hot-path hook for exhaustion sites. No-op (one dict probe)
    unless armed. Disk sites raise ``OSError(ENOSPC)``, ``alloc``
    raises ``MemoryError``, ``feed.latency`` sleeps and returns — the
    caller's normal error handling IS the behavior under test.

    When the armed spec names a tenant, only that tenant's traversals
    fire (and count): ``tenant`` is the caller's explicit attribution,
    falling back to the ambient ``CURRENT_TENANT`` contextvar at
    boundary sites where the request context is still live."""
    spec = _resource_armed.get(site)
    if spec is None:
        return
    want = spec[3] if len(spec) > 3 else None
    if want is not None:
        if tenant is None:
            # lazy import: faults must stay importable before runtime/
            from zipkin_tpu.runtime.tenant import CURRENT_TENANT
            tenant = CURRENT_TENANT.get()
        if tenant != want:
            return  # other tenants pass through, nth/count untouched
    if spec[0] > 1:
        spec[0] -= 1  # not yet at the nth traversal
        return
    if spec[1] > 0:
        spec[1] -= 1
        if spec[1] == 0:
            del _resource_armed[site]  # exhaustion cleared (space freed)
    if site == "feed.latency":
        logger.warning("resource fault %s firing (sleep %.1f ms)",
                       site, spec[2] * 1000.0)
        time.sleep(spec[2])
        return
    logger.warning("resource fault %s firing", site)
    if site == "alloc":
        raise MemoryError(f"injected allocation failure at {site}")
    raise OSError(errno.ENOSPC, f"injected ENOSPC at {site}")


def _arm_from_env() -> None:
    raw = os.environ.get(ENV_VAR)
    if raw:
        action = os.environ.get(ENV_ACTION, "kill").strip() or "kill"
        for spec in raw.split(","):
            spec = spec.strip()
            if not spec:
                continue
            site, _, nth = spec.partition(":")
            try:
                arm(site.strip(), int(nth) if nth.strip() else 1, action)
            except ValueError as e:
                # a typo'd env var must not brick a production boot
                logger.warning("ignoring %s=%r: %s", ENV_VAR, raw, e)
    raw = os.environ.get(ENV_CORRUPT)
    if raw:
        for spec in raw.split(","):
            spec = spec.strip()
            if not spec:
                continue
            parts = spec.split(":")
            try:
                arm_corrupt(
                    parts[0].strip(),
                    parts[1].strip() if len(parts) > 1 and parts[1].strip()
                    else "flip",
                    int(parts[2]) if len(parts) > 2 and parts[2].strip()
                    else 1,
                )
            except ValueError as e:
                logger.warning("ignoring %s=%r: %s", ENV_CORRUPT, raw, e)
    raw = os.environ.get(ENV_RESOURCE)
    if raw:
        try:
            lat_ms = float(os.environ.get(ENV_RESOURCE_LATENCY, "25"))
        except ValueError:
            lat_ms = 25.0
        for spec in raw.split(","):
            spec = spec.strip()
            if not spec:
                continue
            parts = spec.split(":")
            tenant = None
            pos = []
            for p in parts[1:]:
                p = p.strip()
                if p.startswith("tenant="):
                    tenant = p[len("tenant="):] or None
                elif p:
                    pos.append(p)
            try:
                arm_resource(
                    parts[0].strip(),
                    int(pos[0]) if len(pos) > 0 else 1,
                    int(pos[1]) if len(pos) > 1 else 1,
                    latency_ms=lat_ms,
                    tenant=tenant,
                )
            except ValueError as e:
                logger.warning("ignoring %s=%r: %s", ENV_RESOURCE, raw, e)


_arm_from_env()
