"""Internal helpers: hex/id codecs, trace reassembly, dependency linking."""
