"""Date/time bucketing helpers — the ``zipkin2/internal/DateUtil.java``
analog (SURVEY.md §2.1 internal-utils row).

The reference buckets retention by UTC day (daily ES indices
``zipkin*span-YYYY-MM-dd``, daily cassandra ``dependency`` rows keyed by
midnight); the TPU tier buckets by configurable minutes
(AggConfig.bucket_minutes / hist_slice_minutes). Both conventions meet
here: millisecond query parameters in, bucket indices out.
"""

from __future__ import annotations

from typing import List

DAY_MS = 86_400_000
MINUTE_MS = 60_000


def midnight_utc(epoch_ms: int) -> int:
    """Midnight UTC (ms) of the day containing ``epoch_ms`` — the
    reference's ``DateUtil.midnightUTC`` (floor, also for negatives)."""
    return (epoch_ms // DAY_MS) * DAY_MS


def epoch_days(end_ts_ms: int, lookback_ms: int) -> List[int]:
    """Midnights (ms) of every UTC day touched by [endTs - lookback,
    endTs] — the reference's ``DateUtil.epochDays``, which storage
    backends use to enumerate daily rollup rows to merge."""
    first = midnight_utc(max(end_ts_ms - lookback_ms, 0))
    last = midnight_utc(end_ts_ms)
    return list(range(first, last + DAY_MS, DAY_MS))


def epoch_minutes(epoch_ms: int) -> int:
    """Epoch minutes — the device tier's time unit (ring ``ts_min``,
    rollup/slice bucket inputs); clamped at 0. This is the single
    ms-to-minute conversion point for query windows (TpuStorage)."""
    return max(int(epoch_ms) // MINUTE_MS, 0)
