"""Aggregate traces into parent->child service dependency links.

Reference semantics: ``zipkin2/internal/DependencyLinker.java`` (SURVEY.md
§2.1, §3.5) — the computation the TPU tier accelerates. The host
implementation here is the **oracle**: the device path
(:mod:`zipkin_tpu.ops.linker`) must match its edge counts exactly
(BASELINE config[2]).

Linking rules (breadth-first over the reassembled tree):

1. A CLIENT span with children is skipped: the server half(s) below it
   report the link with better knowledge of the server's identity.
2. A span with no kind but both local+remote service names is treated as a
   CLIENT span (uninstrumented RPC convention).
3. SERVER/CONSUMER spans link remoteServiceName (the caller) -> local;
   a root SERVER span with no remote has no known parent -> no link.
4. CLIENT/PRODUCER spans link local -> remoteServiceName (the callee).
5. PRODUCER/CONSUMER (messaging) spans need both sides known — there is no
   tree walk through a broker.
6. For RPC spans, the nearest ancestor with a kind (the "RPC ancestor")
   resolves the parent: a SERVER span prefers its instrumented tree caller
   over its own ``ca`` address annotation; a CLIENT span missing a local
   service name inherits the ancestor's. A CLIENT span whose service
   *differs* from its RPC ancestor's implies an uninstrumented hop between
   them, and that ancestor->client link is backfilled (with no error).
7. An error is counted when the contributing span has an ``error`` tag.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from zipkin_tpu.internal.span_node import SpanNode, build_tree
from zipkin_tpu.model.span import DependencyLink, Kind, Span


class DependencyLinker:
    """Stateful accumulator: feed traces via :meth:`put_trace`, read with
    :meth:`link`."""

    def __init__(self) -> None:
        self._calls: Dict[Tuple[str, str], int] = {}
        self._errors: Dict[Tuple[str, str], int] = {}

    def put_trace(self, spans: Sequence[Span]) -> "DependencyLinker":
        root = build_tree(spans)
        if root is None:
            return self
        for node in root.traverse():
            span = node.span
            assert span is not None
            kind = span.kind
            local = span.local_service_name
            remote = span.remote_service_name

            # Rule 1: defer the client side of an RPC to its server half.
            if kind is Kind.CLIENT and node.children:
                continue

            # Rule 2: unknown kind with both sides known acts like a client.
            if kind is None:
                if local is not None and remote is not None:
                    kind = Kind.CLIENT
                else:
                    continue

            if kind in (Kind.SERVER, Kind.CONSUMER):
                child, parent = local, remote
                if node.parent is None and parent is None:
                    continue  # rule 3: root server with unknown caller
            elif kind in (Kind.CLIENT, Kind.PRODUCER):
                parent, child = local, remote
            else:  # pragma: no cover - exhaustive over Kind
                continue

            is_error = span.is_error
            if kind in (Kind.PRODUCER, Kind.CONSUMER):
                if parent is None or child is None:
                    continue  # rule 5
                self._add(parent, child, is_error)
                continue

            # Rule 6: resolve the parent via the nearest RPC ancestor. For a
            # SERVER span the tree ancestor (the instrumented caller) is
            # more reliable than the ca address annotation, so it wins.
            rpc_ancestor = _find_rpc_ancestor(node)
            if rpc_ancestor is not None:
                ancestor_name = rpc_ancestor.local_service_name
                if ancestor_name is not None:
                    # Rule 6b: a CLIENT span whose service differs from its
                    # RPC ancestor's implies an uninstrumented hop between
                    # them — backfill that link (error unknown, so none).
                    if (
                        kind is Kind.CLIENT
                        and local is not None
                        and ancestor_name != local
                    ):
                        self._add(ancestor_name, local, False)
                    if kind is Kind.SERVER or parent is None:
                        parent = ancestor_name

            if parent is None or child is None:
                continue
            self._add(parent, child, is_error)
        return self

    def put_links(self, links: Sequence[DependencyLink]) -> "DependencyLinker":
        """Merge pre-aggregated links (the daily-rollup read path)."""
        for link in links:
            key = (link.parent, link.child)
            self._calls[key] = self._calls.get(key, 0) + link.call_count
            self._errors[key] = self._errors.get(key, 0) + link.error_count
        return self

    def _add(self, parent: str, child: str, is_error: bool) -> None:
        key = (parent, child)
        self._calls[key] = self._calls.get(key, 0) + 1
        if is_error:
            self._errors[key] = self._errors.get(key, 0) + 1

    def link(self) -> List[DependencyLink]:
        return [
            DependencyLink(
                parent=parent,
                child=child,
                call_count=calls,
                error_count=self._errors.get((parent, child), 0),
            )
            for (parent, child), calls in self._calls.items()
        ]


def _find_rpc_ancestor(node: SpanNode) -> Optional[Span]:
    """Nearest ancestor span that has a kind (skipping local spans)."""
    ancestor = node.parent
    while ancestor is not None:
        span = ancestor.span
        if span is not None and span.kind is not None:
            return span
        ancestor = ancestor.parent
    return None


def link_traces(traces: Sequence[Sequence[Span]]) -> List[DependencyLink]:
    linker = DependencyLinker()
    for trace in traces:
        linker.put_trace(trace)
    return linker.link()
