"""Hex trace/span-id codecs and time bucketing.

Reference semantics: ``zipkin2/internal/HexCodec.java`` and
``zipkin2/internal/DateUtil.java`` (SURVEY.md §2.1).

Zipkin ids are lower-hex strings: span ids are 64-bit (16 chars), trace ids
are 64- or 128-bit (16 or 32 chars). Normalization left-pads with zeros to
the nearest of those widths and lowercases. ``lower_64`` extracts the low 64
bits — the basis both of non-strict trace-id matching and of boundary
sampling (``CollectorSampler``).
"""

from __future__ import annotations

from typing import List, Optional

_HEX = set("0123456789abcdef")

DAY_MS = 86_400_000


def normalize_trace_id(trace_id: str) -> str:
    """Validate + canonicalize a trace id to 16 or 32 lower-hex chars.

    Mirrors ``Span.normalizeTraceId``: 1..32 hex chars accepted; ids longer
    than 16 chars pad to 32, otherwise to 16. Raises ``ValueError`` on
    non-hex input, empty input, or all zeros.
    """
    if trace_id is None:
        raise ValueError("traceId is required")
    lowered = trace_id.lower()
    n = len(lowered)
    if n == 0 or n > 32:
        raise ValueError(f"traceId should be 1..32 hex characters: {trace_id!r}")
    if not set(lowered) <= _HEX:
        raise ValueError(f"traceId is not lower-hex: {trace_id!r}")
    width = 32 if n > 16 else 16
    padded = lowered.zfill(width)
    if padded.strip("0") == "":
        raise ValueError("traceId is all zeros")
    return padded


def normalize_span_id(span_id: str, *, name: str = "id") -> str:
    """Validate + canonicalize a 64-bit span id to 16 lower-hex chars."""
    if span_id is None:
        raise ValueError(f"{name} is required")
    lowered = span_id.lower()
    n = len(lowered)
    if n == 0 or n > 16:
        raise ValueError(f"{name} should be 1..16 hex characters: {span_id!r}")
    if not set(lowered) <= _HEX:
        raise ValueError(f"{name} is not lower-hex: {span_id!r}")
    padded = lowered.zfill(16)
    if padded == "0" * 16:
        raise ValueError(f"{name} is all zeros")
    return padded


def normalize_parent_id(parent_id: Optional[str]) -> Optional[str]:
    """Like :func:`normalize_span_id` but an all-zero / empty parent is None."""
    if parent_id is None or parent_id == "":
        return None
    lowered = parent_id.lower()
    if len(lowered) > 16 or not set(lowered) <= _HEX:
        raise ValueError(f"parentId should be 1..16 hex characters: {parent_id!r}")
    padded = lowered.zfill(16)
    if padded == "0" * 16:
        return None
    return padded


def lower_64(trace_id: str) -> int:
    """The low 64 bits of a normalized trace id, as an unsigned int."""
    return int(trace_id[-16:], 16)


def to_lower_hex(value: int, *, width: int = 16) -> str:
    """Unsigned int -> zero-padded lower-hex."""
    return format(value & ((1 << (4 * width)) - 1), f"0{width}x")


def midnight_utc(epoch_ms: int) -> int:
    """Floor an epoch-millis timestamp to its UTC day boundary.

    Reference: ``DateUtil.midnightUTC`` — the bucket key for daily dependency
    rollups and time-ring retention shards.
    """
    return epoch_ms - (epoch_ms % DAY_MS)


def epoch_minutes(epoch_ms: int) -> int:
    """Epoch minutes — the device tier's time unit (ring ``ts_min``,
    rollup/slice bucket inputs); clamped at 0. The single ms-to-minute
    conversion point for query windows (TpuStorage)."""
    return max(int(epoch_ms) // 60_000, 0)


def epoch_day_buckets(end_ts_ms: int, lookback_ms: int) -> List[int]:
    """All UTC-day bucket start times covering ``(end_ts - lookback, end_ts]``.

    Reference: ``DateUtil.epochDays`` — used by daily-rollup dependency reads.
    """
    if end_ts_ms <= 0:
        raise ValueError("endTs must be positive")
    if lookback_ms <= 0:
        raise ValueError("lookback must be positive")
    start = midnight_utc(max(end_ts_ms - lookback_ms, 0))
    end = midnight_utc(end_ts_ms)
    return list(range(start, end + 1, DAY_MS))
