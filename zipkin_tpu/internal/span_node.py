"""Trace reassembly: build a span tree from the spans of one trace.

Reference semantics: ``zipkin2/internal/SpanNode.java`` and
``zipkin2/internal/Trace.java`` (SURVEY.md §2.1). The builder tolerates
real-world dirt: missing parents (dangling spans attach to the root),
multiple roots (a synthetic root adopts them), mixed v1 shared spans (the
shared SERVER half of an RPC parents under the CLIENT half with the same id),
and duplicate reports (merged field-wise).
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence

from zipkin_tpu.model.span import Span, merge_spans


class SpanNode:
    """A node in the reassembled trace tree."""

    __slots__ = ("span", "parent", "children")

    def __init__(self, span: Optional[Span]) -> None:
        self.span = span  # None only for a synthetic root
        self.parent: Optional[SpanNode] = None
        self.children: List[SpanNode] = []

    def add_child(self, child: "SpanNode") -> None:
        child.parent = self
        self.children.append(child)

    def traverse(self) -> Iterator["SpanNode"]:
        """Breadth-first traversal (the order DependencyLinker relies on)."""
        queue = collections.deque([self])
        while queue:
            node = queue.popleft()
            if node.span is not None:
                yield node
            queue.extend(node.children)

    @property
    def is_synthetic_root(self) -> bool:
        return self.span is None


def build_tree(spans: Sequence[Span]) -> Optional[SpanNode]:
    """Assemble one trace's spans into a tree; returns the root (possibly
    synthetic) or None for empty input.

    Keying: a span is located by its id; the shared (server) half of an RPC
    shares its id with the client half, so shared spans key separately and
    the client half with the same id is their preferred parent. Children of
    a shared server span sent by downstream instrumentation reference the
    shared id too, and attach below the server half.
    """
    if not spans:
        return None

    # Merge duplicate reports of the same span identity first. The key must
    # match Span.key() (id, shared, service) — two spans reusing an id with
    # different services are distinct nodes, not duplicates.
    merged: Dict[tuple, Span] = {}
    for span in spans:
        key = (span.id, bool(span.shared), span.local_service_name)
        if key in merged:
            try:
                merged[key] = merge_spans(merged[key], span)
            except ValueError:
                # e.g. mixed 64/128-bit renditions under lenient trace ids:
                # keep the first report rather than failing the whole trace
                pass
        else:
            merged[key] = span

    nodes: Dict[tuple, SpanNode] = {
        key: SpanNode(span) for key, span in merged.items()
    }

    # Index the primary (non-shared) node per id for parent lookups.
    primary_by_id: Dict[str, SpanNode] = {}
    shared_by_id: Dict[str, List[SpanNode]] = {}
    for node in nodes.values():
        s = node.span
        assert s is not None
        if s.shared:
            shared_by_id.setdefault(s.id, []).append(node)
        else:
            # If duplicates (shouldn't happen post-merge), first wins.
            primary_by_id.setdefault(s.id, node)

    root: Optional[SpanNode] = None
    dangling: List[SpanNode] = []

    for node in nodes.values():
        s = node.span
        assert s is not None
        if s.shared:
            # Shared server half: parent is the client half with the same id,
            # else fall back to its parentId.
            parent = primary_by_id.get(s.id)
            if parent is not None and parent is not node:
                parent.add_child(node)
                continue
            if s.parent_id is not None and s.parent_id in primary_by_id:
                primary_by_id[s.parent_id].add_child(node)
                continue
            dangling.append(node)
        elif s.parent_id is None:
            if root is None:
                root = node
            else:
                dangling.append(node)
        else:
            parent = _choose_parent(
                s, primary_by_id.get(s.parent_id), shared_by_id.get(s.parent_id)
            )
            if parent is not None and parent is not node:
                parent.add_child(node)
            else:
                dangling.append(node)

    if root is None and len(dangling) == 1 and not dangling[0].children:
        return dangling[0]
    if root is not None and not dangling:
        return root
    synthetic = SpanNode(None)
    if root is not None:
        synthetic.add_child(root)
    for node in dangling:
        synthetic.add_child(node)
    # A synthetic root with a single child is just that child.
    if len(synthetic.children) == 1:
        only = synthetic.children[0]
        only.parent = None
        return only
    return synthetic


def _choose_parent(
    child: Span,
    primary: Optional[SpanNode],
    shared: Optional[List[SpanNode]],
) -> Optional[SpanNode]:
    """Pick which half of an RPC a child span nests under.

    When the parent id was an RPC split into a client half and a shared
    server half, work done downstream belongs to the server's process — so
    prefer the half whose service matches the child's, then the server half.
    Mirrors the endpoint-aware parent matching in ``SpanNode.Builder``.
    """
    service = child.local_service_name
    if shared:
        for node in shared:
            if node.span is not None and node.span.local_service_name == service:
                return node
    if (
        primary is not None
        and primary.span is not None
        and primary.span.local_service_name == service
    ):
        return primary
    if shared:
        return shared[0]
    return primary


def merge_trace(spans: Sequence[Span]) -> List[Span]:
    """De-duplicate a trace's spans (same identity merged field-wise) and
    order them for presentation: by timestamp, then id, shared halves after
    their client halves.

    Reference: ``zipkin2/internal/Trace.java#merge``, including its rendition
    unification: when both a 128-bit and a 64-bit rendition of the trace id
    appear (lenient trace-id mode during instrumentation migrations), 64-bit
    spans are rewritten to the 128-bit form before merging, so duplicate
    reports of one span collapse instead of surviving under two ids.
    """
    tid128: Dict[str, str] = {}
    for span in spans:
        if len(span.trace_id) == 32:
            tid128.setdefault(span.trace_id[16:], span.trace_id)
    if tid128:
        spans = [
            dataclasses.replace(s, trace_id=tid128[s.trace_id])
            if len(s.trace_id) == 16 and s.trace_id in tid128
            else s
            for s in spans
        ]
    merged: Dict[tuple, Span] = {}
    for span in spans:
        key = span.key()
        if key in merged:
            merged[key] = merge_spans(merged[key], span)
        else:
            merged[key] = span
    return sorted(
        merged.values(),
        key=lambda s: (s.timestamp_as_long() or 2**63, s.id, bool(s.shared)),
    )
