"""ZT-lint: repo-wide static analysis of the TPU invariants.

The invariants this system's performance rests on — one device→host
transfer per query, no serving-time recompiles, lock-coherent shared
state, donation discipline, no stray device syncs — are exactly the
ones a reviewer cannot reliably re-check by hand every round. This
package makes them mechanical: an AST checker framework (core.py), a
whole-program call-graph engine (callgraph.py — qualified-name
resolution, bounded-depth reachability, cross-module taint summaries),
a device-taint analysis layered on it (taint.py), fourteen rules
grounded in real past regressions (checkers/), inline suppression
pragmas with mandatory justifications, baselines, and a CLI
(``python -m zipkin_tpu.lint``, ``--format json``/``--stats``).
tests/test_lint_clean.py runs the full tree through it in tier-1, so
every future PR is gated.

Public API: :func:`zipkin_tpu.lint.core.run_paths` and the
:class:`~zipkin_tpu.lint.core.Finding` dataclass; see ARCHITECTURE.md
"Static analysis" for the rule catalog and how to add a checker.

Import note: nothing here imports jax/numpy — the linter parses source,
it never executes it, so it runs in any stdlib-only context.
"""

from zipkin_tpu.lint.core import (  # noqa: F401
    Checker,
    Finding,
    Module,
    RunResult,
    all_checkers,
    load_baseline,
    register,
    run_paths,
    write_baseline,
)
