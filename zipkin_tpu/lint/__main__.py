"""``python -m zipkin_tpu.lint`` entry point."""

import sys

from zipkin_tpu.lint.cli import main

sys.exit(main())
