"""Whole-program call graph: qualified-name resolution + bounded reach.

Until ISSUE 17 every reachability walk in ZT-lint was function-local
and name-keyed: one flat ``{bare name: def}`` map per module, so two
same-named functions collided (PR 15 had to rename ``_disk_query``'s
nested ``fetch`` just to dodge the windowed walk) and no invariant was
checked more than one module deep. This module is the shared engine
those walks now ride on:

- **Qualified names.** Every def gets a dotted qualname mirroring
  Python's own scoping: ``pkg.mod.func``, ``pkg.mod.Class.method``,
  ``pkg.mod.outer.<locals>.inner``. Two same-named defs can no longer
  collide, because edges are keyed by qualname, not bare name.

- **Resolution, most-precise first.** A bare-name call resolves
  LEXICALLY (enclosing functions' nested defs, then module scope, then
  ``from x import f`` symbols) — exactly Python's rules, which is what
  deletes the collision class: a nested def is only reachable from the
  scope that can actually see it. ``self.m()`` / ``cls.m()`` resolves
  against the enclosing class (single-inheritance bases included when
  they live in the program). ``alias.attr(...)`` chains resolve through
  the import table (``import a.b.c``, ``from a.b import c as d``).
  Decorated defs resolve like undecorated ones — a ``functools.wraps``
  wrapper changes the runtime object, not the source-level callee.

- **Conservative fallback, bounded.** An attribute call on an unknown
  receiver (``obj.m()``) can't be typed without running the code, so it
  falls back to name-keyed candidates — but only (a) top-level defs and
  class methods in the SAME module (the old ZT07/ZT10 posture:
  over-approximate rather than miss a helper) and (b) a cross-module
  method of that name when exactly ONE class among the caller's
  imported modules defines it (unique ⇒ unambiguous). Nested
  ``<locals>`` defs are never fallback candidates — they aren't
  addressable as attributes, and exempting them is precisely what makes
  the PR 15 collision impossible to reintroduce. Fallback edges carry
  ``resolved=False`` so precision-sensitive rules (ZT08 traced-reach,
  taint summaries) can ignore them while fence rules (ZT07/ZT13) keep
  the over-approximation.

- **Bounded-depth reachability** (:meth:`CallGraph.reach`) with cycle
  tolerance and predecessor chains for ``via f() → g()`` messages;
  ``DEFAULT_DEPTH`` is the "full interprocedural depth" the ZT13
  acceptance bar refers to.

- **Cross-module taint summaries** (:meth:`CallGraph.returns_tainted`):
  does a function return a device-tainted value? Computed lazily over
  resolved edges with memoization and a cycle guard, layered over
  :mod:`zipkin_tpu.lint.taint`'s per-function dataflow so ZT01/ZT02 can
  see a device pull hiding behind a cross-module helper call.

The graph is built ONCE per lint run (``core.run_paths``) and shared by
every rule; modules are parse-cached by mtime, so re-lints only re-read
what changed.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# "Full interprocedural depth": deep enough that no real call chain in
# the repo hits the cutoff (the longest shipped chain is < 10 frames),
# bounded so a pathological cycle-free blowup cannot hang the linter.
DEFAULT_DEPTH = 24

_FUNC_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef)


def module_qualname(rel: str) -> str:
    """``zipkin_tpu/tpu/store.py`` → ``zipkin_tpu.tpu.store``;
    package ``__init__.py`` files take the package's own name."""
    name = rel[:-3] if rel.endswith(".py") else rel
    if name.endswith("/__init__"):
        name = name[: -len("/__init__")]
    return name.replace("/", ".")


class FunctionInfo:
    """One def in the program (module function, method, or nested)."""

    __slots__ = ("qual", "name", "module_rel", "module_qual", "node",
                 "cls", "marker_lines")

    def __init__(self, qual, name, module_rel, module_qual, node, cls):
        self.qual = qual
        self.name = name
        self.module_rel = module_rel
        self.module_qual = module_qual
        self.node = node
        self.cls = cls  # enclosing class name or None


class _ModuleIndex:
    """Per-module name tables the resolver consults."""

    __slots__ = ("module", "qual", "top_funcs", "classes", "bases",
                 "imports_mod", "imports_sym", "imported_quals")

    def __init__(self, module, qual):
        self.module = module
        self.qual = qual
        self.top_funcs: Dict[str, str] = {}       # bare -> qualname
        self.classes: Dict[str, Dict[str, str]] = {}   # cls -> meth -> qual
        self.bases: Dict[str, List[str]] = {}     # cls -> base name list
        self.imports_mod: Dict[str, str] = {}     # alias -> module qual
        self.imports_sym: Dict[str, str] = {}     # alias -> symbol qual
        self.imported_quals: Set[str] = set()     # module quals imported


class CallGraph:
    """The program: every parsed module, indexed and edge-connected."""

    def __init__(self, modules: Sequence) -> None:
        self.modules = list(modules)
        self.functions: Dict[str, FunctionInfo] = {}
        self._by_node: Dict[int, FunctionInfo] = {}   # id(def node) -> info
        self._index: Dict[str, _ModuleIndex] = {}     # module qual -> index
        self._mod_by_rel: Dict[str, object] = {}
        # adjacency: caller qual -> [(callee qual, resolved)]
        self.edges: Dict[str, List[Tuple[str, bool]]] = {}
        # per-call resolution: id(Call node) -> [(callee qual, resolved)]
        self._call_targets: Dict[int, List[Tuple[str, bool]]] = {}
        # bare method/function name -> [quals] (no <locals> entries)
        self._by_bare: Dict[str, List[str]] = {}
        self._taint_memo: Dict[str, bool] = {}
        for m in self.modules:
            self._register_module(m)
        for m in self.modules:
            self._build_edges(m)

    # -- registration -----------------------------------------------------

    def _register_module(self, module) -> None:
        qual = module_qualname(module.rel)
        idx = _ModuleIndex(module, qual)
        self._index[qual] = idx
        self._mod_by_rel[module.rel] = module
        for node in module.tree.body:
            self._register_imports(idx, node)
        self._register_scope(idx, module, module.tree.body, qual, None)
        # conditional / function-local imports still bind module aliases
        for node in ast.walk(module.tree):
            self._register_imports(idx, node)

    def _register_imports(self, idx: _ModuleIndex, node: ast.AST) -> None:
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    idx.imports_mod[a.asname] = a.name
                else:
                    # ``import a.b.c`` binds root ``a``; the resolver
                    # re-joins the full dotted chain at the call site
                    idx.imports_mod[a.name.split(".")[0]] = \
                        a.name.split(".")[0]
                idx.imported_quals.add(a.name)
        elif isinstance(node, ast.ImportFrom) and node.module:
            base = node.module
            if node.level:  # relative import: anchor at this package
                pkg = idx.qual.rsplit(".", node.level)[0]
                base = f"{pkg}.{node.module}" if node.module else pkg
            for a in node.names:
                bound = a.asname or a.name
                target = f"{base}.{a.name}"
                # ``from a.b import c`` may bind a submodule or a symbol;
                # record both readings, module table wins at resolve time
                idx.imports_sym[bound] = target
                idx.imports_mod.setdefault(bound, target)
                idx.imported_quals.add(base)
                idx.imported_quals.add(target)

    def _register_scope(self, idx, module, body, prefix, cls) -> None:
        for node in body:
            if isinstance(node, _FUNC_KINDS):
                qual = f"{prefix}.{node.name}"
                info = FunctionInfo(qual, node.name, module.rel, idx.qual,
                                    node, cls)
                self.functions[qual] = info
                self._by_node[id(node)] = info
                if cls is None and prefix == idx.qual:
                    idx.top_funcs[node.name] = qual
                if cls is not None and "<locals>" not in prefix:
                    idx.classes.setdefault(cls, {})[node.name] = qual
                if "<locals>" not in qual:
                    self._by_bare.setdefault(node.name, []).append(qual)
                self._register_scope(
                    idx, module, node.body, f"{qual}.<locals>", None
                )
            elif isinstance(node, ast.ClassDef):
                idx.classes.setdefault(node.name, {})
                idx.bases[node.name] = [
                    b.id if isinstance(b, ast.Name)
                    else (b.attr if isinstance(b, ast.Attribute) else "")
                    for b in node.bases
                ]
                self._register_scope(
                    idx, module, node.body, f"{prefix}.{node.name}",
                    node.name,
                )
            elif isinstance(node, (ast.If, ast.Try, ast.With,
                                   ast.AsyncWith)):
                inner = list(getattr(node, "body", []))
                inner += list(getattr(node, "orelse", []))
                inner += list(getattr(node, "finalbody", []))
                for hs in getattr(node, "handlers", []):
                    inner += hs.body
                self._register_scope(idx, module, inner, prefix, cls)

    # -- edge building ----------------------------------------------------

    def _build_edges(self, module) -> None:
        idx = self._index[module_qualname(module.rel)]
        for info in list(self.functions.values()):
            if info.module_rel != module.rel:
                continue
            out = self.edges.setdefault(info.qual, [])
            own_nested = set()
            for inner in ast.walk(info.node):
                if inner is not info.node and isinstance(inner, _FUNC_KINDS):
                    own_nested.update(
                        id(n) for n in ast.walk(inner) if n is not inner
                    )
                    own_nested.add(id(inner))
            for call in ast.walk(info.node):
                if not isinstance(call, ast.Call) or id(call) in own_nested:
                    continue  # nested defs own their calls
                targets = self._resolve_call(idx, info, call)
                if targets:
                    self._call_targets[id(call)] = targets
                    out.extend(targets)

    def _resolve_call(self, idx, info, call) -> List[Tuple[str, bool]]:
        f = call.func
        if isinstance(f, ast.Name):
            qual = self._resolve_bare(idx, info, f.id)
            return [(qual, True)] if qual else []
        if isinstance(f, ast.Attribute):
            return self._resolve_attr(idx, info, f)
        return []

    def _resolve_bare(self, idx, info, name) -> Optional[str]:
        """Python's lexical rules: enclosing functions' nested defs,
        module scope, then ``from x import f`` symbols. No name-keyed
        fallback — a bare name the scope can't see is a builtin."""
        prefix = info.qual
        while prefix:
            nested = f"{prefix}.<locals>.{name}"
            if nested in self.functions:
                return nested
            if "." not in prefix:
                break
            parent = prefix.rsplit(".<locals>.", 1)
            prefix = parent[0] if len(parent) == 2 else ""
        if name in idx.top_funcs:
            return idx.top_funcs[name]
        sym = idx.imports_sym.get(name)
        if sym and sym in self.functions:
            return sym
        return None

    def _class_method(self, idx, cls, meth, seen=None) -> Optional[str]:
        """``cls.meth`` with single-inheritance base walk (cycle-safe)."""
        seen = seen or set()
        if cls in seen or cls not in idx.classes:
            return None
        seen.add(cls)
        qual = idx.classes[cls].get(meth)
        if qual:
            return qual
        for base in idx.bases.get(cls, ()):
            hit = self._class_method(idx, base, meth, seen)
            if hit:
                return hit
            # base imported from another module: follow the symbol
            sym = idx.imports_sym.get(base)
            if sym:
                bidx = self._index.get(sym.rsplit(".", 1)[0])
                bname = sym.rsplit(".", 1)[1]
                if bidx is not None:
                    hit = self._class_method(bidx, bname, meth, seen)
                    if hit:
                        return hit
        return None

    def _resolve_attr(self, idx, info, f) -> List[Tuple[str, bool]]:
        parts = _attr_chain(f)
        meth = f.attr
        if parts is not None:
            root = parts[0]
            # self.m() / cls.m(): the enclosing class, bases included
            if root in ("self", "cls") and len(parts) == 2 and info.cls:
                qual = self._class_method(idx, info.cls, meth)
                if qual:
                    return [(qual, True)]
            # alias chains through the import table: mod.f, pkg.mod.f,
            # mod.Class.m — longest dotted prefix that names a module
            expanded = None
            if root in idx.imports_mod:
                expanded = [idx.imports_mod[root]] + parts[1:]
            elif root in idx.imports_sym:
                expanded = idx.imports_sym[root].split(".") + parts[1:]
            if expanded:
                for cut in range(len(expanded) - 1, 0, -1):
                    mod_qual = ".".join(expanded[:cut])
                    midx = self._index.get(mod_qual)
                    if midx is None:
                        continue
                    rest = expanded[cut:]
                    if len(rest) == 1 and rest[0] in midx.top_funcs:
                        return [(midx.top_funcs[rest[0]], True)]
                    if len(rest) == 2:
                        qual = self._class_method(midx, rest[0], rest[1])
                        if qual:
                            return [(qual, True)]
                    break
        # unknown receiver: conservative name-keyed fallback (module
        # docstring) — same-module defs + a uniquely-named imported
        # method; never nested <locals> defs
        out: List[Tuple[str, bool]] = []
        if meth in idx.top_funcs:
            out.append((idx.top_funcs[meth], False))
        for methods in idx.classes.values():
            if meth in methods:
                out.append((methods[meth], False))
        if not out:
            cross = [
                q for q in self._by_bare.get(meth, ())
                if self.functions[q].module_qual in idx.imported_quals
                or any(
                    iq.startswith(self.functions[q].module_qual + ".")
                    or self.functions[q].module_qual.startswith(iq + ".")
                    or iq == self.functions[q].module_qual
                    for iq in idx.imported_quals
                )
            ]
            if len(cross) == 1:
                out.append((cross[0], False))
        return out

    # -- queries -----------------------------------------------------------

    def info_for_node(self, node) -> Optional[FunctionInfo]:
        return self._by_node.get(id(node))

    def module_for(self, rel: str):
        """The parsed Module for a repo-relative path (None if absent)."""
        return self._mod_by_rel.get(rel)

    def qual_of(self, node) -> Optional[str]:
        info = self._by_node.get(id(node))
        return info.qual if info else None

    def callees_of_call(self, call) -> List[Tuple[str, bool]]:
        """Resolution of ONE Call node (empty if unresolvable)."""
        return self._call_targets.get(id(call), [])

    def callers_of(self, qual: str) -> List[str]:
        """Caller quals with any edge (resolved or fallback) into qual."""
        return [
            c for c, outs in self.edges.items()
            if any(t == qual for t, _ in outs)
        ]

    def call_sites_of(self, qual: str) -> List[Tuple[str, ast.Call]]:
        """(caller qual, Call node) pairs targeting ``qual``."""
        out = []
        for caller, outs in self.edges.items():
            if not any(t == qual for t, _ in outs):
                continue
            info = self.functions.get(caller)
            if info is None:
                continue
            for call in ast.walk(info.node):
                if isinstance(call, ast.Call) and any(
                    t == qual
                    for t, _ in self._call_targets.get(id(call), ())
                ):
                    out.append((caller, call))
        return out

    def reach(
        self,
        roots: Iterable[str],
        depth: int = DEFAULT_DEPTH,
        resolved_only: bool = False,
        same_module: bool = False,
    ) -> Dict[str, Tuple[str, int, Optional[str]]]:
        """BFS closure: qual → (root qual, depth, predecessor qual).

        Cycle-tolerant (visited set), bounded by ``depth`` hops.
        ``resolved_only`` drops name-keyed fallback edges;
        ``same_module`` prunes edges that leave the root's module (the
        ZT10 posture — cross-module depth is ZT13's job)."""
        out: Dict[str, Tuple[str, int, Optional[str]]] = {}
        frontier: List[Tuple[str, str, int, Optional[str]]] = [
            (q, q, 0, None) for q in roots if q in self.functions
        ]
        while frontier:
            nxt: List[Tuple[str, str, int, Optional[str]]] = []
            for qual, root, d, pred in frontier:
                if qual in out:
                    continue
                out[qual] = (root, d, pred)
                if d >= depth:
                    continue
                root_mod = self.functions[root].module_rel
                for callee, resolved in self.edges.get(qual, ()):
                    if callee in out or callee not in self.functions:
                        continue
                    if resolved_only and not resolved:
                        continue
                    if (
                        same_module
                        and self.functions[callee].module_rel != root_mod
                    ):
                        continue
                    nxt.append((callee, root, d + 1, qual))
            frontier = nxt
        return out

    def via_chain(self, reached, qual: str, limit: int = 4) -> str:
        """Human-readable ``via a() → b()`` suffix for findings."""
        names = []
        cur = qual
        while cur is not None and len(names) < limit:
            root, _d, pred = reached[cur]
            if pred is None:
                break
            names.append(self.functions[cur].name + "()")
            cur = pred
        if not names:
            return ""
        return " (via " + " → ".join(reversed(names)) + ")"

    # -- cross-module taint summaries --------------------------------------

    def returns_tainted(self, qual: str, _depth: int = 0) -> bool:
        """Does ``qual`` return a device-tainted value? Lazy, memoized,
        cycle-safe (an in-progress query answers False — the fixpoint
        seed), following resolved edges only."""
        if qual in self._taint_memo:
            return self._taint_memo[qual]
        info = self.functions.get(qual)
        if info is None or _depth > 8:
            return False
        self._taint_memo[qual] = False  # cycle guard / fixpoint seed
        from zipkin_tpu.lint.taint import FunctionTaint

        def resolver(call: ast.Call) -> bool:
            return any(
                resolved and self.returns_tainted(t, _depth + 1)
                for t, resolved in self.callees_of_call(call)
            )

        taint = FunctionTaint(info.node, call_resolver=resolver)
        verdict = False
        for node in ast.walk(info.node):
            if isinstance(node, ast.Return) and node.value is not None:
                if taint.is_tainted(node.value):
                    verdict = True
                    break
        self._taint_memo[qual] = verdict
        return verdict

    @property
    def n_edges(self) -> int:
        return sum(len(v) for v in self.edges.values())


def _attr_chain(node: ast.AST) -> Optional[List[str]]:
    """``a.b.c`` → ["a","b","c"]; None when any link isn't a plain
    Name/Attribute (a call or subscript in the chain)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None
