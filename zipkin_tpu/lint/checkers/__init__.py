"""ZT-lint checkers. Importing this package registers every rule.

Rule catalog (grounded in real past regressions — see ARCHITECTURE.md
"Static analysis" for the full story per rule):

- ZT00 suppression hygiene (meta): a ``zt-lint: disable`` pragma with no
  justification text.
- ZT01 host-transfer chokepoint: device→host coercion outside
  ``readpack``.
- ZT02 multi-pull shapes: ≥2 host pulls in one function, or
  multi-``np.asarray`` return tuples.
- ZT03 jit-recompile hazards: ``jax.jit`` constructed per call/iteration;
  varying Python scalars passed positionally to jitted callables.
- ZT04 lock discipline: attributes written under a lock in one method
  but lock-free in another.
- ZT05 donation misuse: a donated argument read after the donating call.
- ZT06 blocking sync: ``block_until_ready`` on serving paths.
- ZT07 fresh-read ring sorts: sort/scan-family ops (or calls back into
  the from-scratch ctx rebuilders) reachable from fresh-read
  entrypoints — only the since-rollup delta segment may be sorted at
  query time.
- ZT08 obs stage discipline: ``obs.record`` reachable from
  device-traced code (host instrumentation runs once at trace time),
  or a stage argument outside the closed taxonomy in
  ``obs/stages.py``.
- ZT09 dispatch-critical loops: Python ``for``/``while``/comprehensions
  inside functions marked ``# zt-dispatch-critical`` — the ingest
  fan-out's single dispatch core must do O(chunks)+O(new-vocab) work,
  never O(spans); justified non-per-span loops carry ZT09 pragmas.
- ZT10 mirror-served lock acquires: aggregator-lock acquisition (bare
  ``.lock`` holds, or calls into known lock-taking helpers) reachable
  from functions marked ``# zt-mirror-served`` within the module — the
  epoch-published read mirror's serve path must never re-queue readers
  on the lock (cross-module chains are ZT13's).
- ZT11 seqlock discipline: writes to registered shm seqlock regions
  (ring slot headers, mirror epoch, critpath ledger slots, recorder
  histograms) must sit inside an odd/even generation-stamp bracket on
  the SAME generation word; gen-aware readers must re-read the
  generation after copying.
- ZT12 durability commit: in ``wal``/``snapshot``/``timetier``/
  ``archive``, restore-readable files flow through the
  tmp+fsync+rename+dir-fsync chokepoints — a bare write-mode ``open``
  or an ``os.replace`` without fsync on its path is a finding.
- ZT13 reader isolation: aggregator-lock / ``InstrumentedRLock``
  acquires statically unreachable — at full interprocedural, cross-
  module depth over the whole-program call graph — from
  ``# zt-mirror-served`` and ``# zt-reader-process`` entrypoints (the
  static gate for the ROADMAP's multi-process read front end).
- ZT14 tenant admission: every ``# zt-ingest-boundary`` wire
  entrypoint must reach a ``# zt-tenant-admission`` chokepoint in the
  whole-program call graph (callable-reference hops like
  ``asyncio.to_thread(f, ...)`` included) — a transport that hands
  bytes to the fan-out tier without traversing admission silently
  breaks tenant isolation (ISSUE 18).

ZT07/ZT08/ZT13/ZT14 walk the shared whole-program call graph built once
per run (``lint/callgraph.py``: qualified-name resolution, bounded-depth
reachability, cross-module taint summaries); ZT01/ZT02/ZT04/ZT09/ZT10
consult it per module for summaries, caller proofs, and callee hops.
"""

from zipkin_tpu.lint.checkers import (  # noqa: F401 - import registers
    blocking,
    dispatchloop,
    donation,
    durability,
    freshread,
    locks,
    mirrorread,
    obsstage,
    pragmas,
    readeriso,
    recompile,
    seqlock,
    tenantadm,
    transfers,
)
