"""ZT06 — blocking sync on serving paths.

``block_until_ready()`` stalls the calling thread until every queued
device computation retires. In benchmarks and evals that is the point
(wall-clock honesty); on a serving path it serializes the async ingest
pipeline behind the device and hands the transport's fixed round trip
to the caller. The ingest/read planes are designed to overlap host and
device work (AsyncIngestFeeder's pipeline stages, the lock-scoped
dispatch-then-pull split in state_clone) — a stray sync undoes that
silently.

Rule: any ``*.block_until_ready()`` (or ``jax.block_until_ready(x)``)
call in library code — paths under ``benchmarks/``, ``evals/`` and
``tests/`` are exempt, as is the body of a method itself NAMED
``block_until_ready`` (that is the deliberate sync seam the exempt
callers use). Legitimate library blockers (health checks, drain seams,
warm-up) carry a scoped pragma naming why blocking is the contract.
"""

from __future__ import annotations

import ast

from zipkin_tpu.lint.core import Checker, Module, register

_FUNC_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef)
_EXEMPT_PATH_PARTS = ("benchmarks/", "evals/", "tests/", "test_")


@register
class BlockingSync(Checker):
    rule = "ZT06"
    severity = "error"
    name = "blocking-sync"
    doc = "block_until_ready outside benchmarks/evals/tests"
    hint = (
        "let the async pipeline overlap host and device work; if "
        "blocking IS the contract (drain/health/warm-up), suppress on "
        "the def line saying so"
    )

    def check(self, module: Module):
        if any(part in module.rel for part in _EXEMPT_PATH_PARTS):
            return
        for node in ast.walk(module.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready"
            ):
                continue
            fn = next(iter(module.enclosing(node, _FUNC_KINDS)), None)
            if fn is not None and fn.name == "block_until_ready":
                continue  # the sync seam's own definition
            where = f" in {fn.name}()" if fn is not None else ""
            yield self.found(
                module,
                node,
                f"block_until_ready{where} — serving-path host stall "
                "until the device queue retires",
            )
