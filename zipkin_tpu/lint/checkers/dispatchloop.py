"""ZT09 — dispatch-core critical sections stay free of per-span loops.

The ingest fan-out tier (tpu/mp_ingest.py) exists because parse/pack is
Python-speed work: N workers each own it, and ONE dispatch core applies
their output to the device. The whole pool's ceiling is therefore the
dispatch core's per-payload cost — which must be O(new vocab entries) +
O(chunks), never O(spans). A per-span Python ``for``/``while``/
comprehension slipping into that section (the historical shape: "just
iterate the record rows to remap them") silently caps N workers at one
interpreter's speed, and no unit test notices because correctness is
unaffected.

Functions opt in by carrying a ``# zt-dispatch-critical: <reason>``
marker comment on their ``def`` header (any header line up to the start
of the body, so multi-line signatures work). Inside a marked function
every loop or comprehension is flagged; loops whose trip count is
provably NOT per-span carry a standard ``zt-lint: disable=ZT09`` pragma
whose justification says what the trip count actually is (per new
string, per chunk, ...) — the pragma audit IS the documentation that
the critical section stayed vectorized.
"""

from __future__ import annotations

import ast
import re

from zipkin_tpu.lint.core import Checker, Module, register

_FUNC_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef)
_LOOP_KINDS = (ast.For, ast.AsyncFor, ast.While)
_COMP_KINDS = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)

MARKER_RE = re.compile(r"#\s*zt-dispatch-critical\b(?P<rest>.*)$")


def _marker(module: Module, fn: ast.AST):
    """The zt-dispatch-critical marker on fn's header lines, if any.

    The header is everything from the ``def`` line up to (not
    including) the first body statement's line — the marker may trail
    the closing paren of a multi-line signature."""
    end = fn.body[0].lineno if fn.body else fn.lineno + 1
    for line_no in range(fn.lineno, end):
        m = MARKER_RE.search(module.line_text(line_no))
        if m:
            return line_no, m.group("rest")
    return None


@register
class DispatchCriticalLoops(Checker):
    rule = "ZT09"
    severity = "error"
    name = "dispatch-critical-loops"
    doc = (
        "Python loops/comprehensions inside functions marked "
        "zt-dispatch-critical (the single-threaded dispatch core of the "
        "ingest fan-out)"
    )
    hint = (
        "vectorize over the batch (numpy LUT / fancy indexing), or if "
        "the trip count is per-chunk/per-new-vocab-entry — not per-span "
        "— justify it with a zt-lint: disable=ZT09 pragma saying so"
    )

    def check(self, module: Module):
        for fn in ast.walk(module.tree):
            if not isinstance(fn, _FUNC_KINDS):
                continue
            marked = _marker(module, fn)
            if marked is None:
                continue
            line_no, rest = marked
            if not rest.lstrip().startswith(":") or not rest.lstrip(": ").strip():
                yield self.found(
                    module, fn,
                    "zt-dispatch-critical marker without a reason — say "
                    "WHY this function is on the dispatch core's critical "
                    "path (# zt-dispatch-critical: <reason>)",
                )
            for node in ast.walk(fn):
                if isinstance(node, _LOOP_KINDS):
                    shape = "loop"
                elif isinstance(node, _COMP_KINDS):
                    shape = "comprehension"
                else:
                    continue
                # anchor at the enclosing STATEMENT: a comprehension's
                # own line is mid-expression, where no pragma can sit —
                # the suppression audit lives on the statement line
                anchor = node
                while anchor is not None and not isinstance(anchor, ast.stmt):
                    anchor = module.parents.get(anchor)
                yield self.found(
                    module, anchor or node,
                    f"Python {shape} inside dispatch-critical "
                    f"{fn.name}() — a per-span trip count here caps "
                    "every parse worker at one interpreter's speed",
                )
