"""ZT09 — dispatch-core critical sections stay free of per-span loops.

The ingest fan-out tier (tpu/mp_ingest.py) exists because parse/pack is
Python-speed work: N workers each own it, and ONE dispatch core applies
their output to the device. The whole pool's ceiling is therefore the
dispatch core's per-payload cost — which must be O(new vocab entries) +
O(chunks), never O(spans). A per-span Python ``for``/``while``/
comprehension slipping into that section (the historical shape: "just
iterate the record rows to remap them") silently caps N workers at one
interpreter's speed, and no unit test notices because correctness is
unaffected.

Functions opt in by carrying a ``# zt-dispatch-critical: <reason>``
marker comment on their ``def`` header (any header line up to the start
of the body, so multi-line signatures work). Inside a marked function
every loop or comprehension is flagged; loops whose trip count is
provably NOT per-span carry a standard ``zt-lint: disable=ZT09`` pragma
whose justification says what the trip count actually is (per new
string, per chunk, ...) — the pragma audit IS the documentation that
the critical section stayed vectorized.

The marker's audit used to stop at the function boundary: hide the
per-span loop in a helper and call the helper, and ZT09 was blind.
With the call graph the rule is compositional one hop out, cross-
module: a call from a marked function that RESOLVES to an unmarked,
loop-bearing callee is flagged at the CALL SITE. The fix is to mark
the callee (putting its loops under this same audit) or to pragma the
call with the trip-count justification. Hops beyond the first are
covered inductively — marking the callee makes ITS calls audited.
"""

from __future__ import annotations

import ast
import re

from zipkin_tpu.lint.core import Checker, Module, register

_FUNC_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef)
_LOOP_KINDS = (ast.For, ast.AsyncFor, ast.While)
_COMP_KINDS = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)

MARKER_RE = re.compile(r"#\s*zt-dispatch-critical\b(?P<rest>.*)$")


def _has_own_loop(fn: ast.AST) -> bool:
    """A loop/comprehension in fn's own body (nested defs excluded —
    they are separate functions with their own edges)."""
    nested = set()
    for n in ast.walk(fn):
        if isinstance(n, _FUNC_KINDS) and n is not fn:
            nested.update(id(x) for x in ast.walk(n))
    return any(
        isinstance(n, _LOOP_KINDS + _COMP_KINDS) and id(n) not in nested
        for n in ast.walk(fn)
    )


def _marker(module: Module, fn: ast.AST):
    """The zt-dispatch-critical marker on fn's header lines, if any.

    The header is everything from the ``def`` line up to (not
    including) the first body statement's line — the marker may trail
    the closing paren of a multi-line signature."""
    end = fn.body[0].lineno if fn.body else fn.lineno + 1
    for line_no in range(fn.lineno, end):
        m = MARKER_RE.search(module.line_text(line_no))
        if m:
            return line_no, m.group("rest")
    return None


@register
class DispatchCriticalLoops(Checker):
    rule = "ZT09"
    severity = "error"
    name = "dispatch-critical-loops"
    doc = (
        "Python loops/comprehensions inside functions marked "
        "zt-dispatch-critical (the single-threaded dispatch core of the "
        "ingest fan-out)"
    )
    hint = (
        "vectorize over the batch (numpy LUT / fancy indexing), or if "
        "the trip count is per-chunk/per-new-vocab-entry — not per-span "
        "— justify it with a zt-lint: disable=ZT09 pragma saying so"
    )

    def check(self, module: Module):
        for fn in ast.walk(module.tree):
            if not isinstance(fn, _FUNC_KINDS):
                continue
            marked = _marker(module, fn)
            if marked is None:
                continue
            line_no, rest = marked
            if not rest.lstrip().startswith(":") or not rest.lstrip(": ").strip():
                yield self.found(
                    module, fn,
                    "zt-dispatch-critical marker without a reason — say "
                    "WHY this function is on the dispatch core's critical "
                    "path (# zt-dispatch-critical: <reason>)",
                )
            for node in ast.walk(fn):
                if isinstance(node, _LOOP_KINDS):
                    shape = "loop"
                elif isinstance(node, _COMP_KINDS):
                    shape = "comprehension"
                else:
                    continue
                # anchor at the enclosing STATEMENT: a comprehension's
                # own line is mid-expression, where no pragma can sit —
                # the suppression audit lives on the statement line
                anchor = node
                while anchor is not None and not isinstance(anchor, ast.stmt):
                    anchor = module.parents.get(anchor)
                yield self.found(
                    module, anchor or node,
                    f"Python {shape} inside dispatch-critical "
                    f"{fn.name}() — a per-span trip count here caps "
                    "every parse worker at one interpreter's speed",
                )
            yield from self._check_callees(module, fn)

    def _check_callees(self, module: Module, fn: ast.AST):
        """Compositional hop: calls resolving to unmarked loop-bearing
        functions — the hidden-helper-loop shape."""
        graph = self.graph(module)
        for call in ast.walk(fn):
            if not isinstance(call, ast.Call):
                continue
            for qual, _resolved in graph.callees_of_call(call):
                info = graph.functions.get(qual)
                if info is None or "<locals>" in qual:
                    continue  # nested defs are inside the marked body
                callee_mod = graph.module_for(info.module_rel) or module
                if _marker(callee_mod, info.node) is not None:
                    continue  # marked callee: its loops carry the audit
                if not _has_own_loop(info.node):
                    continue
                # one finding per call site even when the conservative
                # fallback offers several loop-bearing candidates
                yield self.found(
                    module, call,
                    f"dispatch-critical {fn.name}() calls "
                    f"{info.name}() [{info.module_rel}], which contains "
                    "a Python loop but is not marked zt-dispatch-"
                    "critical — the helper's trip count is unaudited",
                    hint=(
                        "mark the callee zt-dispatch-critical (its "
                        "loops then need per-trip-count justification) "
                        "or pragma this call with the bound"
                    ),
                )
                break
