"""ZT05 — donation misuse.

Every state-mutating program in the repo donates its input buffers
(``jax.jit(..., donate_argnums=(0,))``): the step/flush/rollup programs
reuse the state's device memory, which is why a reader racing a step
touches deleted arrays (the aggregator lock exists for exactly this).
The SAFE idiom is ``state = step(state, batch)`` — the donated name is
rebound to the result in the same statement, so nothing can read the
deleted buffer afterwards.

Rule: resolve callables bound from ``jax.jit(..., donate_argnums=...)``
(by local/module name, or ``self._name`` bound in a method). At each
call site, the argument expressions at donated positions are captured;
if the call's result is NOT assigned back to that same expression, any
later read of the expression in the same function scope is a finding —
a read of donated (deleted) device memory.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from zipkin_tpu.lint.core import Checker, Module, register

_FUNC_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _donated_positions(call: ast.Call):
    """The donate_argnums literal of a jax.jit(...) call, or None."""
    f = call.func
    is_jit = (isinstance(f, ast.Attribute) and f.attr == "jit") or (
        isinstance(f, ast.Name) and f.id == "jit"
    )
    if not is_jit:
        return None
    for k in call.keywords:
        if k.arg != "donate_argnums":
            continue
        v = k.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = tuple(
                el.value
                for el in v.elts
                if isinstance(el, ast.Constant) and isinstance(el.value, int)
            )
            return out or None
    return None


def _donating_names(module: Module) -> Dict[str, Tuple[int, ...]]:
    """name -> donated positions, for ``x = jax.jit(..., donate_argnums)``
    and ``self._x = jax.jit(...)`` bindings anywhere in the module."""
    out: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
            continue
        pos = _donated_positions(node.value)
        if pos is None:
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                out[t.id] = pos
            elif (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                out[f"self.{t.attr}"] = pos
    return out


def _call_name(call: ast.Call):
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if (
        isinstance(f, ast.Attribute)
        and isinstance(f.value, ast.Name)
        and f.value.id == "self"
    ):
        return f"self.{f.attr}"
    return None


def _expr_key(node: ast.AST):
    """A stable identity for 'the same expression': Name or self.attr
    chains only — anything fancier can't be tracked reliably."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        inner = _expr_key(node.value)
        return f"{inner}.{node.attr}" if inner else None
    return None


@register
class DonationMisuse(Checker):
    rule = "ZT05"
    severity = "error"
    name = "donation-misuse"
    doc = "a donated argument read after the donating call"
    hint = (
        "rebind the result to the donated name in the same statement "
        "(state = step(state, ...)) or drop donate_argnums"
    )

    def check(self, module: Module):
        if not module.imported_roots & {"jax", "jnp"}:
            return
        donating = _donating_names(module)
        if not donating:
            return
        for fn in ast.walk(module.tree):
            if isinstance(fn, _FUNC_KINDS):
                yield from self._check_scope(module, fn, donating)

    def _check_scope(self, module: Module, fn: ast.AST, donating):
        # donated expression keys and the line their buffers died on
        dead: Dict[str, int] = {}
        calls: List[Tuple[ast.Call, str]] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = _call_name(node)
                if name in donating:
                    calls.append((node, name))
        calls.sort(key=lambda c: (c[0].lineno, c[0].col_offset))
        for call, name in calls:
            # the NEAREST enclosing statement decides the same-statement
            # rebind (state = step(state, ...) keeps the name live)
            stmt = next(iter(module.enclosing(call, ast.stmt)), None)
            rebound: Set[str] = set()
            if isinstance(stmt, ast.Assign) and stmt.value is call:
                rebound = {
                    k for k in map(_expr_key, stmt.targets) if k is not None
                }
            for pos in donating[name]:
                if pos >= len(call.args):
                    continue
                key = _expr_key(call.args[pos])
                if key is not None and key not in rebound:
                    dead[key] = call.lineno
        if not dead:
            return
        # uses in source order, so a later rebind ends tracking exactly
        # where it happens (a rebound name is a live buffer again)
        uses = []
        for node in ast.walk(fn):
            if not isinstance(node, (ast.Name, ast.Attribute)):
                continue
            key = _expr_key(node)
            if key in dead:
                uses.append((node.lineno, node.col_offset, key, node))
        for _, _, key, node in sorted(uses, key=lambda u: (u[0], u[1])):
            if key not in dead or node.lineno <= dead[key]:
                continue
            if isinstance(node.ctx, ast.Store):
                dead.pop(key, None)
                continue
            if not isinstance(node.ctx, ast.Load):
                continue
            yield self.found(
                module,
                node,
                f"{key} was donated on line {dead[key]} and read "
                "here — its device buffer is deleted",
            )
            dead.pop(key, None)  # one finding per donated expr
