"""ZT12 — durability-commit chokepoints in the persistence modules.

A file a restore can read must be COMMITTED, not merely written: bytes
to a tmp name, ``fsync`` the file (bytes durable), ``os.replace`` onto
the real name (visibility atomic), ``fsync`` the directory (the rename
itself durable). Skip any link and there is a crash window where
recovery reads a file that is missing, empty, or half-written — the
exact class of bug the crashpoint harness exists to catch, except a
NEW write path only gets crashpoint coverage if someone remembers to
add it. This rule makes forgetting loud, in the four registered
persistence modules (``wal.py``, ``snapshot.py``, ``timetier.py``,
``archive.py``):

- **``os.replace`` / ``os.rename`` without a preceding fsync**: the
  destination name can point at unsynced bytes — after a crash the
  rename survives but the contents don't.
- **``os.replace`` / ``os.rename`` without a following directory
  fsync**: the rename itself can vanish — recovery sees the OLD file.
- **a write-mode ``open()`` with no fsync anywhere on its path**: the
  function, its resolved callees, and its in-graph callers (the
  open-here-fsync-in-caller split ``Wal._file_for``/``append`` uses)
  are all searched via the call graph before flagging.

Exempt by construction: tmp-named targets (a ``*.tmp`` path or a name
binding containing ``tmp`` — those bytes are committed by the rename
that follows, which is checked instead) and ``os.fdopen`` inside a
function that called ``tempfile.mkstemp``. Deliberate exceptions —
quarantine renames that move ALREADY-corrupt bytes aside, append-mode
live files whose durability contract is the WAL's — carry
pragma-with-reason at the site, so every exception is a reviewed
sentence, not an unstated assumption.
"""

from __future__ import annotations

import ast
from typing import Optional, Set

from zipkin_tpu.lint.core import Checker, Module, register

_FUNC_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef)

MODULES = (
    "zipkin_tpu/tpu/wal.py",
    "zipkin_tpu/tpu/snapshot.py",
    "zipkin_tpu/tpu/timetier.py",
    "zipkin_tpu/tpu/archive.py",
)

_RENAMES = {"replace", "rename"}
_REACH_DEPTH = 3  # helper chains are shallow; bounds the fsync search


def _callee_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_fsync_name(name: Optional[str]) -> bool:
    return bool(name) and ("fsync" in name or name == "fdatasync")


def _write_mode(call: ast.Call) -> bool:
    """open()/os.fdopen() with a literal w/a/x mode."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if not (isinstance(mode, ast.Constant) and isinstance(mode.value, str)):
        return False
    return mode.value.replace("b", "").replace("+", "") in {"w", "a", "x"}


def _tmp_target(node: ast.AST) -> bool:
    """Heuristic tmp-ness of a path expression: any name binding with
    ``tmp`` in it, or a string constant mentioning ``tmp``."""
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and "tmp" in n.id.lower():
            return True
        if isinstance(n, ast.Attribute) and "tmp" in n.attr.lower():
            return True
        if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                and "tmp" in n.value.lower():
            return True
    return False


@register
class DurabilityCommit(Checker):
    rule = "ZT12"
    severity = "error"
    name = "durability-commit"
    doc = (
        "persistence modules: restore-readable files flow through "
        "tmp+fsync+rename+dir-fsync; bare writes/renames are findings"
    )
    hint = (
        "write to a tmp name, fsync the file, os.replace onto the real "
        "name, then fsync the directory (see snapshot.py's commit chain)"
    )

    def check(self, module: Module):
        if not any(module.rel.endswith(m) for m in MODULES):
            return
        for fn in ast.walk(module.tree):
            if not isinstance(fn, _FUNC_KINDS):
                continue
            yield from self._check_function(module, fn)

    # -- per-function ------------------------------------------------------

    def _check_function(self, module: Module, fn: ast.AST):
        nested: Set[int] = set()
        for n in ast.walk(fn):
            if isinstance(n, _FUNC_KINDS) and n is not fn:
                nested.update(id(x) for x in ast.walk(n))
        calls = [
            n for n in ast.walk(fn)
            if isinstance(n, ast.Call) and id(n) not in nested
        ]
        fsync_lines = sorted(
            c.lineno for c in calls if self._reaches_fsync(c)
        )
        has_mkstemp = any(
            _callee_name(c.func) in {"mkstemp", "NamedTemporaryFile"}
            for c in calls
        )
        for call in calls:
            name = _callee_name(call.func)
            if name in _RENAMES and isinstance(call.func, ast.Attribute):
                if not any(line < call.lineno for line in fsync_lines):
                    yield self.found(
                        module, call,
                        f"os.{name} in {fn.name}() without a preceding "
                        "fsync — after a crash the new name can point at "
                        "unsynced (lost) bytes",
                    )
                if not any(line > call.lineno for line in fsync_lines):
                    yield self.found(
                        module, call,
                        f"os.{name} in {fn.name}() without a following "
                        "directory fsync — the rename itself is not "
                        "durable and recovery may see the old file",
                    )
            elif name == "open" and isinstance(call.func, ast.Name):
                if not _write_mode(call) or not call.args:
                    continue
                if _tmp_target(call.args[0]):
                    continue  # committed by the rename, checked above
                if fsync_lines or self._caller_fsyncs(module, fn):
                    continue
                yield self.found(
                    module, call,
                    f"write-mode open in {fn.name}() with no fsync on "
                    "any path through it (function, callees, callers) — "
                    "a restore can read this file's unsynced bytes",
                )
            elif name == "fdopen" and not has_mkstemp and _write_mode(call):
                if not fsync_lines and not self._caller_fsyncs(module, fn):
                    yield self.found(
                        module, call,
                        f"write-mode fdopen in {fn.name}() outside the "
                        "mkstemp+fsync+rename commit idiom",
                    )

    # -- graph-backed fsync search ----------------------------------------

    def _reaches_fsync(self, call: ast.Call) -> bool:
        """The call IS an fsync, or resolves to a function that reaches
        one within a short chain (``self._commit()`` helpers)."""
        if _is_fsync_name(_callee_name(call.func)):
            return True
        if self.program is None:
            return False
        return any(
            self._fn_reaches_fsync(qual, _REACH_DEPTH)
            for qual, _resolved in self.program.callees_of_call(call)
        )

    def _fn_reaches_fsync(self, qual: str, depth: int) -> bool:
        info = self.program.functions.get(qual)
        if info is None or depth < 0:
            return False
        for n in ast.walk(info.node):
            if isinstance(n, ast.Call) and _is_fsync_name(
                _callee_name(n.func)
            ):
                return True
        if depth == 0:
            return False
        return any(
            resolved and self._fn_reaches_fsync(callee, depth - 1)
            for callee, resolved in self.program.edges.get(qual, ())
        )

    def _caller_fsyncs(self, module: Module, fn: ast.AST) -> bool:
        """The split idiom: this function opens, its caller fsyncs
        (``Wal._file_for`` / ``Wal.append``). Honest only when EVERY
        in-graph caller fsyncs — one caller skipping it is the bug."""
        if self.program is None:
            return False
        qual = self.program.qual_of(fn)
        if qual is None:
            return False
        callers = self.program.callers_of(qual)
        if not callers:
            return False
        return all(
            self._fn_reaches_fsync(c, _REACH_DEPTH) for c in callers
        )
