"""ZT07 — full-ring sorts on the fresh-read path.

ISSUE 5's tentpole moved the O(R log R) link-context rebuild (a 4-key
``lax.sort`` over 2^19 union lanes — 29.6 ms of the 41.3 ms r5 fresh
read) off the query path: the sorted union order is maintained
incrementally at rollup cadence, and a fresh read may sort only the
since-rollup DELTA segment (``ops/delta_linker.py``). This rule is the
regression fence: any sort/scan-family op — or a call back into the
from-scratch rebuilders — reachable from a fresh-read entrypoint is a
reintroduction of the full-ring cost and fails tier-1
(tests/test_lint_clean.py).

Mechanics: per module, functions named in ``FRESH_READ_ENTRYPOINTS``
seed a call-graph walk over locally-defined functions (bare-name and
attribute calls both descend when a local def matches — conservative:
cross-module edges can't be followed, so each module on the path names
its own entrypoint). Inside reachable functions two shapes are flagged:

1. sort/scan-family calls: ``lax.sort``, ``jnp.sort``, ``jnp.argsort``,
   ``jnp.lexsort``, ``lax.associative_scan``, ``lax.scan``.
   ``jnp.cumsum`` is deliberately NOT in the set: prefix sums are the
   delta formulation's own workhorse (compaction counting, run-id
   assignment) and are O(n) elementwise-cheap vectorized ops — the
   hazard this rule fences is the O(n log n) comparison sort and the
   sequential carry loop, not parallel prefix.
2. calls to the from-scratch rebuilders ``link_context`` /
   ``resolve_parents`` (ops/linker.py): correct answers, wrong tier —
   they are the rollup/oracle path.

The ONE legitimate sort on the fresh path — the delta-segment sort in
``delta_linker._resolve_core`` — carries a ZT07 pragma whose reason
states the bound (``sorts only the 2·Δ delta-segment lanes``); the
pragma-with-reason mechanism (ZT00) keeps that claim reviewable.

ISSUE 15 added a second fenced surface with the same failure shape at a
different tier: windowed sketch queries (``[lookback, endTs]`` on the
quantile/cardinality/dependency routes) answer by merging sealed
time-bucket segments (``tpu/timetier.py``) — compact host-side numpy
over O(W) segments. The tempting regression is a "helpful" fallback
that answers an uncovered window by rescanning the span archive
(``candidate_trace_ids`` / ``_disk_query`` — O(archive) wall per
query, exactly the cost the tier exists to avoid; uncovered epochs are
reported as coverage gaps instead). That walk is UNGATED on jax
imports: the windowed routing layer is pure host code and must stay
fenced even if it moves out of a jax-importing module.
"""

from __future__ import annotations

import ast

from zipkin_tpu.lint.core import Checker, Module, register
from zipkin_tpu.lint.taint import _root_name

_FUNC_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef)

# the query-path surface: functions that run (or build the program for)
# a FRESH read — every module on the fresh path names its own entrypoint
# because the walk cannot follow imports
FRESH_READ_ENTRYPOINTS = {
    "spmd_link_ctx",        # parallel/sharded.py: ctx-only program
    "spmd_edges_fresh",     # parallel/sharded.py: fused ctx+edges program
    "fresh_link_context",   # tpu/ingest.py: delta-read entrypoint
    "delta_link_context",   # ops/delta_linker.py: resolve + chase + rules
    "delta_resolve",        # ops/delta_linker.py: resolve only
}

# O(n log n) sorts and sequential-carry scans; jnp.cumsum is deliberately
# absent (see module docstring)
SORT_SCAN_ATTRS = {"sort", "argsort", "lexsort", "associative_scan", "scan"}
SORT_SCAN_ROOTS = {"jax", "jnp", "lax"}

# the from-scratch oracle surface (ops/linker.py)
FULL_REBUILDERS = {"link_context", "resolve_parents"}

# windowed sketch-tier entrypoints (tpu/store.py, ISSUE 15): queries
# carrying a [lookback, endTs] range answer from merged time-bucket
# segments — same per-module seeding rule as the fresh-read set
WINDOWED_ENTRYPOINTS = {
    "latency_quantiles",
    "trace_cardinalities",
    "_get_dependencies",
    "_tt_window",
}

# the full-archive scan surface (tpu/store.py → tpu/archive.py):
# correct for trace retrieval, catastrophic as a windowed-sketch
# fallback — O(archive) wall per query
ARCHIVE_SCANNERS = {"candidate_trace_ids", "_disk_query"}


def _callee_name(func: ast.AST):
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _reach(defs, roots):
    """Conservative local reachability: def node -> (node, seed name).

    Bare-name and attribute calls both descend when a local def
    matches — over-approximate rather than miss a helper; cross-module
    edges can't be followed, so each module on a fenced path names its
    own entrypoints.
    """
    reached = {}
    stack = [(d, d.name) for d in roots]
    while stack:
        fn, root = stack.pop()
        if fn.name in reached:
            continue
        reached[fn.name] = (fn, root)
        for call in ast.walk(fn):
            if isinstance(call, ast.Call):
                tgt = defs.get(_callee_name(call.func))
                if tgt is not None and tgt.name not in reached:
                    stack.append((tgt, root))
    return reached


@register
class FreshReadRingSort(Checker):
    rule = "ZT07"
    severity = "error"
    name = "fresh-read-ring-sort"
    doc = (
        "sort/scan ops or from-scratch ctx rebuilds reachable from "
        "fresh-read entrypoints"
    )
    hint = (
        "fresh reads may only sort the since-rollup delta segment "
        "(ops/delta_linker.py); move full-ring work to rollup cadence, "
        "or suppress with a reason stating the delta-size bound"
    )

    def check(self, module: Module):
        defs = {}
        for node in ast.walk(module.tree):
            if isinstance(node, _FUNC_KINDS):
                defs.setdefault(node.name, node)
        # walk 1 — fresh-read sort fence, gated on jax imports (the
        # hazard is a device sort/scan; a jax-free module can't emit one)
        if module.imported_roots & {"jax", "jnp"}:
            roots = [
                d for n, d in defs.items() if n in FRESH_READ_ENTRYPOINTS
            ]
            for fn, root in _reach(defs, roots).values():
                yield from self._scan_function(module, fn, root)
        # walk 2 — windowed archive-scan fence, UNGATED: the windowed
        # routing layer is pure host code (see module docstring)
        w_roots = [d for n, d in defs.items() if n in WINDOWED_ENTRYPOINTS]
        for fn, root in _reach(defs, w_roots).values():
            yield from self._scan_windowed(module, fn, root)

    def _scan_function(self, module: Module, fn: ast.AST, root: str):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _callee_name(node.func)
            if (
                isinstance(node.func, ast.Attribute)
                and name in SORT_SCAN_ATTRS
                and _root_name(node.func) in SORT_SCAN_ROOTS
            ):
                where = "" if fn.name == root else f" (via {fn.name}())"
                yield self.found(
                    module,
                    node,
                    f"{_root_name(node.func)}.{name} reachable from "
                    f"fresh-read entrypoint {root}(){where} — fresh reads "
                    "must not pay full-ring sort/scan cost",
                )
            elif name in FULL_REBUILDERS and fn.name not in FULL_REBUILDERS:
                where = "" if fn.name == root else f" (via {fn.name}())"
                yield self.found(
                    module,
                    node,
                    f"from-scratch rebuilder {name}() called from "
                    f"fresh-read entrypoint {root}(){where} — use the "
                    "incremental delta formulation",
                )

    def _scan_windowed(self, module: Module, fn: ast.AST, root: str):
        if fn.name in ARCHIVE_SCANNERS:
            # the scanners themselves (and their internals) are the
            # trace-retrieval path — only CALLS INTO them from the
            # windowed surface are the violation
            return
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _callee_name(node.func)
            if name in ARCHIVE_SCANNERS:
                where = "" if fn.name == root else f" (via {fn.name}())"
                yield self.found(
                    module,
                    node,
                    f"archive scanner {name}() reachable from windowed "
                    f"entrypoint {root}(){where} — windowed queries must "
                    "merge sealed time-bucket segments (coverage gaps "
                    "are reported, not backfilled by archive rescans)",
                )
