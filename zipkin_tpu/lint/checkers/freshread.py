"""ZT07 — full-ring sorts on the fresh-read path.

ISSUE 5's tentpole moved the O(R log R) link-context rebuild (a 4-key
``lax.sort`` over 2^19 union lanes — 29.6 ms of the 41.3 ms r5 fresh
read) off the query path: the sorted union order is maintained
incrementally at rollup cadence, and a fresh read may sort only the
since-rollup DELTA segment (``ops/delta_linker.py``). This rule is the
regression fence: any sort/scan-family op — or a call back into the
from-scratch rebuilders — reachable from a fresh-read entrypoint is a
reintroduction of the full-ring cost and fails tier-1
(tests/test_lint_clean.py).

Mechanics: functions named in ``FRESH_READ_ENTRYPOINTS`` — wherever
they live — seed a walk over the WHOLE-PROGRAM call graph (qualified-
name resolution; conservative fallback edges descend into same-module
defs and uniquely-named imported methods, over-approximating rather
than missing a helper), so a sort can no longer hide one import away.
Inside reachable functions, in whatever module the walk lands, two
shapes are flagged:

1. sort/scan-family calls: ``lax.sort``, ``jnp.sort``, ``jnp.argsort``,
   ``jnp.lexsort``, ``lax.associative_scan``, ``lax.scan``.
   ``jnp.cumsum`` is deliberately NOT in the set: prefix sums are the
   delta formulation's own workhorse (compaction counting, run-id
   assignment) and are O(n) elementwise-cheap vectorized ops — the
   hazard this rule fences is the O(n log n) comparison sort and the
   sequential carry loop, not parallel prefix.
2. calls to the from-scratch rebuilders ``link_context`` /
   ``resolve_parents`` (ops/linker.py): correct answers, wrong tier —
   they are the rollup/oracle path.

The ONE legitimate sort on the fresh path — the delta-segment sort in
``delta_linker._resolve_core`` — carries a ZT07 pragma whose reason
states the bound (``sorts only the 2·Δ delta-segment lanes``); the
pragma-with-reason mechanism (ZT00) keeps that claim reviewable.

ISSUE 15 added a second fenced surface with the same failure shape at a
different tier: windowed sketch queries (``[lookback, endTs]`` on the
quantile/cardinality/dependency routes) answer by merging sealed
time-bucket segments (``tpu/timetier.py``) — compact host-side numpy
over O(W) segments. The tempting regression is a "helpful" fallback
that answers an uncovered window by rescanning the span archive
(``candidate_trace_ids`` / ``_disk_query`` — O(archive) wall per
query, exactly the cost the tier exists to avoid; uncovered epochs are
reported as coverage gaps instead). That walk is UNGATED on jax
imports: the windowed routing layer is pure host code and must stay
fenced even if it moves out of a jax-importing module. (The sort fence
gates on the ROOT's module importing jax — the hazard is a device
sort/scan, which a jax-free entrypoint module cannot seed.)
"""

from __future__ import annotations

import ast

from zipkin_tpu.lint.core import Checker, Module, register
from zipkin_tpu.lint.taint import _root_name

_FUNC_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef)

# the query-path surface: functions that run (or build the program for)
# a FRESH read. The graph follows imports now, so seeding each module's
# own entrypoint is belt-and-braces rather than a necessity; the names
# stay because each IS an entrypoint of its tier.
FRESH_READ_ENTRYPOINTS = {
    "spmd_link_ctx",        # parallel/sharded.py: ctx-only program
    "spmd_edges_fresh",     # parallel/sharded.py: fused ctx+edges program
    "fresh_link_context",   # tpu/ingest.py: delta-read entrypoint
    "delta_link_context",   # ops/delta_linker.py: resolve + chase + rules
    "delta_resolve",        # ops/delta_linker.py: resolve only
}

# O(n log n) sorts and sequential-carry scans; jnp.cumsum is deliberately
# absent (see module docstring)
SORT_SCAN_ATTRS = {"sort", "argsort", "lexsort", "associative_scan", "scan"}
SORT_SCAN_ROOTS = {"jax", "jnp", "lax"}

# the from-scratch oracle surface (ops/linker.py)
FULL_REBUILDERS = {"link_context", "resolve_parents"}

# windowed sketch-tier entrypoints (tpu/store.py, ISSUE 15): queries
# carrying a [lookback, endTs] range answer from merged time-bucket
# segments
WINDOWED_ENTRYPOINTS = {
    "latency_quantiles",
    "trace_cardinalities",
    "_get_dependencies",
    "_tt_window",
}

# the full-archive scan surface (tpu/store.py → tpu/archive.py):
# correct for trace retrieval, catastrophic as a windowed-sketch
# fallback — O(archive) wall per query
ARCHIVE_SCANNERS = {"candidate_trace_ids", "_disk_query"}


def _callee_name(func: ast.AST):
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


@register
class FreshReadRingSort(Checker):
    rule = "ZT07"
    severity = "error"
    name = "fresh-read-ring-sort"
    doc = (
        "sort/scan ops or from-scratch ctx rebuilds reachable from "
        "fresh-read entrypoints"
    )
    hint = (
        "fresh reads may only sort the since-rollup delta segment "
        "(ops/delta_linker.py); move full-ring work to rollup cadence, "
        "or suppress with a reason stating the delta-size bound"
    )
    whole_program = True

    def check_program(self, program):
        fresh_roots, windowed_roots = [], []
        for module in program.modules:
            jax_gated = bool(module.imported_roots & {"jax", "jnp"})
            for fn in ast.walk(module.tree):
                if not isinstance(fn, _FUNC_KINDS):
                    continue
                qual = program.qual_of(fn)
                if qual is None:
                    continue
                # sort fence gates on the ROOT's module importing jax:
                # the hazard is a device sort/scan, which a jax-free
                # entrypoint module cannot seed
                if fn.name in FRESH_READ_ENTRYPOINTS and jax_gated:
                    fresh_roots.append(qual)
                if fn.name in WINDOWED_ENTRYPOINTS:
                    windowed_roots.append(qual)
        for qual, (root, _d, _p) in program.reach(fresh_roots).items():
            info = program.functions[qual]
            module = program.module_for(info.module_rel)
            if module is not None:
                yield from self._scan_function(
                    module, info.node, program.functions[root].name
                )
        for qual, (root, _d, _p) in program.reach(windowed_roots).items():
            info = program.functions[qual]
            module = program.module_for(info.module_rel)
            if module is not None:
                yield from self._scan_windowed(
                    module, info.node, program.functions[root].name
                )

    def _scan_function(self, module: Module, fn: ast.AST, root: str):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _callee_name(node.func)
            if (
                isinstance(node.func, ast.Attribute)
                and name in SORT_SCAN_ATTRS
                and _root_name(node.func) in SORT_SCAN_ROOTS
            ):
                where = "" if fn.name == root else f" (via {fn.name}())"
                yield self.found(
                    module,
                    node,
                    f"{_root_name(node.func)}.{name} reachable from "
                    f"fresh-read entrypoint {root}(){where} — fresh reads "
                    "must not pay full-ring sort/scan cost",
                )
            elif name in FULL_REBUILDERS and fn.name not in FULL_REBUILDERS:
                where = "" if fn.name == root else f" (via {fn.name}())"
                yield self.found(
                    module,
                    node,
                    f"from-scratch rebuilder {name}() called from "
                    f"fresh-read entrypoint {root}(){where} — use the "
                    "incremental delta formulation",
                )

    def _scan_windowed(self, module: Module, fn: ast.AST, root: str):
        if fn.name in ARCHIVE_SCANNERS:
            # the scanners themselves (and their internals) are the
            # trace-retrieval path — only CALLS INTO them from the
            # windowed surface are the violation
            return
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _callee_name(node.func)
            if name in ARCHIVE_SCANNERS:
                where = "" if fn.name == root else f" (via {fn.name}())"
                yield self.found(
                    module,
                    node,
                    f"archive scanner {name}() reachable from windowed "
                    f"entrypoint {root}(){where} — windowed queries must "
                    "merge sealed time-bucket segments (coverage gaps "
                    "are reported, not backfilled by archive rescans)",
                )
