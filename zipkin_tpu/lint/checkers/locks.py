"""ZT04 — lock discipline across methods of a class.

The r5 regression this pins the shape of: vocab-sidecar persistence
raced concurrent writers — ``_archive_vocab_persisted`` and the sidecar
``os.replace`` were updated under a lock on one path and lock-free on
another, so a delayed writer could replace a NEWER sidecar with an older
snapshot (fixed by ``_persist_lock``; previously pinned only by one
behavioral test). "Fast Concurrent Data Sketches" (PAPERS.md) is the
motivating frame: the ingest and read planes share mutable sketch state
across threads, exactly where silent races are born.

Rule: within one class, collect the lock attributes (``self.x =
threading.Lock()/RLock()/Condition()`` — any assignment whose value is
a ``threading.*`` constructor call). An instance attribute is
*lock-associated* when some method writes it inside a ``with
self.<lock>:`` block. Every OTHER write to that attribute (plain
assignment, augmented assignment, or ``self.attr[...] = ...`` item
write) outside any with-lock block — in any method except ``__init__``
(construction precedes concurrency) — is a finding.

"Callers hold the lock" helper methods are real and common (the
aggregator's ``_flush_now``); they are exactly what the scoped pragma on
the ``def`` line is for, with the justification naming the lock.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from zipkin_tpu.lint.core import Checker, Module, register
from zipkin_tpu.lint.taint import _root_name

_FUNC_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef)
# InstrumentedRLock (obs/querytrace.py) is a drop-in RLock with a
# contention ledger — the aggregator's with-discipline must survive the
# swap, so ZT04 recognizes it as a lock constructor too.
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore", "InstrumentedRLock"}


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """self.X assigned from a threading.* lock constructor anywhere in
    the class body."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
            continue
        f = node.value.func
        ctor = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        if ctor not in _LOCK_CTORS:
            continue
        if isinstance(f, ast.Attribute) and _root_name(f) not in (
            "threading",
            "multiprocessing",
            "mp",
            "querytrace",
        ):
            continue
        for t in node.targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                out.add(t.attr)
    return out


def _self_attr_write(target: ast.AST):
    """'attr' when the assignment target writes self.attr or
    self.attr[...] (an item write mutates the shared container)."""
    if isinstance(target, ast.Subscript):
        target = target.value
    if (
        isinstance(target, ast.Attribute)
        and isinstance(target.value, ast.Name)
        and target.value.id == "self"
    ):
        return target.attr
    return None


def _with_locks(module: Module, node: ast.AST, lock_attrs: Set[str]) -> bool:
    """Is ``node`` lexically inside a ``with self.<lock>:`` block?"""
    for w in module.enclosing(node, (ast.With, ast.AsyncWith)):
        for item in w.items:
            e = item.context_expr
            # with self.lock: / with self._cv: / with self.lock, other:
            if (
                isinstance(e, ast.Attribute)
                and isinstance(e.value, ast.Name)
                and e.value.id == "self"
                and e.attr in lock_attrs
            ):
                return True
    return False


@register
class LockDiscipline(Checker):
    rule = "ZT04"
    severity = "error"
    name = "lock-discipline"
    doc = "attribute locked in one method, written lock-free in another"
    hint = (
        "take the same lock (or, if the caller provably holds it, "
        "suppress on the def line naming the lock)"
    )

    def check(self, module: Module):
        for cls in ast.walk(module.tree):
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(module, cls)

    def _check_class(self, module: Module, cls: ast.ClassDef):
        lock_attrs = _lock_attrs(cls)
        if not lock_attrs:
            return
        # every write site: (attr, node, method, guarded?)
        writes: List[Tuple[str, ast.AST, str, bool]] = []
        for method in cls.body:
            if not isinstance(method, _FUNC_KINDS):
                continue
            for node in ast.walk(method):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for t in targets:
                    attr = _self_attr_write(t)
                    if attr is None or attr in lock_attrs:
                        continue
                    writes.append(
                        (
                            attr,
                            node,
                            method.name,
                            _with_locks(module, node, lock_attrs),
                        )
                    )
        guarded_attrs: Dict[str, Set[str]] = {}
        for attr, _, meth, guarded in writes:
            if guarded:
                guarded_attrs.setdefault(attr, set()).add(meth)
        for attr, node, meth, guarded in writes:
            if guarded or attr not in guarded_attrs or meth == "__init__":
                continue
            lockers = ", ".join(sorted(guarded_attrs[attr]))
            yield self.found(
                module,
                node,
                f"{cls.name}.{attr} written lock-free in {meth}() but "
                f"under a lock in {lockers}() — the r5 sidecar-race shape",
            )
