"""ZT04 — lock discipline across methods of a class.

The r5 regression this pins the shape of: vocab-sidecar persistence
raced concurrent writers — ``_archive_vocab_persisted`` and the sidecar
``os.replace`` were updated under a lock on one path and lock-free on
another, so a delayed writer could replace a NEWER sidecar with an older
snapshot (fixed by ``_persist_lock``; previously pinned only by one
behavioral test). "Fast Concurrent Data Sketches" (PAPERS.md) is the
motivating frame: the ingest and read planes share mutable sketch state
across threads, exactly where silent races are born.

Rule: within one class, collect the lock attributes (``self.x =
threading.Lock()/RLock()/Condition()`` — any assignment whose value is
a ``threading.*`` constructor call). An instance attribute is
*lock-associated* when some method writes it inside a ``with
self.<lock>:`` block. Every OTHER write to that attribute (plain
assignment, augmented assignment, or ``self.attr[...] = ...`` item
write) outside any with-lock block — in any method except ``__init__``
(construction precedes concurrency) — is a finding.

"Callers hold the lock" helper methods are real and common (the
aggregator's ``_flush_now``). When the call graph can PROVE the claim —
every in-graph call site of the method is a same-class call lexically
inside ``with self.<lock>:`` — the write is accepted without ceremony;
the scoped pragma on the ``def`` line (justification naming the lock)
remains for the cases the graph can't see (callbacks, cross-class
protocols, calls from outside the linted tree). One unguarded caller
kills the proof: that caller IS the race.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from zipkin_tpu.lint.core import Checker, Module, register
from zipkin_tpu.lint.taint import _root_name

_FUNC_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef)
# InstrumentedRLock (obs/querytrace.py) is a drop-in RLock with a
# contention ledger — the aggregator's with-discipline must survive the
# swap, so ZT04 recognizes it as a lock constructor too.
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore", "InstrumentedRLock"}


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """self.X assigned from a threading.* lock constructor anywhere in
    the class body."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
            continue
        f = node.value.func
        ctor = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        if ctor not in _LOCK_CTORS:
            continue
        if isinstance(f, ast.Attribute) and _root_name(f) not in (
            "threading",
            "multiprocessing",
            "mp",
            "querytrace",
        ):
            continue
        for t in node.targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                out.add(t.attr)
    return out


def _self_attr_write(target: ast.AST):
    """'attr' when the assignment target writes self.attr or
    self.attr[...] (an item write mutates the shared container)."""
    if isinstance(target, ast.Subscript):
        target = target.value
    if (
        isinstance(target, ast.Attribute)
        and isinstance(target.value, ast.Name)
        and target.value.id == "self"
    ):
        return target.attr
    return None


def _with_locks(module: Module, node: ast.AST, lock_attrs: Set[str]) -> bool:
    """Is ``node`` lexically inside a ``with self.<lock>:`` block?"""
    for w in module.enclosing(node, (ast.With, ast.AsyncWith)):
        for item in w.items:
            e = item.context_expr
            # with self.lock: / with self._cv: / with self.lock, other:
            if (
                isinstance(e, ast.Attribute)
                and isinstance(e.value, ast.Name)
                and e.value.id == "self"
                and e.attr in lock_attrs
            ):
                return True
    return False


@register
class LockDiscipline(Checker):
    rule = "ZT04"
    severity = "error"
    name = "lock-discipline"
    doc = "attribute locked in one method, written lock-free in another"
    hint = (
        "take the same lock (or, if the caller provably holds it, "
        "suppress on the def line naming the lock)"
    )

    def check(self, module: Module):
        for cls in ast.walk(module.tree):
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(module, cls)

    def _check_class(self, module: Module, cls: ast.ClassDef):
        lock_attrs = _lock_attrs(cls)
        if not lock_attrs:
            return
        # every write site: (attr, node, method, guarded?)
        writes: List[Tuple[str, ast.AST, str, bool]] = []
        methods: Dict[str, ast.AST] = {}
        for method in cls.body:
            if not isinstance(method, _FUNC_KINDS):
                continue
            methods[method.name] = method
            for node in ast.walk(method):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for t in targets:
                    attr = _self_attr_write(t)
                    if attr is None or attr in lock_attrs:
                        continue
                    writes.append(
                        (
                            attr,
                            node,
                            method.name,
                            _with_locks(module, node, lock_attrs),
                        )
                    )
        guarded_attrs: Dict[str, Set[str]] = {}
        for attr, _, meth, guarded in writes:
            if guarded:
                guarded_attrs.setdefault(attr, set()).add(meth)
        callers_hold: Dict[str, bool] = {}
        for attr, node, meth, guarded in writes:
            if guarded or attr not in guarded_attrs or meth == "__init__":
                continue
            if meth not in callers_hold:
                callers_hold[meth] = self._callers_hold_lock(
                    module, methods[meth], lock_attrs
                )
            if callers_hold[meth]:
                continue  # the graph proves every call site holds it
            lockers = ", ".join(sorted(guarded_attrs[attr]))
            yield self.found(
                module,
                node,
                f"{cls.name}.{attr} written lock-free in {meth}() but "
                f"under a lock in {lockers}() — the r5 sidecar-race shape",
            )

    def _callers_hold_lock(
        self, module: Module, method: ast.AST, lock_attrs: Set[str]
    ) -> bool:
        """The interprocedural caller-holds-the-lock proof: every
        in-graph call site is a same-class call made inside ``with
        self.<lock>:``. No callers ⇒ no proof (an entrypoint nobody
        calls locked is exactly the bug)."""
        graph = self.graph(module)
        qual = graph.qual_of(method)
        if qual is None:
            return False
        owner = qual.rsplit(".", 1)[0]  # module.Class prefix
        sites = graph.call_sites_of(qual)
        if not sites:
            return False
        for caller_qual, call in sites:
            if caller_qual.rsplit(".", 1)[0] != owner:
                return False  # cross-class call: same-named lock ≠ same lock
            caller_info = graph.functions[caller_qual]
            caller_mod = graph.module_for(caller_info.module_rel) or module
            if not _with_locks(caller_mod, call, lock_attrs):
                return False
        return True
