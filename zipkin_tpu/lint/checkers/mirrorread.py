"""ZT10 — mirror-served reads stay off the aggregator lock.

ISSUE 14's tentpole took the query path off the aggregator lock: the
epoch-published read mirror (``tpu/mirror.py``) serves immutable
snapshots behind a seqlock generation stamp, and QUERY_SLO_r08's whole
p99 claim rests on the serve path never blocking. The regression shape
this rule fences is quiet and plausible-looking: someone "just adds" a
live-counter touch or a cache probe to the serve path, the call chain
re-enters ``_cached_read`` or an aggregator read method, and suddenly 8
reader threads queue on the lock again — correctness unaffected, the
SLO gone, and no unit test notices.

Functions opt in with a ``# zt-mirror-served: <reason>`` marker on the
``def`` header (multi-line signatures work, same mechanics as ZT09's
dispatch-critical marker). From each marked function the rule walks the
whole-program call graph restricted to the module (qualified-name
resolution: bare names bind lexically, ``self.m()`` binds to the
enclosing class, unknown attribute receivers fall back conservatively
to same-module defs — over-approximate rather than miss a helper) and
flags, anywhere reachable:

1. taking the aggregator lock itself — ``with X.lock:`` or
   ``X.lock.acquire(...)`` where the attribute is spelled exactly
   ``lock``. The repo's naming convention is load-bearing here: the
   InstrumentedRLock on the aggregator is the ONE lock published as a
   bare ``.lock`` attribute; private coordination locks are ``_lock``,
   ``_demand_lock``, ``_snapshot_lock``, ... and stay legal (the
   mirror's demand registry uses one).
2. calls into known lock-taking entrypoints (``LOCK_TAKERS``): the
   store's version-keyed memoizer and the aggregator read methods that
   acquire internally. These are correct answers on the WRONG path —
   each one re-serializes the reader behind ingest holds.

A marker without a reason is itself a finding (the ZT00 bar: opt-in
claims are reviewable statements, not magic words).

This rule stays same-module on purpose: chains that LEAVE the module
are ZT13's jurisdiction (reader isolation at full interprocedural
depth), so one bug yields one rule's finding.
"""

from __future__ import annotations

import ast
import re

from zipkin_tpu.lint.core import Checker, Module, register

_FUNC_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef)

MARKER_RE = re.compile(r"#\s*zt-mirror-served\b(?P<rest>.*)$")

# entrypoints known to acquire the aggregator lock (directly or one hop
# down): the store's memoizer + the aggregator's locked read surface.
# Conservative by NAME — a same-named method on another object is still
# a finding, because on a mirror-served path there should be no object
# answering these names at all.
LOCK_TAKERS = frozenset({
    "_cached_read",
    "dependency_edges",
    "dependency_matrices",
    "quantiles",
    "cardinalities",
    "sketch_overview",
    "merged_digest",
    "merged_sketches",
    "window_fully_rolled",
    "state_clone",
    "sync_pend_lanes",
    # ISSUE 15 time tier: the packed device pull of the unsealed
    # current bucket acquires the aggregator lock (flush-then-read),
    # and TimeTier.window() reaches it for any range past
    # sealed_through — windowed serves must come off the published
    # ``ttq:`` WindowAnswer, never recompute the merge per request
    "tt_read",
    "tt_sketches",
})


def _marker(module: Module, fn: ast.AST):
    """The zt-mirror-served marker on fn's header lines, if any."""
    end = fn.body[0].lineno if fn.body else fn.lineno + 1
    for line_no in range(fn.lineno, end):
        m = MARKER_RE.search(module.line_text(line_no))
        if m:
            return line_no, m.group("rest")
    return None


def _callee_name(func: ast.AST):
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_bare_lock_attr(node: ast.AST) -> bool:
    """True for ``<anything>.lock`` — the aggregator-lock spelling."""
    return isinstance(node, ast.Attribute) and node.attr == "lock"


@register
class MirrorServedLockAcquire(Checker):
    rule = "ZT10"
    severity = "error"
    name = "mirror-served-lock-acquire"
    doc = (
        "aggregator-lock acquisition (direct, or via known lock-taking "
        "helpers) reachable from functions marked zt-mirror-served"
    )
    hint = (
        "a mirror serve must stay lock-free: read the published "
        "snapshot, or move the locked work into the mirror publisher "
        "(one lock hold per epoch, not per query)"
    )

    def check(self, module: Module):
        roots = []
        for fn in ast.walk(module.tree):
            if not isinstance(fn, _FUNC_KINDS):
                continue
            marked = _marker(module, fn)
            if marked is None:
                continue
            _line, rest = marked
            if not rest.lstrip().startswith(":") or not rest.lstrip(": ").strip():
                yield self.found(
                    module, fn,
                    "zt-mirror-served marker without a reason — say WHY "
                    "this function serves lock-free "
                    "(# zt-mirror-served: <reason>)",
                )
            roots.append(fn)
        if not roots:
            return
        # qualified-name reachability within the module (cross-module
        # chains are ZT13's); conservative fallback edges included —
        # over-approximate rather than miss a helper
        graph = self.graph(module)
        root_quals = [q for q in map(graph.qual_of, roots) if q]
        reached = graph.reach(root_quals, same_module=True)
        seen = set()  # one scan per function even when several roots reach it
        for qual, (root, _depth, _pred) in reached.items():
            info = graph.functions[qual]
            if info.module_rel != module.rel or id(info.node) in seen:
                continue
            seen.add(id(info.node))
            yield from self._scan_function(
                module, info.node, graph.functions[root].name
            )

    def _scan_function(self, module: Module, fn: ast.AST, root: str):
        via = "" if fn.name == root else f" (via {fn.name}())"
        for node in ast.walk(fn):
            if isinstance(node, ast.With) or isinstance(node, ast.AsyncWith):
                for item in node.items:
                    if _is_bare_lock_attr(item.context_expr):
                        yield self.found(
                            module, node,
                            f"aggregator lock held inside mirror-served "
                            f"{root}(){via} — the serve path re-queues "
                            "readers behind ingest holds",
                        )
            elif isinstance(node, ast.Call):
                name = _callee_name(node.func)
                if (
                    name == "acquire"
                    and isinstance(node.func, ast.Attribute)
                    and _is_bare_lock_attr(node.func.value)
                ):
                    yield self.found(
                        module, node,
                        f"aggregator lock acquired inside mirror-served "
                        f"{root}(){via} — the serve path re-queues "
                        "readers behind ingest holds",
                    )
                elif name in LOCK_TAKERS:
                    yield self.found(
                        module, node,
                        f"lock-taking helper {name}() called from "
                        f"mirror-served {root}(){via} — this re-enters "
                        "the aggregator lock per query; serve the "
                        "published snapshot instead",
                    )
