"""ZT10 — mirror-served reads stay off the aggregator lock.

ISSUE 14's tentpole took the query path off the aggregator lock: the
epoch-published read mirror (``tpu/mirror.py``) serves immutable
snapshots behind a seqlock generation stamp, and QUERY_SLO_r08's whole
p99 claim rests on the serve path never blocking. The regression shape
this rule fences is quiet and plausible-looking: someone "just adds" a
live-counter touch or a cache probe to the serve path, the call chain
re-enters ``_cached_read`` or an aggregator read method, and suddenly 8
reader threads queue on the lock again — correctness unaffected, the
SLO gone, and no unit test notices.

Functions opt in with a ``# zt-mirror-served: <reason>`` marker on the
``def`` header (multi-line signatures work, same mechanics as ZT09's
dispatch-critical marker). From each marked function the rule walks the
local call graph (ZT07's conservative reachability: bare-name and
attribute calls both descend into same-module defs) and flags, anywhere
reachable:

1. taking the aggregator lock itself — ``with X.lock:`` or
   ``X.lock.acquire(...)`` where the attribute is spelled exactly
   ``lock``. The repo's naming convention is load-bearing here: the
   InstrumentedRLock on the aggregator is the ONE lock published as a
   bare ``.lock`` attribute; private coordination locks are ``_lock``,
   ``_demand_lock``, ``_snapshot_lock``, ... and stay legal (the
   mirror's demand registry uses one).
2. calls into known lock-taking entrypoints (``LOCK_TAKERS``): the
   store's version-keyed memoizer and the aggregator read methods that
   acquire internally. These are correct answers on the WRONG path —
   each one re-serializes the reader behind ingest holds.

A marker without a reason is itself a finding (the ZT00 bar: opt-in
claims are reviewable statements, not magic words).
"""

from __future__ import annotations

import ast
import re

from zipkin_tpu.lint.core import Checker, Module, register

_FUNC_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef)

MARKER_RE = re.compile(r"#\s*zt-mirror-served\b(?P<rest>.*)$")

# entrypoints known to acquire the aggregator lock (directly or one hop
# down): the store's memoizer + the aggregator's locked read surface.
# Conservative by NAME — a same-named method on another object is still
# a finding, because on a mirror-served path there should be no object
# answering these names at all.
LOCK_TAKERS = frozenset({
    "_cached_read",
    "dependency_edges",
    "dependency_matrices",
    "quantiles",
    "cardinalities",
    "sketch_overview",
    "merged_digest",
    "merged_sketches",
    "window_fully_rolled",
    "state_clone",
    "sync_pend_lanes",
    # ISSUE 15 time tier: the packed device pull of the unsealed
    # current bucket acquires the aggregator lock (flush-then-read),
    # and TimeTier.window() reaches it for any range past
    # sealed_through — windowed serves must come off the published
    # ``ttq:`` WindowAnswer, never recompute the merge per request
    "tt_read",
    "tt_sketches",
})


def _marker(module: Module, fn: ast.AST):
    """The zt-mirror-served marker on fn's header lines, if any."""
    end = fn.body[0].lineno if fn.body else fn.lineno + 1
    for line_no in range(fn.lineno, end):
        m = MARKER_RE.search(module.line_text(line_no))
        if m:
            return line_no, m.group("rest")
    return None


def _callee_name(func: ast.AST):
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_bare_lock_attr(node: ast.AST) -> bool:
    """True for ``<anything>.lock`` — the aggregator-lock spelling."""
    return isinstance(node, ast.Attribute) and node.attr == "lock"


@register
class MirrorServedLockAcquire(Checker):
    rule = "ZT10"
    severity = "error"
    name = "mirror-served-lock-acquire"
    doc = (
        "aggregator-lock acquisition (direct, or via known lock-taking "
        "helpers) reachable from functions marked zt-mirror-served"
    )
    hint = (
        "a mirror serve must stay lock-free: read the published "
        "snapshot, or move the locked work into the mirror publisher "
        "(one lock hold per epoch, not per query)"
    )

    def check(self, module: Module):
        defs = {}
        for node in ast.walk(module.tree):
            if isinstance(node, _FUNC_KINDS):
                defs.setdefault(node.name, node)
        roots = []
        for fn in defs.values():
            marked = _marker(module, fn)
            if marked is None:
                continue
            _line, rest = marked
            if not rest.lstrip().startswith(":") or not rest.lstrip(": ").strip():
                yield self.found(
                    module, fn,
                    "zt-mirror-served marker without a reason — say WHY "
                    "this function serves lock-free "
                    "(# zt-mirror-served: <reason>)",
                )
            roots.append(fn)
        if not roots:
            return
        # reachability over local defs (ZT07's walk: attribute calls
        # descend too — over-approximate rather than miss a helper)
        reached = {}
        stack = [(d, d.name) for d in roots]
        while stack:
            fn, root = stack.pop()
            if fn.name in reached:
                continue
            reached[fn.name] = (fn, root)
            for call in ast.walk(fn):
                if isinstance(call, ast.Call):
                    tgt = defs.get(_callee_name(call.func))
                    if tgt is not None and tgt.name not in reached:
                        stack.append((tgt, root))
        for fn, root in reached.values():
            yield from self._scan_function(module, fn, root)

    def _scan_function(self, module: Module, fn: ast.AST, root: str):
        via = "" if fn.name == root else f" (via {fn.name}())"
        for node in ast.walk(fn):
            if isinstance(node, ast.With) or isinstance(node, ast.AsyncWith):
                for item in node.items:
                    if _is_bare_lock_attr(item.context_expr):
                        yield self.found(
                            module, node,
                            f"aggregator lock held inside mirror-served "
                            f"{root}(){via} — the serve path re-queues "
                            "readers behind ingest holds",
                        )
            elif isinstance(node, ast.Call):
                name = _callee_name(node.func)
                if (
                    name == "acquire"
                    and isinstance(node.func, ast.Attribute)
                    and _is_bare_lock_attr(node.func.value)
                ):
                    yield self.found(
                        module, node,
                        f"aggregator lock acquired inside mirror-served "
                        f"{root}(){via} — the serve path re-queues "
                        "readers behind ingest holds",
                    )
                elif name in LOCK_TAKERS:
                    yield self.found(
                        module, node,
                        f"lock-taking helper {name}() called from "
                        f"mirror-served {root}(){via} — this re-enters "
                        "the aggregator lock per query; serve the "
                        "published snapshot instead",
                    )
