"""ZT08 — flight-recorder stage discipline.

The obs tier (``zipkin_tpu/obs``) is host-side instrumentation with a
CLOSED stage taxonomy (``obs.stages.STAGES``): dashboards, budgets, and
the /statusz schema key off the fixed name set, and the recorder indexes
histograms by ``STAGE_INDEX`` — an unknown name is a hot-path KeyError.
Two shapes are flagged:

1. ``record()`` reachable from device-traced code. ``obs.record`` is
   Python host code (thread-local lists, a seqlock counter): inside a
   ``jax.jit``/``shard_map`` region it would execute once at trace time
   — recording a single bogus near-zero sample, then silently never
   again — or fail outright under tracing. Traced defs are those
   decorated with (or passed to) ``jax.jit``/``shard_map``, plus
   everything they reach through the whole-program call graph's
   RESOLVED edges (lexical/self/import resolution) at cross-module
   depth. Fallback name-keyed edges are deliberately excluded from this
   walk: traced code calling ``x.m()`` on an unknown receiver must not
   smear "traced" onto every same-named host method — precision rules
   ride resolved edges, fence rules keep the over-approximation.
2. A ``record()`` stage argument that is not a string literal from the
   taxonomy. Literal-only keeps every stage name greppable and lets
   this rule verify membership statically; a dynamic stage would also
   dodge the budget table. To add a stage, extend ``obs/stages.py``
   (name + budget) — see its docstring — and this rule learns it
   automatically.

Recognized record shapes: ``obs.record(...)``, ``RECORDER.record(...)``,
``obs.RECORDER.record(...)``, and a bare ``record(...)`` when the module
imports it ``from zipkin_tpu.obs import record``. ``record_relayed`` —
the no-selfspan variant the fan-out dispatcher uses for worker-measured
stages — is held to the same discipline (literal taxonomy stage, host
code only).

The windowed-telemetry and device-observatory hooks (ISSUE 9) are host
instrumentation too: ``WINDOWS.tick()`` / ``tick_if_due()`` mutate ring
state under locks, ``OBSERVATORY.wrap()`` / ``observe()`` time dispatch
walls with ``perf_counter``. Inside a traced region each would burn in a
trace-time constant or fail under tracing, so traced-reachability flags
them alongside ``record`` (roots ``WINDOWS``/``OBSERVATORY``/
``obs_device``, plus bare imports from ``zipkin_tpu.obs.windows`` /
``zipkin_tpu.obs.device``).
"""

from __future__ import annotations

import ast

from zipkin_tpu.lint.core import Checker, Module, register
from zipkin_tpu.lint.taint import _root_name
from zipkin_tpu.obs.stages import STAGES

_FUNC_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef)

_RECORD_ATTRS = {"record", "record_relayed"}
_RECORD_ROOTS = {"obs", "RECORDER"}
# windows/device/shadow hooks: host-only for the same reason record is;
# flagged by the traced-reach pass but exempt from stage-arg validation
# (they take no stage). The accuracy-observatory hooks (ISSUE 10) join
# the set: offer_* are bounded-deque appends and drain/rollup mutate
# shadow state under locks — a traced region would capture one
# trace-time batch forever (or fail under tracing).
_HOOK_ATTRS = {
    "tick", "tick_if_due", "observe", "wrap",
    "offer_cols", "offer_fused", "offer_spans", "drain",
    "rollup", "maybe_rollup",
    # critical-path tracer (ISSUE 11): ledger writes are seqlocked
    # shared-memory mutation + perf_counter reads, and the stitcher
    # folds under a lock — all host-only. A traced region would stamp
    # one trace-time interval forever (or fail under tracing).
    "stamp", "stamp_active", "alloc", "ack", "abandon", "release",
    "stitch", "calibrate", "set_active", "set_active_group",
    "clear_active",
    # query-plane observatory (ISSUE 12): trace arming is thread-local
    # state, the instrumented-lock wrapper measures perf_counter waits,
    # and the stitcher folds under a lock — all host-only. A traced
    # region would bake one trace-time interval (or fail under tracing).
    "begin", "finish", "relabel", "lock_label",
}
_HOOK_ROOTS = {
    "obs", "WINDOWS", "OBSERVATORY", "obs_device", "SHADOW", "ACCURACY",
    "critpath", "_critpath", "CRITPATH",
    "querytrace", "_querytrace", "QUERYTRACE",
}
_HOOK_MODULES = {
    "zipkin_tpu.obs.windows", "zipkin_tpu.obs.device",
    "zipkin_tpu.obs.shadow", "zipkin_tpu.obs.accuracy",
    "zipkin_tpu.obs.critpath", "zipkin_tpu.obs.querytrace",
}
_TRACE_NAMES = {"jit", "shard_map"}


def _is_trace_call(node: ast.AST) -> bool:
    """jax.jit(...), jit(...), shard_map(...), or a partial over one."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _TRACE_NAMES:
        return True
    if isinstance(f, ast.Name) and f.id in _TRACE_NAMES:
        return True
    if (
        isinstance(f, ast.Attribute)
        and f.attr == "partial"
        and node.args
        and _is_trace_call(ast.Call(func=node.args[0], args=[], keywords=[]))
    ):
        return True
    return False


@register
class ObsStageDiscipline(Checker):
    rule = "ZT08"
    severity = "error"
    name = "obs-stage-discipline"
    doc = (
        "obs.record inside device-traced code; stage args outside the "
        "closed taxonomy"
    )
    hint = (
        "record stages from host code only, with a string literal from "
        "obs.stages.STAGES; to add a stage extend obs/stages.py"
    )

    whole_program = True

    def check_program(self, program):
        aliases = {}  # module rel -> (record aliases, hook aliases)
        traced_roots = []
        for module in program.modules:
            if "zipkin_tpu" not in module.imported_roots:
                continue
            bare, bare_hooks = self._bare_aliases(module)
            aliases[module.rel] = (bare, bare_hooks)
            records = [
                node
                for node in ast.walk(module.tree)
                if self._is_record_call(node, bare)
            ]
            yield from self._check_stage_args(module, records)
            if module.imported_roots & {"jax", "jnp"}:
                traced_roots.extend(
                    q for q in map(
                        program.qual_of, self._traced_defs(module)
                    ) if q
                )
        if not traced_roots:
            return
        # traced-reach rides RESOLVED edges only (module docstring)
        reached = program.reach(traced_roots, resolved_only=True)
        for qual, (root, _d, _p) in reached.items():
            info = program.functions[qual]
            module = program.module_for(info.module_rel)
            if module is None:
                continue
            if module.rel not in aliases:
                aliases[module.rel] = self._bare_aliases(module)
            bare, bare_hooks = aliases[module.rel]
            yield from self._scan_traced(
                module, info.node, program.functions[root].name,
                bare, bare_hooks,
            )

    # -- record/hook call recognition --------------------------------------

    def _bare_aliases(self, module: Module):
        """(record aliases, hook aliases) pulled in by bare imports."""
        records, hooks = set(), set()
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ImportFrom):
                continue
            if node.module == "zipkin_tpu.obs":
                for a in node.names:
                    if a.name in _RECORD_ATTRS:
                        records.add(a.asname or a.name)
            elif node.module in _HOOK_MODULES:
                for a in node.names:
                    if a.name in _HOOK_ATTRS:
                        hooks.add(a.asname or a.name)
        return records, hooks

    def _is_record_call(self, node: ast.AST, bare: set) -> bool:
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _RECORD_ATTRS:
            return _root_name(f) in _RECORD_ROOTS
        return isinstance(f, ast.Name) and f.id in bare

    def _is_hook_call(self, node: ast.AST, bare_hooks: set) -> bool:
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _HOOK_ATTRS:
            return _root_name(f) in _HOOK_ROOTS
        return isinstance(f, ast.Name) and f.id in bare_hooks

    # -- shape 2: stage names come from the closed taxonomy ----------------

    def _check_stage_args(self, module: Module, records):
        for call in records:
            arg = call.args[0] if call.args else None
            if arg is None:
                for kw in call.keywords:
                    if kw.arg == "stage":
                        arg = kw.value
            if arg is None:
                yield self.found(module, call, "record() call with no stage")
                continue
            if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
                yield self.found(
                    module,
                    call,
                    "record() stage must be a string literal — dynamic "
                    "names dodge the taxonomy and the budget table",
                )
                continue
            if arg.value not in STAGES:
                yield self.found(
                    module,
                    call,
                    f"unknown stage {arg.value!r} — not in obs.stages."
                    "STAGES (histograms/budgets/statusz key off the "
                    "closed set)",
                )

    # -- shape 1: no recording inside device-traced code -------------------

    def _traced_defs(self, module: Module):
        """Defs decorated with (or passed by name to) jit/shard_map."""
        defs = {}
        for node in ast.walk(module.tree):
            if isinstance(node, _FUNC_KINDS):
                defs.setdefault(node.name, node)
        traced = []
        for fn in defs.values():
            if any(_is_trace_call(d) or _trace_target(d) for d in fn.decorator_list):
                traced.append(fn)
        for node in ast.walk(module.tree):
            if _is_trace_call(node):
                for arg in node.args:
                    tgt = defs.get(arg.id) if isinstance(arg, ast.Name) else None
                    if tgt is not None:
                        traced.append(tgt)
        return traced

    def _scan_traced(self, module, fn, root, bare, bare_hooks):
        for node in ast.walk(fn):
            if self._is_record_call(node, bare):
                where = "" if fn.name == root else f" (via {fn.name}())"
                yield self.found(
                    module,
                    node,
                    f"obs.record inside device-traced {root}(){where} "
                    "— host-side instrumentation runs once at trace "
                    "time, then never again",
                )
            elif self._is_hook_call(node, bare_hooks):
                where = "" if fn.name == root else f" (via {fn.name}())"
                yield self.found(
                    module,
                    node,
                    f"obs windows/device hook inside device-traced "
                    f"{root}(){where} — ring/registry mutation is host "
                    "code; under tracing it burns in a trace-time "
                    "constant",
                )


def _trace_target(dec: ast.AST) -> bool:
    """Bare (non-call) jit/shard_map decorator: ``@jax.jit``/``@jit``."""
    if isinstance(dec, ast.Attribute):
        return dec.attr in _TRACE_NAMES
    return isinstance(dec, ast.Name) and dec.id in _TRACE_NAMES
