"""ZT00 — suppression hygiene (meta-rule, always active).

The acceptance bar for every other rule is "fixed or suppressed WITH a
reason"; this rule makes the linter enforce its own bar: a
``# zt-lint: disable=...`` pragma whose rule list is followed by no
justification text is itself a finding. ZT00 cannot be deselected
(core.run_paths pins it) — otherwise reasonless pragmas rot silently.
"""

from __future__ import annotations

from zipkin_tpu.lint.core import Checker, Finding, Module, register


@register
class SuppressionHygiene(Checker):
    rule = "ZT00"
    severity = "error"
    name = "suppression-hygiene"
    doc = "zt-lint pragma without a justification"
    hint = "append the reason: # zt-lint: disable=ZTxx — why this is safe"

    def check(self, module: Module):
        for pragma in module.pragmas:
            if not pragma.reason:
                yield Finding(
                    rule=self.rule,
                    severity=self.severity,
                    path=module.rel,
                    line=pragma.line,
                    col=0,
                    message=(
                        "suppression without justification: "
                        f"disable={','.join(sorted(pragma.rules))}"
                    ),
                    hint=self.hint,
                )
