"""ZT13 — reader isolation at full interprocedural depth.

ROADMAP item 3 (scale-out read serving) moves query serving into
processes that map the published state read-only: in that world a
reader that acquires the aggregator lock doesn't just lose the p99 SLO
— it deadlocks or faults, because the lock lives in the writer process.
The invariant worth that migration is "readers never take the
aggregator lock", and it has to hold through EVERY call chain, not
just the ones ZT10 can see inside one module. This rule is the static
gate the multi-process front end will be built against.

Roots are reader entrypoints, program-wide:

- functions marked ``# zt-mirror-served: <reason>`` (ZT10's marker —
  today's lock-free serve surface), and
- functions marked ``# zt-reader-process: <reason>`` — FUTURE
  reader-process entrypoints staked out before the process split
  exists, so the isolation proof precedes the migration. A marker
  without a reason is itself a finding (the ZT00 bar).

From each root the whole-program call graph is walked to
``DEFAULT_DEPTH`` (conservative edges included: an over-approximate
walk may flag a chain the runtime never takes, but it cannot miss one
the resolver can see). In every reached function, cross-module from
the root, these are findings:

- ``with X.lock:`` / ``X.lock.acquire(...)`` — the bare-``.lock``
  spelling is the aggregator lock by repo convention (ZT10's rule 1);
- ``with X.<attr>:`` / ``X.<attr>.acquire(...)`` where ``<attr>`` is
  assigned from ``InstrumentedRLock(...)`` ANYWHERE in the program —
  renaming the lock does not launder the acquire.

Sinks in the ROOT'S OWN module are ZT10's jurisdiction and skipped
here, so one bug yields one rule's finding; ZT13 is precisely the
cross-module depth ZT10 never had.
"""

from __future__ import annotations

import ast
import re
from typing import Set

from zipkin_tpu.lint.core import Checker, register
from zipkin_tpu.lint.checkers.mirrorread import _is_bare_lock_attr, _marker

_FUNC_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef)

READER_MARKER_RE = re.compile(r"#\s*zt-reader-process\b(?P<rest>.*)$")


def _rlock_attr_names(program) -> Set[str]:
    """Attribute/name bindings assigned from ``InstrumentedRLock(...)``
    anywhere in the program — the aggregator-lock aliases."""
    names: Set[str] = set()
    for module in program.modules:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Assign):
                continue
            v = node.value
            if not (
                isinstance(v, ast.Call)
                and (
                    (isinstance(v.func, ast.Name)
                     and v.func.id == "InstrumentedRLock")
                    or (isinstance(v.func, ast.Attribute)
                        and v.func.attr == "InstrumentedRLock")
                )
            ):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute):
                    names.add(tgt.attr)
                elif isinstance(tgt, ast.Name):
                    names.add(tgt.id)
    return names


@register
class ReaderIsolation(Checker):
    rule = "ZT13"
    severity = "error"
    name = "reader-isolation"
    doc = (
        "aggregator-lock / InstrumentedRLock acquires reachable cross-"
        "module from mirror-served or reader-process entrypoints"
    )
    hint = (
        "a reader entrypoint must stay lock-free at every depth: serve "
        "the published snapshot, or move locked work into the publisher"
    )
    whole_program = True

    def check_program(self, program):
        rlock_attrs = _rlock_attr_names(program) | {"lock"}
        roots = []
        for module in program.modules:
            for fn in ast.walk(module.tree):
                if not isinstance(fn, _FUNC_KINDS):
                    continue
                marked = _reader_marker(module, fn)
                if marked is not None:
                    _line, rest = marked
                    if not rest.lstrip().startswith(":") \
                            or not rest.lstrip(": ").strip():
                        yield self.found(
                            module, fn,
                            "zt-reader-process marker without a reason — "
                            "say WHY this entrypoint must stay reader-"
                            "isolated (# zt-reader-process: <reason>)",
                        )
                if marked is None and _marker(module, fn) is None:
                    continue
                qual = program.qual_of(fn)
                if qual is not None:
                    roots.append(qual)
        if not roots:
            return
        reached = program.reach(roots)
        for qual, (root, depth, _pred) in reached.items():
            info = program.functions[qual]
            root_info = program.functions[root]
            if info.module_rel == root_info.module_rel:
                continue  # same-module chains are ZT10's jurisdiction
            module = program.module_for(info.module_rel)
            if module is None:
                continue
            via = program.via_chain(reached, qual)
            yield from self._scan_function(
                module, info.node, root_info, via, rlock_attrs
            )

    def _scan_function(self, module, fn, root_info, via, rlock_attrs):
        where = (
            f"reached from reader entrypoint {root_info.name}() "
            f"[{root_info.module_rel}]{via}"
        )
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if self._is_rlock_attr(item.context_expr, rlock_attrs):
                        yield self.found(
                            module, node,
                            f"aggregator lock held in {fn.name}() — "
                            f"{where}; a reader process cannot take the "
                            "writer's lock",
                        )
            elif isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr == "acquire"
                    and self._is_rlock_attr(f.value, rlock_attrs)
                ):
                    yield self.found(
                        module, node,
                        f"aggregator lock acquired in {fn.name}() — "
                        f"{where}; a reader process cannot take the "
                        "writer's lock",
                    )

    @staticmethod
    def _is_rlock_attr(node: ast.AST, rlock_attrs: Set[str]) -> bool:
        if _is_bare_lock_attr(node):
            return True
        return isinstance(node, ast.Attribute) and node.attr in rlock_attrs


def _reader_marker(module, fn):
    """The zt-reader-process marker on fn's header lines, if any."""
    end = fn.body[0].lineno if fn.body else fn.lineno + 1
    for line_no in range(fn.lineno, end):
        m = READER_MARKER_RE.search(module.line_text(line_no))
        if m:
            return line_no, m.group("rest")
    return None
