"""ZT03 — jit-recompile hazards.

Remote-tunnel compiles take minutes (ARCHITECTURE.md warm-up note), so a
``jax.jit`` that re-traces at serving time is a production stall, not a
micro-inefficiency. Two shapes are flagged:

1. ``jax.jit(...)`` *constructed* inside a loop body, or inside a plain
   function/method (a fresh jit wrapper per call has a fresh trace
   cache: every call recompiles). Module scope is fine; so is any
   enclosing function cached with ``functools.lru_cache``/``cache`` —
   the repo's ``_compiled_programs`` factory pattern.
2. A *known-jitted* callable (bound from ``jax.jit(...)`` without
   ``static_argnums``/``static_argnames``) invoked with a varying
   Python scalar positional arg — a loop variable, or an ``int()``/
   ``float()`` coercion at the call site. Each distinct value traces a
   new program (Python scalars hash into the jit cache key by value
   when weak-typed promotion fails to canonicalize them); wrap in
   ``jnp.uint32(...)``/``jnp.asarray`` or declare the arg static.
"""

from __future__ import annotations

import ast

from zipkin_tpu.lint.core import Checker, Module, register
from zipkin_tpu.lint.taint import _root_name

_FUNC_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef)
_CACHE_DECORATORS = {"lru_cache", "cache", "cached_property"}


def _is_jit_call(node: ast.AST) -> bool:
    """jax.jit(...), jit(...), or functools.partial(jax.jit, ...)."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "jit" and _root_name(f) == "jax":
        return True
    if isinstance(f, ast.Name) and f.id == "jit":
        return True
    if (
        isinstance(f, ast.Attribute)
        and f.attr == "partial"
        and node.args
        and _is_jit_call(ast.Call(func=node.args[0], args=[], keywords=[]))
    ):
        return True
    return False


def _decorator_names(fn: ast.AST):
    for dec in getattr(fn, "decorator_list", ()):
        target = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(target, ast.Attribute):
            yield target.attr
        elif isinstance(target, ast.Name):
            yield target.id


def _jit_has_static(call: ast.Call) -> bool:
    return any(
        k.arg in ("static_argnums", "static_argnames") for k in call.keywords
    )


@register
class RecompileHazards(Checker):
    rule = "ZT03"
    severity = "error"
    name = "jit-recompile-hazards"
    doc = "jax.jit per call/iteration; varying scalars into jitted callables"
    hint = (
        "hoist jax.jit to module scope or an lru_cache'd factory; pass "
        "scalars as jnp arrays (jnp.uint32(x)) or declare them static"
    )

    def check(self, module: Module):
        if not module.imported_roots & {"jax", "jnp"}:
            return
        yield from self._jit_construction_sites(module)
        yield from self._scalar_args_to_jitted(module)

    # -- shape 1: where is jax.jit constructed? ---------------------------

    def _jit_construction_sites(self, module: Module):
        # decorator expressions evaluate at def time (module scope for
        # top-level defs) — @functools.partial(jax.jit, ...) is NOT a
        # per-call construction
        in_decorator = set()
        for fn in ast.walk(module.tree):
            for dec in getattr(fn, "decorator_list", ()):
                in_decorator.update(ast.walk(dec))
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call) and _is_jit_call(node)):
                continue
            if node in in_decorator:
                continue
            loop = next(
                iter(module.enclosing(node, (ast.For, ast.While))), None
            )
            if loop is not None:
                yield self.found(
                    module,
                    node,
                    "jax.jit constructed inside a loop — every iteration "
                    "builds a wrapper with an empty trace cache",
                )
                continue
            enclosing_fns = list(module.enclosing(node, _FUNC_KINDS))
            if not enclosing_fns:
                continue  # module scope: compiled once per import
            if any(
                set(_decorator_names(fn)) & _CACHE_DECORATORS
                for fn in enclosing_fns
            ):
                continue  # the cached-factory pattern (_compiled_programs)
            yield self.found(
                module,
                node,
                f"jax.jit constructed inside {enclosing_fns[0].name}() — "
                "a fresh wrapper (and recompile) per call; hoist to "
                "module scope or cache the factory",
            )

    # -- shape 2: varying Python scalars hitting jitted callables ---------

    def _scalar_args_to_jitted(self, module: Module):
        # names bound from jax.jit(...) without static declarations, at
        # any assignment site in the module (module or function scope)
        jitted: dict = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if _is_jit_call(node.value) and not _jit_has_static(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            jitted[t.id] = node.value
        if not jitted:
            return
        for node in ast.walk(module.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in jitted
            ):
                continue
            for arg in node.args:
                reason = self._varying_scalar(module, node, arg)
                if reason:
                    yield self.found(
                        module,
                        node,
                        f"jitted callable {node.func.id}() takes a "
                        f"{reason} positionally — each distinct value "
                        "recompiles (not declared static)",
                    )
                    break

    def _varying_scalar(self, module: Module, call: ast.Call, arg: ast.AST):
        if (
            isinstance(arg, ast.Call)
            and isinstance(arg.func, ast.Name)
            and arg.func.id in ("int", "float")
        ):
            return "Python-scalar int()/float() coercion"
        if isinstance(arg, ast.Name):
            for loop in module.enclosing(call, (ast.For,)):
                t = loop.target
                names = (
                    {t.id}
                    if isinstance(t, ast.Name)
                    else {
                        el.id
                        for el in getattr(t, "elts", ())
                        if isinstance(el, ast.Name)
                    }
                )
                if arg.id in names:
                    return f"loop-varying Python scalar ({arg.id})"
        return None
