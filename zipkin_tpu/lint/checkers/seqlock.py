"""ZT11 — shm seqlock write/read discipline on registered regions.

The cross-process tiers share mutable state with NO locks: a writer
makes the generation word odd, mutates the payload, then re-evens the
generation; a reader snapshots the generation, copies, and retries when
the generation was odd or changed. Nothing but convention stops a new
method from writing a payload word outside the bracket — and a torn
read of that word is a once-a-week production mystery, not a test
failure. This rule makes the convention mechanical over the four
REGISTERED regions:

==================  =========================  =========================
region              generation word(s)         protected payload
==================  =========================  =========================
tpu/ring.py         ``hdr[_S_GEN]``            ``_S_PIDX``..``_S_PUBLISH_NS``
                    (slot headers)             (the ``_S_*`` payload words)
tpu/mirror.py       ``self.gen``               ``self._snap``
                    (epoch)
obs/critpath.py     ``_OFF_GEN_D``/``_OFF_GEN_W``  ``_OFF_N_D``/``_OFF_N_W``/
                    (ledger slots)             ``_OFF_D_IV``/``_OFF_W_IV``
obs/recorder.py     ``h.gen``                  ``counts``/``sums``/``maxes``
                    (snapshots)
==================  =========================  =========================

State-machine words (ring ``_S_STATE``/``_S_PID``, critpath
``_OFF_STATE``/``_OFF_FLAGS``/timestamps) are deliberately NOT
protected: they are single-word transitions whose visibility protocol
is the state value itself, not the generation.

Three shapes are flagged, per function in a region module
(``__init__`` is exempt — construction precedes sharing):

- **W1 unstamped write**: a protected-payload write in a function with
  no generation stamp (``gen_word += 1``). Relaxed interprocedurally:
  when every in-graph caller is itself a stamping function of the same
  module, the callee inherits the caller's bracket (split-helper
  idiom). A function with ONE stamp participates in a cross-function
  bracket (ring: ``try_claim`` odds, ``publish`` re-evens) and passes.
- **W2 write outside the bracket**: in a function with a full bracket
  (two or more stamps), a protected write before the first or after
  the last stamp.
- **R1 unvalidated read**: a pure reader (no protected writes, no
  stamps) that consults the generation word exactly ONCE alongside a
  protected read — it can observe a torn value and has no way to know.
  Zero generation reads is legal (the function reads an immutable
  copy someone else validated); two or more is the retry/recheck
  idiom this rule cannot distinguish further syntactically.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from zipkin_tpu.lint.core import Checker, Module, register

_FUNC_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef)

# ring payload words: every _S_* slot-header constant EXCEPT the
# generation itself and the state-machine words
_RING_EXEMPT = {"_S_GEN", "_S_STATE", "_S_PID"}


class _Region:
    """One registered seqlock region: how to spot its generation word
    and its protected payload in source."""

    __slots__ = ("suffix", "label", "gen_kind", "gen_names",
                 "prot_kind", "prot_names", "prot_prefix", "prot_exempt")

    def __init__(self, suffix, label, gen_kind, gen_names, prot_kind,
                 prot_names=frozenset(), prot_prefix="",
                 prot_exempt=frozenset()):
        self.suffix = suffix
        self.label = label
        self.gen_kind = gen_kind          # "index" | "attr"
        self.gen_names = gen_names
        self.prot_kind = prot_kind        # "index" | "index_prefix" | "attr"
        self.prot_names = prot_names
        self.prot_prefix = prot_prefix
        self.prot_exempt = prot_exempt

    # -- matchers ---------------------------------------------------------

    def _index_names(self, node: ast.Subscript) -> Set[str]:
        return {
            n.id for n in ast.walk(node.slice) if isinstance(n, ast.Name)
        }

    def is_gen(self, node: ast.AST) -> bool:
        if self.gen_kind == "index":
            return isinstance(node, ast.Subscript) and bool(
                self._index_names(node) & self.gen_names
            )
        return isinstance(node, ast.Attribute) and node.attr in self.gen_names

    def is_protected(self, node: ast.AST) -> bool:
        if self.prot_kind == "attr":
            # h.counts, h.counts[i], self._snap ...
            if isinstance(node, ast.Subscript):
                node = node.value
            return (
                isinstance(node, ast.Attribute)
                and node.attr in self.prot_names
            )
        if not isinstance(node, ast.Subscript):
            return False
        names = self._index_names(node)
        if self.prot_kind == "index_prefix":
            return any(
                n.startswith(self.prot_prefix) and n not in self.prot_exempt
                for n in names
            )
        return bool(names & self.prot_names)


REGIONS: Tuple[_Region, ...] = (
    _Region(
        suffix="zipkin_tpu/tpu/ring.py",
        label="span-ring slot header",
        gen_kind="index", gen_names=frozenset({"_S_GEN"}),
        prot_kind="index_prefix", prot_prefix="_S_",
        prot_exempt=frozenset(_RING_EXEMPT),
    ),
    _Region(
        suffix="zipkin_tpu/tpu/mirror.py",
        label="mirror epoch",
        gen_kind="attr", gen_names=frozenset({"gen"}),
        prot_kind="attr", prot_names=frozenset({"_snap"}),
    ),
    _Region(
        suffix="zipkin_tpu/obs/critpath.py",
        label="critpath ledger slot",
        gen_kind="index", gen_names=frozenset({"_OFF_GEN_D", "_OFF_GEN_W"}),
        prot_kind="index",
        prot_names=frozenset({"_OFF_N_D", "_OFF_N_W", "_OFF_D_IV",
                              "_OFF_W_IV"}),
    ),
    _Region(
        suffix="zipkin_tpu/obs/recorder.py",
        label="recorder histogram",
        gen_kind="attr", gen_names=frozenset({"gen"}),
        prot_kind="attr", prot_names=frozenset({"counts", "sums", "maxes"}),
    ),
)


def _store_targets(stmt: ast.AST):
    if isinstance(stmt, ast.Assign):
        return stmt.targets
    if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        return [stmt.target]
    return []


@register
class SeqlockDiscipline(Checker):
    rule = "ZT11"
    severity = "error"
    name = "seqlock-discipline"
    doc = (
        "registered shm seqlock regions: payload writes bracketed by "
        "generation stamps; readers validate the generation"
    )
    hint = (
        "bracket payload writes with gen += 1 (odd) ... gen += 1 "
        "(even); readers re-read the generation after copying"
    )

    def check(self, module: Module):
        region = None
        for r in REGIONS:
            if module.rel.endswith(r.suffix) or module.rel == r.suffix:
                region = r
                break
        if region is None:
            return
        stampers: Set[str] = set()
        facts: List[Tuple[ast.AST, Dict]] = []
        for fn in ast.walk(module.tree):
            if not isinstance(fn, _FUNC_KINDS) or fn.name == "__init__":
                continue
            f = self._function_facts(region, fn)
            facts.append((fn, f))
            if f["stamps"]:
                stampers.add(fn.name)
        for fn, f in facts:
            yield from self._judge(module, region, fn, f, stampers)

    # -- per-function fact extraction -------------------------------------

    def _function_facts(self, region: _Region, fn: ast.AST) -> Dict:
        stamps: List[int] = []      # lineno of each gen_word += 1
        writes: List[ast.AST] = []  # protected-payload store nodes
        prot_reads = 0
        gen_reads = 0
        own = [n for n in ast.walk(fn)
               if not (isinstance(n, _FUNC_KINDS) and n is not fn)]
        # exclude nested defs' bodies: they are their own functions
        nested: Set[int] = set()
        for n in ast.walk(fn):
            if isinstance(n, _FUNC_KINDS) and n is not fn:
                nested.update(id(x) for x in ast.walk(n))
        for node in own:
            if id(node) in nested:
                continue
            if isinstance(node, ast.AugAssign) and isinstance(
                node.op, ast.Add
            ) and region.is_gen(node.target):
                stamps.append(node.lineno)
                continue
            for tgt in _store_targets(node):
                if region.is_protected(tgt):
                    writes.append(tgt)
            if isinstance(node, (ast.Subscript, ast.Attribute)) and \
                    isinstance(getattr(node, "ctx", None), ast.Load):
                if region.is_protected(node):
                    prot_reads += 1
                elif region.is_gen(node):
                    gen_reads += 1
        return {
            "stamps": sorted(stamps),
            "writes": writes,
            "prot_reads": prot_reads,
            "gen_reads": gen_reads,
        }

    # -- verdicts ---------------------------------------------------------

    def _judge(self, module, region, fn, f, stampers):
        stamps, writes = f["stamps"], f["writes"]
        if writes and not stamps:
            if not self._callers_all_stamp(module, fn, stampers):
                for w in writes:
                    yield self.found(
                        module, w,
                        f"unstamped write to the {region.label} — no "
                        f"generation stamp anywhere in {fn.name}(), so a "
                        "concurrent reader can observe this word torn",
                    )
            return
        if writes and len(stamps) >= 2:
            first, last = stamps[0], stamps[-1]
            for w in writes:
                if w.lineno < first or w.lineno > last:
                    side = "before the odd" if w.lineno < first else \
                        "after the closing even"
                    yield self.found(
                        module, w,
                        f"{region.label} write {side} generation stamp "
                        f"in {fn.name}() — outside the seqlock bracket",
                    )
            return
        if not writes and not stamps and f["prot_reads"]:
            if f["gen_reads"] == 1:
                yield self.found(
                    module, fn,
                    f"{fn.name}() reads the {region.label} payload but "
                    "samples the generation only once — a torn copy "
                    "cannot be detected; re-read the generation after "
                    "copying and retry on odd/changed",
                )

    def _callers_all_stamp(self, module, fn, stampers) -> bool:
        """Split-helper relaxation: every in-graph caller (same module)
        is a stamping function, so the callee runs inside the caller's
        bracket. No graph or no callers ⇒ no relaxation."""
        if self.program is None:
            return False
        qual = self.program.qual_of(fn)
        if qual is None:
            return False
        callers = [
            self.program.functions[c]
            for c in self.program.callers_of(qual)
            if c in self.program.functions
        ]
        callers = [c for c in callers if c.module_rel == module.rel]
        return bool(callers) and all(c.name in stampers for c in callers)
