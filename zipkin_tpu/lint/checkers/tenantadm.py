"""ZT14 — tenant-admission coverage for ingest boundaries.

ISSUE 18 makes tenant isolation a fault-containment property: every
payload that enters from the wire must be attributed to a tenant and
charged against that tenant's budget BEFORE any parse or device
dispatch. The failure mode this rule guards against is the quiet
bypass: a new transport handler (or a refactored one) that hands bytes
to the fan-out tier without traversing admission — from then on a
flooding tenant's bytes are indistinguishable from everyone else's and
the isolation story silently rots.

Markers, program-wide (the ZT00 reason bar applies to both):

- ``# zt-ingest-boundary: <reason>`` — a wire entrypoint (HTTP ingest
  handler, gRPC Report, a future transport). These are the roots.
- ``# zt-tenant-admission: <reason>`` — an admission chokepoint
  (``Collector.accept_spans_bytes``, ``OverloadController.admit``).

From each boundary the whole-program call graph is walked; a boundary
from which NO admission-marked function is reachable is a finding, as
is a program that marks boundaries but no chokepoint at all.

The stock call graph only follows ``ast.Call`` edges, but boundary
handlers hop threads by *reference*: ``asyncio.to_thread(
self.collector.accept_spans_bytes, body, enc)`` passes the callee as
an argument. This checker augments the walk with callable-reference
edges — an ``ast.Attribute``/``ast.Name`` argument naming a known
function adds an edge from the enclosing function — so the to_thread
hop (and the grpc handler-registration hop) does not break the chain.
Over-approximate edges can only HIDE a missing-admission finding for a
chain the runtime never takes; they cannot invent one, so lint noise
stays zero.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from zipkin_tpu.lint.core import Checker, register

_FUNC_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef)

BOUNDARY_RE = re.compile(r"#\s*zt-ingest-boundary\b(?P<rest>.*)$")
ADMISSION_RE = re.compile(r"#\s*zt-tenant-admission\b(?P<rest>.*)$")

# comment lines immediately above a def that may carry its marker
_LOOKBACK_LINES = 8


def _marker_on(module, fn, pattern):
    """The marker attributed to ``fn``: anywhere in its body extent, or
    in the run of comment/blank lines immediately above the ``def``
    (both placements appear in the tree)."""
    end = getattr(fn, "end_lineno", None) or (fn.lineno + 1)
    for line_no in range(fn.lineno, end + 1):
        m = pattern.search(module.line_text(line_no))
        if m:
            return line_no, m.group("rest")
    for line_no in range(fn.lineno - 1,
                         max(0, fn.lineno - 1 - _LOOKBACK_LINES), -1):
        text = module.line_text(line_no).strip()
        if text and not text.startswith("#"):
            break
        m = pattern.search(text)
        if m:
            return line_no, m.group("rest")
    return None


def _reason_missing(rest: str) -> bool:
    return not rest.lstrip().startswith(":") or not rest.lstrip(": ").strip()


@register
class TenantAdmissionChain(Checker):
    rule = "ZT14"
    severity = "error"
    name = "tenant-admission"
    doc = (
        "ingest boundaries (# zt-ingest-boundary) from which no "
        "tenant-admission chokepoint (# zt-tenant-admission) is "
        "reachable in the whole-program call graph"
    )
    hint = (
        "route the payload through the admission chokepoint "
        "(Collector.accept_spans_bytes / OverloadController.admit) "
        "before any parse or device dispatch"
    )
    whole_program = True

    def check_program(self, program):
        roots: List[Tuple] = []
        chokepoints: Set[str] = set()
        for module in program.modules:
            for fn in ast.walk(module.tree):
                if not isinstance(fn, _FUNC_KINDS):
                    continue
                boundary = _marker_on(module, fn, BOUNDARY_RE)
                admission = _marker_on(module, fn, ADMISSION_RE)
                for hit, label in (
                    (boundary, "zt-ingest-boundary"),
                    (admission, "zt-tenant-admission"),
                ):
                    if hit is not None and _reason_missing(hit[1]):
                        yield self.found(
                            module, fn,
                            f"{label} marker without a reason — say WHY "
                            f"this function is part of the tenant "
                            f"admission contract (# {label}: <reason>)",
                        )
                qual = program.qual_of(fn)
                if qual is None:
                    continue
                if admission is not None:
                    chokepoints.add(qual)
                if boundary is not None:
                    roots.append((module, fn, qual))
        if not roots:
            return
        if not chokepoints:
            for module, fn, _qual in roots:
                yield self.found(
                    module, fn,
                    f"ingest boundary {fn.name}() is marked but the "
                    "program has no zt-tenant-admission chokepoint at "
                    "all — nothing attributes payloads to tenants",
                )
            return
        extra = self._callable_ref_edges(program)
        for module, fn, qual in roots:
            if qual in chokepoints:
                continue
            if not self._reaches(program, qual, chokepoints, extra):
                yield self.found(
                    module, fn,
                    f"ingest boundary {fn.name}() never traverses a "
                    "tenant-admission chokepoint — payloads from this "
                    "entrypoint reach the fan-out tier without being "
                    "charged to any tenant's budget",
                )

    # -- callable-reference edges ---------------------------------------

    @staticmethod
    def _callable_ref_edges(program) -> Dict[str, List[str]]:
        """Extra edges for callables passed by reference as call
        arguments (``asyncio.to_thread(f, ...)``, handler registration).
        Attribute args resolve name-keyed program-wide; bare-name args
        resolve within the same module (nested defs included)."""
        by_bare = getattr(program, "_by_bare", {})
        edges: Dict[str, List[str]] = {}
        for qual, info in program.functions.items():
            out: List[str] = []
            for call in ast.walk(info.node):
                if not isinstance(call, ast.Call):
                    continue
                for arg in list(call.args) + [
                    kw.value for kw in call.keywords
                ]:
                    if isinstance(arg, ast.Attribute):
                        out.extend(by_bare.get(arg.attr, ()))
                    elif isinstance(arg, ast.Name):
                        out.extend(
                            q for q in by_bare.get(arg.id, ())
                            if program.functions[q].module_rel
                            == info.module_rel
                        )
            if out:
                edges[qual] = out
        return edges

    @staticmethod
    def _reaches(program, root: str, targets: Set[str],
                 extra: Dict[str, List[str]], depth: int = 24) -> bool:
        seen = {root}
        frontier = [root]
        for _ in range(depth):
            if not frontier:
                break
            nxt: List[str] = []
            for qual in frontier:
                if qual in targets:
                    return True
                callees = [c for c, _r in program.edges.get(qual, ())]
                callees.extend(extra.get(qual, ()))
                for callee in callees:
                    if callee in seen or callee not in program.functions:
                        continue
                    seen.add(callee)
                    nxt.append(callee)
            frontier = nxt
        return bool(targets & seen)
