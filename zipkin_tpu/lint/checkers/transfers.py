"""ZT01 / ZT02 — device→host transfer discipline.

The r5 regression these rules pin: the dependencies read path made ~8
separate device→host pulls per query (`np.asarray` per output array plus
store-layer extras), amplifying the transport's fixed round trip into an
822 ms quiesced wall for a 42.9 ms device program (VERDICT r5 weak #1).
PR 1 collapsed the query path to ONE counted pull through
``zipkin_tpu.readpack`` and pinned it with a one-file AST lint; these
checkers apply the same invariant to the whole tree.

- **ZT01**: a device-tainted value (see :mod:`zipkin_tpu.lint.taint`)
  coerced to host via ``np.asarray``/``np.array``/``float()``/
  ``.item()``/``.tolist()``, or any ``jax.device_get`` call, outside the
  sanctioned chokepoint module (``zipkin_tpu/readpack.py``). Route pulls
  through ``readpack.pull``/``readpack.device_get`` so ``hostTransfers``
  counts them. Taint is per-function dataflow PLUS whole-program return
  summaries over resolved call-graph edges: ``np.asarray(helper(x))``
  is a transfer when ``helper`` — in this module or another — returns a
  device value.
- **ZT02**: the multi-pull *shape* — ≥2 host pulls in a single function
  (each pays the transport round trip; pack on device and pull once), or
  a ``return np.asarray(a), np.asarray(b), ...`` tuple anywhere (a
  multi-pull read being born; subsumes the retired
  tests/test_read_path_lint.py).
"""

from __future__ import annotations

import ast

from zipkin_tpu.lint.core import Checker, Module, register
from zipkin_tpu.lint.taint import FunctionTaint, _root_name

# the sanctioned chokepoint: the ONE module allowed to device_get (its
# counter is what makes transfers-per-query observable in production)
CHOKEPOINT_PATH_SUFFIXES = ("zipkin_tpu/readpack.py",)

_FUNC_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _is_chokepoint(module: Module) -> bool:
    return module.rel.endswith(CHOKEPOINT_PATH_SUFFIXES)


def _np_coercion(call: ast.Call):
    """('asarray'|'array', arg) for np.asarray/np.array calls."""
    f = call.func
    if (
        isinstance(f, ast.Attribute)
        and f.attr in ("asarray", "array")
        and isinstance(f.value, ast.Name)
        and f.value.id == "np"
        and call.args
    ):
        return f.attr, call.args[0]
    return None


def _device_get_call(call: ast.Call):
    """'jax' for jax.device_get(...) — an uncounted pull; 'chokepoint'
    for readpack.device_get(...) or a bare device_get(...) (the counted
    readpack chokepoint, imported or qualified); None otherwise."""
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "device_get":
        return "jax" if _root_name(f) == "jax" else "chokepoint"
    if isinstance(f, ast.Name) and f.id == "device_get":
        return "chokepoint"
    return None


def _iter_functions(module: Module):
    for node in ast.walk(module.tree):
        if isinstance(node, _FUNC_KINDS):
            yield node


def _taint_for(checker: Checker, fn: ast.AST) -> FunctionTaint:
    """Per-function taint wired to the run's cross-module return
    summaries (resolved edges only) when the graph is available."""
    graph = checker.program
    if graph is None:
        return FunctionTaint(fn)

    def resolver(call: ast.Call) -> bool:
        return any(
            resolved and graph.returns_tainted(qual)
            for qual, resolved in graph.callees_of_call(call)
        )

    return FunctionTaint(fn, call_resolver=resolver)


def _host_pulls(module: Module, fn: ast.AST, taint: FunctionTaint):
    """Every (node, kind) in ``fn`` that moves device data to host:
    tainted coercions, device_get calls, and ``self._pull``/
    ``readpack.pull`` chokepoint calls (sanctioned, but each is still
    one transfer — two of them in one method is still the r5 shape)."""
    own = set()
    for inner in ast.walk(fn):
        if inner is not fn and isinstance(inner, _FUNC_KINDS):
            own.update(ast.walk(inner))
    for node in ast.walk(fn):
        if node in own and node is not fn:
            # nested defs get their own function entry (and their own
            # taint scope) — don't double-count their pulls here
            continue
        if not isinstance(node, ast.Call):
            continue
        dg = _device_get_call(node)
        if dg is not None:
            yield node, (
                "jax.device_get" if dg == "jax" else "chokepoint pull"
            )
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in ("pull", "_pull"):
            root = _root_name(f)
            if root in ("self", "readpack", "agg") or f.attr == "_pull":
                yield node, "chokepoint pull"
            continue
        coercion = _np_coercion(node)
        if coercion is not None and taint.is_tainted(coercion[1]):
            yield node, f"np.{coercion[0]} of a device value"
            continue
        if (
            isinstance(f, ast.Name)
            and f.id == "float"
            and node.args
            and taint.is_tainted(node.args[0])
        ):
            yield node, "float() of a device value"
            continue
        if (
            isinstance(f, ast.Attribute)
            and f.attr in ("item", "tolist")
            and not node.args
            and taint.is_tainted(f.value)
        ):
            yield node, f".{f.attr}() of a device value"


@register
class HostTransferChokepoint(Checker):
    rule = "ZT01"
    severity = "error"
    name = "host-transfer-chokepoint"
    doc = "device→host coercion outside readpack"
    hint = (
        "route the pull through readpack.pull/readpack.device_get so "
        "hostTransfers counts it (zipkin_tpu/readpack.py)"
    )

    def check(self, module: Module):
        if _is_chokepoint(module):
            return
        if not module.imported_roots & {"jax", "jnp"}:
            # a module that never touches jax holds no device values;
            # np.asarray there is host-only input coercion
            return
        for fn in _iter_functions(module):
            taint = _taint_for(self, fn)
            for node, kind in _host_pulls(module, fn, taint):
                if kind == "chokepoint pull":
                    continue  # sanctioned (counted) — ZT02 counts them
                yield self.found(
                    module,
                    node,
                    f"{kind} in {fn.name}() — a device→host transfer "
                    "outside the counted readpack chokepoint",
                )


@register
class MultiPullShapes(Checker):
    rule = "ZT02"
    severity = "error"
    name = "multi-pull-shapes"
    doc = "≥2 host pulls per function / multi-asarray return tuples"
    hint = (
        "pack the program's outputs on device (readpack.pack) and pull "
        "the one buffer once"
    )

    def check(self, module: Module):
        if _is_chokepoint(module):
            return
        has_jax = bool(module.imported_roots & {"jax", "jnp"})
        for fn in _iter_functions(module):
            if has_jax:
                taint = _taint_for(self, fn)
                pulls = list(_host_pulls(module, fn, taint))
                if len(pulls) >= 2:
                    kinds = ", ".join(k for _, k in pulls)
                    yield self.found(
                        module,
                        pulls[1][0],
                        f"{fn.name}() makes {len(pulls)} host pulls "
                        f"({kinds}) — each pays the transport round trip",
                    )
            # `return np.asarray(a), np.asarray(b)` is a multi-pull read
            # being born whatever the taint analysis can prove — reject
            # the shape itself (this subsumes the retired one-file lint)
            for node in ast.walk(fn):
                if not (
                    isinstance(node, ast.Return)
                    and isinstance(node.value, ast.Tuple)
                ):
                    continue
                n_asarray = sum(
                    1
                    for el in node.value.elts
                    if isinstance(el, ast.Call) and _np_coercion(el)
                )
                if n_asarray >= 2:
                    yield self.found(
                        module,
                        node,
                        f"return tuple with {n_asarray} np.asarray "
                        f"sections in {fn.name}() — one transfer per "
                        "element",
                    )
