"""ZT-lint CLI: ``python -m zipkin_tpu.lint [paths] [options]``.

Exit code 0 = clean (after pragmas, --select/--ignore, and --baseline
filtering); 1 = live findings or unparsable files. Designed to gate
tier-1 (tests/test_lint_clean.py runs the same entry in-process).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from zipkin_tpu.lint.core import (
    all_checkers,
    iter_py_files,
    load_baseline,
    run_paths,
    write_baseline,
)


def _rule_set(spec):
    if not spec:
        return None
    rules = {r.strip().upper() for r in spec.split(",") if r.strip()}
    known = set(all_checkers())
    unknown = rules - known
    if unknown:
        raise SystemExit(
            f"zt-lint: unknown rule(s) {sorted(unknown)}; "
            f"known: {sorted(known)}"
        )
    return rules


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m zipkin_tpu.lint",
        description="ZT-lint: TPU-invariant static analysis for zipkin-tpu",
    )
    p.add_argument(
        "paths",
        nargs="*",
        default=["zipkin_tpu"],
        help="files or directories to lint (default: zipkin_tpu)",
    )
    p.add_argument(
        "--select",
        metavar="RULES",
        help="comma-separated rule ids to run (e.g. ZT01,ZT04); "
        "ZT00 always runs",
    )
    p.add_argument(
        "--ignore",
        metavar="RULES",
        help="comma-separated rule ids to skip (ZT00 cannot be skipped)",
    )
    p.add_argument(
        "--baseline",
        metavar="FILE",
        help="JSON baseline of accepted findings to filter out",
    )
    p.add_argument(
        "--write-baseline",
        metavar="FILE",
        help="write the current live findings as a baseline and exit 0",
    )
    p.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    p.add_argument(
        "-q", "--quiet", action="store_true", help="findings only, no summary"
    )
    p.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="output format: human-readable text (default) or one JSON "
        "document (findings + suppressions + run stats) on stdout",
    )
    p.add_argument(
        "--stats",
        action="store_true",
        help="print run statistics (files, call-graph size, wall time) "
        "after the summary",
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rule, checker in all_checkers().items():
            print(f"{rule}  {checker.name:28s} {checker.doc}")
        return 0
    baseline = load_baseline(args.baseline) if args.baseline else None
    result = run_paths(
        args.paths,
        select=_rule_set(args.select),
        ignore=_rule_set(args.ignore),
        baseline=baseline,
        root=Path.cwd(),
    )
    if args.format == "json" and not args.write_baseline:
        # one machine-readable document on stdout, nothing else — the
        # CI consumer parses stdout and keys off the exit code
        print(json.dumps(result.to_dict(), indent=2, sort_keys=True))
        return result.exit_code
    for err in result.errors:
        print(f"ERROR {err}", file=sys.stderr)
    if args.write_baseline:
        # fingerprints need each finding's source-line context
        entries = []
        by_path = {}
        for f in result.findings:
            lines = by_path.setdefault(
                f.path, Path(f.path).read_text().splitlines()
            )
            ctx = lines[f.line - 1].strip() if f.line <= len(lines) else ""
            entries.append((f, ctx))
        write_baseline(args.write_baseline, entries)
        print(
            f"wrote {len(entries)} finding(s) to baseline "
            f"{args.write_baseline}"
        )
        return 0
    for f in result.findings:
        print(f.render())
    if not args.quiet:
        n_files = len(list(iter_py_files(args.paths)))
        print(
            f"zt-lint: {len(result.findings)} finding(s) in {n_files} "
            f"file(s); {len(result.suppressed)} suppressed by pragma, "
            f"{len(result.baselined)} baselined",
            file=sys.stderr,
        )
    if args.stats:
        s = result.stats
        print(
            "zt-lint stats: {files} file(s), {functions} function(s), "
            "{edges} call edge(s), {rules} rule(s), {elapsed_ms:.0f} ms".format(
                **{k: s.get(k, 0) for k in
                   ("files", "functions", "edges", "rules", "elapsed_ms")}
            ),
            file=sys.stderr,
        )
    return result.exit_code


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
