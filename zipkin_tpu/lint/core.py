"""ZT-lint core: findings, checker registry, pragmas, baselines, runner.

The framework is deliberately dependency-free (ast + tokenize from the
stdlib) so it runs everywhere tier-1 runs. A checker is a class with a
``rule`` id, a ``severity``, and a ``check(module)`` generator; the
runner parses each file ONCE into a :class:`Module` (tree, parent map,
comment pragmas) shared by every checker, then filters findings through
inline suppressions and an optional baseline.

Suppression pragma grammar (``# zt-lint: disable=ZT01[,ZT04] — reason``):

- on the offending line: suppresses matching findings on that line;
- on its own comment line: applies to the next code line (so long
  justifications don't fight the line length), skipping blank and
  further comment lines;
- either placement on a ``def`` / ``class`` / ``with`` header line:
  suppresses matching findings anywhere inside that statement's body;
- a pragma with NO justification text after the rule list is itself a
  finding (ZT00) — the acceptance bar is "suppressed WITH a reason",
  and the linter enforces its own bar mechanically.
"""

from __future__ import annotations

import ast
import io
import json
import re
import time
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

PRAGMA_RE = re.compile(
    r"#\s*zt-lint\s*:\s*disable\s*=\s*"
    r"(?P<rules>ZT\d{2}(?:\s*,\s*ZT\d{2})*)"
    r"(?P<reason>.*)$"
)

SEVERITIES = ("error", "warning")


@dataclass(frozen=True)
class Finding:
    rule: str            # "ZT01"
    severity: str        # "error" | "warning"
    path: str            # repo-relative posix path
    line: int            # 1-based
    col: int             # 0-based
    message: str
    hint: str = ""

    def render(self) -> str:
        out = f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"
        if self.hint:
            out += f"  [fix: {self.hint}]"
        return out

    def fingerprint(self, context: str) -> Tuple[str, str, str]:
        """Line-number-independent identity for baseline matching: the
        stripped source line survives unrelated edits above it."""
        return (self.rule, self.path, context)


@dataclass
class Pragma:
    line: int
    rules: Set[str]
    reason: str


class Module:
    """One parsed source file, shared by every checker."""

    def __init__(self, path: Path, rel: str, source: str) -> None:
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel)
        # parent links let checkers walk OUT of a node (enclosing
        # function / loop / with-block) without re-walking the tree
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.pragmas: List[Pragma] = list(_parse_pragmas(source))
        # a pragma on its OWN comment line governs the next code line;
        # one trailing a statement governs that statement's line
        self._pragma_by_line: Dict[int, Pragma] = {}
        for p in self.pragmas:
            self._pragma_by_line[self._pragma_target(p.line)] = p
        # top-level import names: "imports jax" gates device-taint rules
        self.imported_roots: Set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imported_roots.add((a.asname or a.name).split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                self.imported_roots.add(node.module.split(".")[0])

    def _pragma_target(self, line: int) -> int:
        text = self.lines[line - 1].lstrip() if line <= len(self.lines) else ""
        if not text.startswith("#"):
            return line  # trailing pragma: governs its own line
        for nxt in range(line + 1, len(self.lines) + 1):
            t = self.lines[nxt - 1].strip()
            if t and not t.startswith("#"):
                return nxt
        return line  # pragma at EOF: nothing to govern

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def enclosing(self, node: ast.AST, kinds) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, kinds):
                yield cur
            cur = self.parents.get(cur)

    def suppressed(self, finding: Finding) -> Optional[Pragma]:
        """The pragma suppressing this finding, if any: exact line, or a
        scoped pragma on a def/class/with header whose span covers it."""
        p = self._pragma_by_line.get(finding.line)
        if p is not None and finding.rule in p.rules:
            return p
        for node in ast.walk(self.tree):
            if not isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.With),
            ):
                continue
            p = self._pragma_by_line.get(node.lineno)
            if (
                p is not None
                and finding.rule in p.rules
                and node.lineno <= finding.line <= (node.end_lineno or node.lineno)
            ):
                return p
        return None


def _parse_pragmas(source: str) -> Iterator[Pragma]:
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = PRAGMA_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip() for r in m.group("rules").split(",")}
            reason = m.group("reason").strip(" \t-—:(").rstrip(")")
            yield Pragma(line=tok.start[0], rules=rules, reason=reason)
    except tokenize.TokenError:  # pragma: no cover - unparsable file
        return


class Checker:
    """Base checker. Subclasses set ``rule``/``severity``/``hint`` and
    implement :meth:`check` (per module) or — with ``whole_program =
    True`` — :meth:`check_program` (once per run, over the call graph),
    yielding findings (use :meth:`found`).

    During a run the shared :class:`~zipkin_tpu.lint.callgraph.CallGraph`
    is bound to ``self.program`` (None when linting without the graph,
    e.g. a single file fed to :meth:`check` directly in a unit test), so
    per-module checkers can consult interprocedural facts — resolve a
    call, walk callers, ask for a cross-module taint summary — without
    rebuilding anything: the graph is built once and shared by every
    rule."""

    rule: str = "ZT??"
    severity: str = "error"
    name: str = ""
    doc: str = ""
    hint: str = ""
    whole_program: bool = False
    program = None  # bound by run_paths for the duration of a run

    def found(
        self, module: Module, node: ast.AST, message: str, hint: str = ""
    ) -> Finding:
        return Finding(
            rule=self.rule,
            severity=self.severity,
            path=module.rel,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message,
            hint=hint or self.hint,
        )

    def check(self, module: Module) -> Iterable[Finding]:
        return ()

    def check_program(self, program) -> Iterable[Finding]:
        return ()

    def graph(self, module: Module):
        """The run's shared CallGraph, or (when a checker is driven
        directly against one module, outside run_paths) a fresh
        single-module graph — resolution semantics are identical."""
        if self.program is not None:
            return self.program
        from zipkin_tpu.lint.callgraph import CallGraph

        return CallGraph([module])


_REGISTRY: Dict[str, Checker] = {}


def register(checker_cls):
    """Class decorator: instantiate + index by rule id. Importing
    ``zipkin_tpu.lint.checkers`` populates the registry."""
    inst = checker_cls()
    _REGISTRY[inst.rule] = inst
    return checker_cls


def all_checkers() -> Dict[str, Checker]:
    from zipkin_tpu.lint import checkers  # noqa: F401 - registers on import

    return dict(sorted(_REGISTRY.items()))


# -- baseline ------------------------------------------------------------


def load_baseline(path) -> Set[Tuple[str, str, str]]:
    """A baseline is the fingerprint set of known findings: matching
    findings are reported as suppressed, so a tree with accepted debt
    still gates NEW violations. Entries: {rule, path, context}."""
    with open(path) as f:
        data = json.load(f)
    return {
        (e["rule"], e["path"], e["context"]) for e in data.get("findings", ())
    }


def write_baseline(path, findings: Sequence[Tuple[Finding, str]]) -> None:
    data = {
        "findings": [
            {"rule": f.rule, "path": f.path, "context": ctx}
            for f, ctx in findings
        ]
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")


# -- runner --------------------------------------------------------------


@dataclass
class RunResult:
    findings: List[Finding] = field(default_factory=list)        # live
    suppressed: List[Finding] = field(default_factory=list)      # pragma'd
    baselined: List[Finding] = field(default_factory=list)       # in baseline
    errors: List[str] = field(default_factory=list)              # parse errors
    # the pragma that suppressed each entry of ``suppressed``, same order
    suppressed_pragmas: List[Pragma] = field(default_factory=list)
    stats: Dict[str, float] = field(default_factory=dict)

    @property
    def exit_code(self) -> int:
        return 1 if self.findings or self.errors else 0

    def to_dict(self) -> Dict:
        """Machine-readable shape for ``--format json``: every finding
        with rule/path/line plus its pragma status (live findings have
        ``pragma: null``; suppressed ones carry line + reason)."""

        def one(f: Finding, pragma: Optional[Pragma]) -> Dict:
            return {
                "rule": f.rule,
                "severity": f.severity,
                "path": f.path,
                "line": f.line,
                "col": f.col,
                "message": f.message,
                "hint": f.hint,
                "pragma": None if pragma is None else {
                    "line": pragma.line,
                    "reason": pragma.reason,
                },
            }

        return {
            "findings": [one(f, None) for f in self.findings],
            "suppressed": [
                one(f, p)
                for f, p in zip(self.suppressed, self.suppressed_pragmas)
            ],
            "baselined": [one(f, None) for f in self.baselined],
            "errors": list(self.errors),
            "stats": dict(self.stats),
            "exit_code": self.exit_code,
        }


def iter_py_files(paths: Sequence, root: Optional[Path] = None) -> Iterator[Path]:
    for p in paths:
        p = Path(p)
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            yield p


# Parse cache: (resolved path, rel) -> (mtime_ns, size, Module). Parsing
# + parent-map construction dominates lint wall time, so repeat runs in
# one process (tier-1 runs the linter several times) only re-parse files
# whose mtime or size changed.
_MODULE_CACHE: Dict[Tuple[str, str], Tuple[int, int, Module]] = {}


def _load_module(path: Path, rel: str) -> Module:
    st = path.stat()
    key = (str(path.resolve()), rel)
    hit = _MODULE_CACHE.get(key)
    if hit is not None and hit[0] == st.st_mtime_ns and hit[1] == st.st_size:
        return hit[2]
    module = Module(path, rel, path.read_text())
    _MODULE_CACHE[key] = (st.st_mtime_ns, st.st_size, module)
    return module


def run_paths(
    paths: Sequence,
    select: Optional[Set[str]] = None,
    ignore: Optional[Set[str]] = None,
    baseline: Optional[Set[Tuple[str, str, str]]] = None,
    root: Optional[Path] = None,
) -> RunResult:
    """Lint every .py under ``paths``. ``select``/``ignore`` are rule-id
    sets (select wins first, then ignore removes). ZT00 (suppression
    hygiene) always runs: disabling the meta-rule would let reasonless
    pragmas rot silently.

    Two-phase: every file is parsed first (mtime-cached), the whole-
    program call graph is built ONCE over the parsed set, then each rule
    runs with the graph bound to ``checker.program`` — per-module rules
    over each file, ``whole_program`` rules once over the graph."""
    from zipkin_tpu.lint.callgraph import CallGraph

    t0 = time.monotonic()
    checkers = all_checkers()
    active = {
        rule: c
        for rule, c in checkers.items()
        if (select is None or rule in select or rule == "ZT00")
        and not (ignore and rule in ignore and rule != "ZT00")
    }
    root = Path(root) if root is not None else Path.cwd()
    result = RunResult()
    modules: List[Module] = []
    by_rel: Dict[str, Module] = {}
    for path in iter_py_files(paths):
        try:
            rel = path.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = path.as_posix()
        try:
            module = _load_module(path, rel)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            result.errors.append(f"{rel}: unparsable: {e}")
            continue
        modules.append(module)
        by_rel[module.rel] = module

    graph = CallGraph(modules)

    def file_findings(checker, module):
        for finding in checker.check(module):
            yield module, finding

    def program_findings(checker):
        # several roots can reach one sink: report each line once
        seen: Set[Tuple[str, str, int, int]] = set()
        for finding in checker.check_program(graph):
            key = (finding.rule, finding.path, finding.line, finding.col)
            if key in seen:
                continue
            seen.add(key)
            module = by_rel.get(finding.path)
            if module is not None:
                yield module, finding

    try:
        for checker in active.values():
            checker.program = graph
        for checker in active.values():
            if checker.whole_program:
                produced = program_findings(checker)
            else:
                produced = (
                    pair
                    for module in modules
                    for pair in file_findings(checker, module)
                )
            for module, finding in produced:
                pragma = module.suppressed(finding)
                if pragma is not None:
                    result.suppressed.append(finding)
                    result.suppressed_pragmas.append(pragma)
                    continue
                if baseline is not None:
                    ctx = module.line_text(finding.line)
                    if finding.fingerprint(ctx) in baseline:
                        result.baselined.append(finding)
                        continue
                result.findings.append(finding)
    finally:
        for checker in active.values():
            checker.program = None
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    result.stats = {
        "files": len(modules),
        "functions": len(graph.functions),
        "edges": graph.n_edges,
        "rules": len(active),
        "elapsed_ms": round((time.monotonic() - t0) * 1000.0, 1),
    }
    return result
