"""Device-value taint analysis (syntactic, per-function + summaries).

ZT01/ZT02 must tell ``np.asarray(qs)`` (input coercion of a host list)
apart from ``np.asarray(self._merge(self.state))`` (a device→host pull).
There is no type information at lint time, so this module runs a small
forward dataflow pass per function: an expression is *device-tainted*
when it is built from

- the aggregator state (any attribute chain rooted at ``self.state`` or
  a bare ``state`` name — the pytree every compiled program takes),
- a ``jax.*`` / ``jnp.*`` call (device arrays are born there),
- any call that RECEIVES a tainted argument (compiled programs are
  opaque callables like ``self._merge``; what flows in device-flavored
  comes out device-flavored), or
- a call whose callee the whole-program graph RESOLVES to a function
  that returns a tainted value (``call_resolver`` — the cross-module
  summary hook :meth:`CallGraph.returns_tainted` plugs in, so a device
  pull can no longer hide one helper call away in another module),

propagated through names: assignment / tuple-unpack / for-targets of a
tainted value taint the bound names. Two passes over the statement list
approximate a fixpoint (enough for loops that bind before use).

Deliberately syntactic: a checker needs NO false negatives on the
shapes that caused real regressions (multi-``np.asarray`` reads of
program outputs) and LOW false positives on host-only numpy code. The
per-function pass stays local; interprocedural flow comes in ONLY via
summaries over resolved call-graph edges, which keeps the fallback
(name-keyed) edges from smearing taint onto unrelated host code.
"""

from __future__ import annotations

import ast
from typing import Set

DEVICE_ROOT_MODULES = {"jax", "jnp"}
STATE_ATTR = "state"

# jax.* calls that return host-side METADATA (Device handles, counts),
# not device arrays — np.asarray over these is not a transfer
HOST_ONLY_JAX_ATTRS = {
    "devices",
    "local_devices",
    "device_count",
    "local_device_count",
    "process_index",
    "process_count",
}


def _root_name(node: ast.AST):
    """The leftmost Name of an attribute/subscript/call chain, if any."""
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
        node = node.func if isinstance(node, ast.Call) else node.value
    return node.id if isinstance(node, ast.Name) else None


def _is_state_chain(node: ast.AST) -> bool:
    """True for ``self.state``, ``self.state.pend_pos``,
    ``self.agg.state.hll``, ``state.hll``... — an attribute/subscript
    chain with a ``.state`` segment (or a bare ``state`` name): the
    aggregator pytree however it is reached."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if isinstance(node, ast.Attribute) and node.attr == STATE_ATTR:
            return True
        node = node.value
    return isinstance(node, ast.Name) and node.id == STATE_ATTR


class FunctionTaint:
    """Taint facts for one function body (nested defs included).

    ``call_resolver`` is an optional ``Call node -> bool`` oracle: when
    the local rules don't taint a call, the resolver may (cross-module
    summary: the resolved callee returns a device value)."""

    def __init__(self, fn: ast.AST, call_resolver=None) -> None:
        self.fn = fn
        self.call_resolver = call_resolver
        self.tainted_names: Set[str] = set()
        body = getattr(fn, "body", [])
        for _ in range(2):  # two passes ≈ fixpoint for name-level flow
            for stmt in body:
                self._visit_stmt(stmt)

    # -- statement walk (assignments bind taint to names) ----------------

    def _visit_stmt(self, stmt: ast.AST) -> None:
        if isinstance(stmt, ast.Assign):
            if self.is_tainted(stmt.value):
                for target in stmt.targets:
                    self._taint_target(target)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            if stmt.value is not None and self.is_tainted(stmt.value):
                self._taint_target(stmt.target)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            if self.is_tainted(stmt.iter):
                self._taint_target(stmt.target)
            for s in stmt.body + stmt.orelse:
                self._visit_stmt(s)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is not None and self.is_tainted(
                    item.context_expr
                ):
                    self._taint_target(item.optional_vars)
            for s in stmt.body:
                self._visit_stmt(s)
        elif isinstance(stmt, (ast.If, ast.While)):
            for s in stmt.body + stmt.orelse:
                self._visit_stmt(s)
        elif isinstance(stmt, ast.Try):
            for s in (
                stmt.body
                + stmt.orelse
                + stmt.finalbody
                + [h for hs in stmt.handlers for h in hs.body]
            ):
                self._visit_stmt(s)
        # nested defs keep their own scopes; don't descend

    def _taint_target(self, target: ast.AST) -> None:
        if isinstance(target, ast.Name):
            self.tainted_names.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._taint_target(el)
        elif isinstance(target, ast.Starred):
            self._taint_target(target.value)
        # attribute/subscript targets don't bind local names

    # -- expression taint -------------------------------------------------

    def is_tainted(self, node: ast.AST) -> bool:
        if node is None:
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted_names or node.id == STATE_ATTR
        if isinstance(node, (ast.Attribute, ast.Subscript)):
            if _is_state_chain(node):
                return True
            return self.is_tainted(node.value)
        if isinstance(node, ast.Call):
            root = _root_name(node.func)
            if root in DEVICE_ROOT_MODULES:
                return not (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in HOST_ONLY_JAX_ATTRS
                )
            if any(self.is_tainted(a) for a in node.args):
                return True
            if any(self.is_tainted(k.value) for k in node.keywords):
                return True
            if self.call_resolver is not None and self.call_resolver(node):
                return True
            return False
        if isinstance(node, ast.BinOp):
            return self.is_tainted(node.left) or self.is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.Compare):
            return self.is_tainted(node.left) or any(
                self.is_tainted(c) for c in node.comparators
            )
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_tainted(el) for el in node.elts)
        if isinstance(node, ast.Starred):
            return self.is_tainted(node.value)
        if isinstance(node, ast.IfExp):
            return self.is_tainted(node.body) or self.is_tainted(node.orelse)
        if isinstance(node, ast.NamedExpr):
            return self.is_tainted(node.value)
        return False
