"""L0: the span model and wire codecs (JSON v2/v1, proto3, thrift)."""

from zipkin_tpu.model.span import (  # noqa: F401
    Annotation,
    DependencyLink,
    Endpoint,
    Kind,
    Span,
)
