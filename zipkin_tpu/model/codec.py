"""Codec dispatch: wire-format auto-detection and the encoder/decoder enums.

Reference semantics: ``zipkin2/codec/SpanBytesDecoder.java`` /
``SpanBytesEncoder.java`` and the first-byte sniffing in
``ZipkinHttpCollector`` (SURVEY.md §3.2): ``[`` begins JSON (v1 or v2
distinguished by content), ``0x0a`` a proto3 ``ListOfSpans`` (field 1,
length-delimited), ``0x0c`` a thrift struct-list.
"""

from __future__ import annotations

import enum
import json
from typing import Callable, List, Sequence

from zipkin_tpu.model import json_v1, json_v2, proto3, thrift
from zipkin_tpu.model.span import Span


class Encoding(enum.Enum):
    JSON_V2 = "json_v2"
    JSON_V1 = "json_v1"
    PROTO3 = "proto3"
    THRIFT = "thrift"

    @property
    def media_type(self) -> str:
        return {
            Encoding.JSON_V2: "application/json",
            Encoding.JSON_V1: "application/json",
            Encoding.PROTO3: "application/x-protobuf",
            Encoding.THRIFT: "application/x-thrift",
        }[self]


_DECODERS: dict = {
    Encoding.JSON_V2: json_v2.decode_span_list,
    Encoding.JSON_V1: json_v1.decode_v1_span_list,
    Encoding.PROTO3: proto3.decode_span_list,
    Encoding.THRIFT: thrift.decode_span_list,
}

_ENCODERS: dict = {
    Encoding.JSON_V2: json_v2.encode_span_list,
    Encoding.JSON_V1: json_v1.encode_v1_span_list,
    Encoding.PROTO3: proto3.encode_span_list,
    Encoding.THRIFT: thrift.encode_span_list,
}


def _looks_like_v1_json(data: bytes) -> bool:
    """v1 JSON is distinguished by binaryAnnotations or endpoint'd annotations."""
    if b'"binaryAnnotations"' in data:
        return True
    # annotations with an "endpoint" member only exist in v1
    if b'"annotations"' in data and b'"endpoint"' in data:
        return True
    return False


def _looks_like_json(data: bytes) -> bool:
    """Whitespace-tolerant JSON shape check: opens with [/{ and closes with
    ]/} after stripping whitespace. A payload that is ALSO a structurally
    valid proto3 frame is resolved by detect() in proto3's favor."""
    head = data[:256].lstrip(b" \t\r\n")
    tail = data[-64:].rstrip(b" \t\r\n")
    return head[:1] in (b"[", b"{") and tail[-1:] in (b"]", b"}")


def _plausible_proto3_frame(data: bytes) -> bool:
    """True if ``data`` is structurally a proto3 ``ListOfSpans``: repeated
    0x0A-tagged length-delimited elements consuming the payload exactly."""
    pos, n = 0, len(data)
    while pos < n:
        if data[pos] != 0x0A:
            return False
        pos += 1
        # varint length
        length, shift = 0, 0
        while True:
            if pos >= n or shift > 28:
                return False
            b = data[pos]
            pos += 1
            length |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        pos += length
    return pos == n


def detect(data: bytes) -> Encoding:
    """Sniff the encoding of an ingest payload from its first byte(s)."""
    if not data:
        raise ValueError("empty payload")
    first = data[0]
    # 0x0A is ambiguous: proto3's field-1 header AND '\n'. A proto3 payload
    # can even end in 0x7D (string tag ending in '}'), so the JSON shape
    # check alone cannot resolve it; a structural frame walk can — a valid
    # ListOfSpans is a sequence of 0x0A-tagged length-delimited elements
    # consuming the payload exactly, which whitespace-padded JSON is not.
    if first == 0x0A:
        if _plausible_proto3_frame(data):
            return Encoding.PROTO3
        if _looks_like_json(data):
            return Encoding.JSON_V1 if _looks_like_v1_json(data) else Encoding.JSON_V2
        return Encoding.PROTO3
    if first in (0x5B, 0x7B) or (
        first in (0x20, 0x09, 0x0D) and _looks_like_json(data)
    ):
        return Encoding.JSON_V1 if _looks_like_v1_json(data) else Encoding.JSON_V2
    if first == 0x0C:
        return Encoding.THRIFT
    raise ValueError(f"unrecognized span payload (first byte 0x{first:02x})")


def decode_spans(data: bytes, encoding: Encoding | None = None) -> List[Span]:
    """Decode an ingest payload to v2 spans, sniffing the format if needed."""
    enc = encoding or detect(data)
    decoder: Callable[[bytes], List[Span]] = _DECODERS[enc]
    return decoder(data)


def encode_spans(spans: Sequence[Span], encoding: Encoding = Encoding.JSON_V2) -> bytes:
    encoder = _ENCODERS.get(encoding)
    if encoder is None:
        raise ValueError(f"encoding {encoding} does not support span encode")
    return encoder(spans)


def pretty_json(data: bytes) -> str:  # pragma: no cover - debug aid
    return json.dumps(json.loads(data), indent=2)
