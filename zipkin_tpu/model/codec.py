"""Codec dispatch: wire-format auto-detection and the encoder/decoder enums.

Reference semantics: ``zipkin2/codec/SpanBytesDecoder.java`` /
``SpanBytesEncoder.java`` and the first-byte sniffing in
``ZipkinHttpCollector`` (SURVEY.md §3.2): ``[`` begins JSON (v1 or v2
distinguished by content), ``0x0a`` a proto3 ``ListOfSpans`` (field 1,
length-delimited), ``0x0c`` a thrift struct-list.
"""

from __future__ import annotations

import enum
import json
from typing import Callable, List, Sequence

from zipkin_tpu.model import json_v1, json_v2, proto3, thrift
from zipkin_tpu.model.span import Span


class Encoding(enum.Enum):
    JSON_V2 = "json_v2"
    JSON_V1 = "json_v1"
    PROTO3 = "proto3"
    THRIFT = "thrift"

    @property
    def media_type(self) -> str:
        return {
            Encoding.JSON_V2: "application/json",
            Encoding.JSON_V1: "application/json",
            Encoding.PROTO3: "application/x-protobuf",
            Encoding.THRIFT: "application/x-thrift",
        }[self]


_DECODERS: dict = {
    Encoding.JSON_V2: json_v2.decode_span_list,
    Encoding.JSON_V1: json_v1.decode_v1_span_list,
    Encoding.PROTO3: proto3.decode_span_list,
    Encoding.THRIFT: thrift.decode_span_list,
}

_ENCODERS: dict = {
    Encoding.JSON_V2: json_v2.encode_span_list,
    Encoding.JSON_V1: json_v1.encode_v1_span_list,
    Encoding.PROTO3: proto3.encode_span_list,
}


def _looks_like_v1_json(data: bytes) -> bool:
    """v1 JSON is distinguished by binaryAnnotations or endpoint'd annotations."""
    if b'"binaryAnnotations"' in data:
        return True
    # annotations with an "endpoint" member only exist in v1
    if b'"annotations"' in data and b'"endpoint"' in data:
        return True
    return False


def _looks_like_json(data: bytes) -> bool:
    """Whitespace-tolerant JSON shape check: opens with [/{ and closes with
    ]/} after stripping whitespace — disambiguates a leading 0x0a newline
    from a proto3 field-1 header, which a first-byte test alone cannot."""
    head = data[:256].lstrip(b" \t\r\n")
    tail = data[-64:].rstrip(b" \t\r\n")
    return head[:1] in (b"[", b"{") and tail[-1:] in (b"]", b"}")


def detect(data: bytes) -> Encoding:
    """Sniff the encoding of an ingest payload from its first byte(s)."""
    if not data:
        raise ValueError("empty payload")
    first = data[0]
    if first in (0x5B, 0x7B) or (
        first in (0x20, 0x09, 0x0D, 0x0A) and _looks_like_json(data)
    ):
        return Encoding.JSON_V1 if _looks_like_v1_json(data) else Encoding.JSON_V2
    if first == 0x0A:
        return Encoding.PROTO3
    if first == 0x0C:
        return Encoding.THRIFT
    raise ValueError(f"unrecognized span payload (first byte 0x{first:02x})")


def decode_spans(data: bytes, encoding: Encoding | None = None) -> List[Span]:
    """Decode an ingest payload to v2 spans, sniffing the format if needed."""
    enc = encoding or detect(data)
    decoder: Callable[[bytes], List[Span]] = _DECODERS[enc]
    return decoder(data)


def encode_spans(spans: Sequence[Span], encoding: Encoding = Encoding.JSON_V2) -> bytes:
    encoder = _ENCODERS.get(encoding)
    if encoder is None:
        raise ValueError(f"encoding {encoding} does not support span encode")
    return encoder(spans)


def pretty_json(data: bytes) -> str:  # pragma: no cover - debug aid
    return json.dumps(json.loads(data), indent=2)
