"""Zipkin v1 (legacy) JSON model and v1 -> v2 semantic conversion.

Reference semantics: ``zipkin2/v1/V1Span.java``, ``V1SpanConverter.java``,
``V2SpanConverter.java`` and the JSON_V1 arm of ``SpanBytesDecoder``
(SURVEY.md §2.1). v1 is the Scribe-era shape: core annotations ``cs/cr``
(client send/receive), ``sr/ss`` (server receive/send), ``ms/mr`` (message
send/receive) encode what v2 models as ``kind`` + timestamp/duration, and
binary annotations encode tags plus the address annotations ``sa/ca/ma``
that became ``remoteEndpoint``.

Conversion rules implemented (each is exercised in tests):

1. ``cs`` present: a CLIENT span exists; timestamp = cs, duration = cr - cs
   when ``cr`` is present, else the v1 timestamp/duration.
2. ``sr``/``ss`` present *without* ``cs``/``cr``: a SERVER span;
   **shared = parentId is set** — i.e. a non-root v1 server span is assumed
   to be the server half of an RPC whose id the client also reported.
3. ``cs`` *and* ``sr`` in one v1 span: the span is split into a CLIENT span
   (cs endpoint) and a *shared* SERVER span (sr endpoint, timestamp = sr,
   duration = ss - sr).
4. ``ms`` -> PRODUCER, ``mr`` -> CONSUMER (timestamp = the annotation).
5. Binary annotations of string type become tags; ``sa``/``ca``/``ma``
   (address annotations) become the remoteEndpoint of the opposite side:
   ``sa`` is the remote of the client span, ``ca`` the remote of the server
   span, ``ma`` of either messaging kind.
6. The ``lc`` ("local component") binary annotation contributes its endpoint
   as localEndpoint and survives as tag ``lc``.
7. Non-core annotations pass through with their timestamps.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

from zipkin_tpu.model.json_v2 import endpoint_to_dict
from zipkin_tpu.model.span import Annotation, Endpoint, Kind, Span

CORE_ANNOTATIONS = frozenset(["cs", "cr", "ss", "sr", "ms", "mr", "ws", "wr"])
ADDRESS_KEYS = frozenset(["sa", "ca", "ma"])


@dataclasses.dataclass(frozen=True)
class V1Annotation:
    timestamp: int
    value: str
    endpoint: Optional[Endpoint] = None


@dataclasses.dataclass(frozen=True)
class V1BinaryAnnotation:
    key: str
    value: Any  # str for tags; True for address annotations
    endpoint: Optional[Endpoint] = None

    @property
    def is_address(self) -> bool:
        return self.key in ADDRESS_KEYS and self.value is True


@dataclasses.dataclass(frozen=True)
class V1Span:
    trace_id: str
    id: str
    parent_id: Optional[str] = None
    name: Optional[str] = None
    timestamp: Optional[int] = None
    duration: Optional[int] = None
    annotations: Tuple[V1Annotation, ...] = ()
    binary_annotations: Tuple[V1BinaryAnnotation, ...] = ()
    debug: Optional[bool] = None


def _find(annotations: Sequence[V1Annotation], value: str) -> Optional[V1Annotation]:
    for a in annotations:
        if a.value == value:
            return a
    return None


def convert_v1_span(v1: V1Span) -> List[Span]:
    """Convert one v1 span into one or two v2 spans per the module rules."""
    anns = v1.annotations
    cs, cr = _find(anns, "cs"), _find(anns, "cr")
    sr, ss = _find(anns, "sr"), _find(anns, "ss")
    ms, mr = _find(anns, "ms"), _find(anns, "mr")

    tags: Dict[str, str] = {}
    local_from_lc: Optional[Endpoint] = None
    sa = ca = ma = None
    for b in v1.binary_annotations:
        if b.is_address:
            if b.key == "sa":
                sa = b.endpoint
            elif b.key == "ca":
                ca = b.endpoint
            else:
                ma = b.endpoint
        elif isinstance(b.value, str):
            tags[b.key] = b.value
            if b.endpoint is not None and local_from_lc is None:
                local_from_lc = b.endpoint

    extra = tuple(
        Annotation(a.timestamp, a.value) for a in anns if a.value not in CORE_ANNOTATIONS
    )

    def endpoint_of(
        *candidates: Optional[V1Annotation], scan_all: bool = True
    ) -> Optional[Endpoint]:
        for c in candidates:
            if c is not None and c.endpoint is not None:
                return c.endpoint
        if scan_all:
            for a in anns:
                if a.endpoint is not None:
                    return a.endpoint
        return local_from_lc

    out: List[Span] = []

    def build(
        kind: Optional[Kind],
        begin: Optional[V1Annotation],
        end: Optional[V1Annotation],
        local: Optional[Endpoint],
        remote: Optional[Endpoint],
        *,
        shared: Optional[bool] = None,
        use_v1_timing: bool = True,
    ) -> None:
        timestamp = begin.timestamp if begin is not None else None
        duration = None
        if begin is not None and end is not None and end.timestamp > begin.timestamp:
            duration = end.timestamp - begin.timestamp
        if use_v1_timing:
            timestamp = timestamp or v1.timestamp
            duration = duration or v1.duration
        out.append(
            Span.create(
                trace_id=v1.trace_id,
                id=v1.id,
                parent_id=v1.parent_id,
                kind=kind,
                name=v1.name,
                timestamp=timestamp,
                duration=duration,
                local_endpoint=local,
                remote_endpoint=remote,
                annotations=extra if not out else (),
                tags=tags if not out else {},
                debug=v1.debug,
                shared=shared,
            )
        )

    has_client = cs is not None or cr is not None
    has_server = sr is not None or ss is not None

    if has_client and has_server:
        # One v1 span carrying both halves of the RPC: split (rule 3). Each
        # half may only adopt its own side's endpoints — scanning all
        # annotations would leak the server's endpoint onto the client half.
        build(Kind.CLIENT, cs, cr or sr, endpoint_of(cs, cr, scan_all=False), sa)
        build(
            Kind.SERVER,
            sr,
            ss,
            endpoint_of(sr, ss, scan_all=False),
            ca,
            shared=True,
            use_v1_timing=False,
        )
    elif has_client:
        build(Kind.CLIENT, cs, cr, endpoint_of(cs, cr), sa)
    elif has_server:
        build(
            Kind.SERVER,
            sr,
            ss,
            endpoint_of(sr, ss),
            ca,
            shared=True if v1.parent_id is not None else None,  # rule 2
        )
    elif ms is not None:
        build(Kind.PRODUCER, ms, None, endpoint_of(ms), ma)
    elif mr is not None:
        build(Kind.CONSUMER, mr, None, endpoint_of(mr), ma)
    else:
        # Local / unannotated span: endpoint from any annotation or "lc".
        build(None, None, None, endpoint_of(), sa)
    return out


def convert_v1_spans(v1_spans: Sequence[V1Span]) -> List[Span]:
    out: List[Span] = []
    for v1 in v1_spans:
        out.extend(convert_v1_span(v1))
    return out


# -- v1 JSON wire decode/encode -------------------------------------------


def _v1_endpoint_from_dict(obj: Optional[Dict[str, Any]]) -> Optional[Endpoint]:
    if not obj:
        return None
    port = obj.get("port")
    return Endpoint.create(
        service_name=obj.get("serviceName"),
        ipv4=obj.get("ipv4"),
        ipv6=obj.get("ipv6"),
        port=int(port) if port is not None else None,
    )


def v1_span_from_dict(obj: Dict[str, Any]) -> V1Span:
    annotations = tuple(
        V1Annotation(
            timestamp=int(a["timestamp"]),
            value=str(a["value"]),
            endpoint=_v1_endpoint_from_dict(a.get("endpoint")),
        )
        for a in obj.get("annotations", ())
    )
    binary = []
    for b in obj.get("binaryAnnotations", ()):
        value = b.get("value")
        btype = b.get("type")
        if btype == "BOOL" or value is True:
            value = bool(value)
        elif not isinstance(value, str):
            value = json.dumps(value) if value is not None else ""
        binary.append(
            V1BinaryAnnotation(
                key=str(b["key"]),
                value=value,
                endpoint=_v1_endpoint_from_dict(b.get("endpoint")),
            )
        )
    return V1Span(
        trace_id=obj["traceId"],
        id=obj["id"],
        parent_id=obj.get("parentId"),
        name=obj.get("name"),
        timestamp=int(obj["timestamp"]) if obj.get("timestamp") else None,
        duration=int(obj["duration"]) if obj.get("duration") else None,
        annotations=annotations,
        binary_annotations=tuple(binary),
        debug=bool(obj.get("debug")) or None,
    )


def decode_v1_span_list(data: bytes) -> List[Span]:
    """Decode a v1 JSON array straight to v2 spans (the ingest path)."""
    parsed = json.loads(data)
    if not isinstance(parsed, list):
        raise ValueError("expected a JSON array of v1 spans")
    return convert_v1_spans([v1_span_from_dict(o) for o in parsed])


def encode_v1_span_list(spans: Sequence[Span]) -> bytes:
    """Encode v2 spans in the v1 JSON shape (legacy read compatibility).

    Reference: ``V2SpanConverter`` + JSON_V1 encoder. Kind/shared map back to
    core annotations; tags become string binary annotations; remoteEndpoint
    becomes the matching address annotation.
    """
    out = []
    for s in spans:
        obj: Dict[str, Any] = {"traceId": s.trace_id, "id": s.id}
        if s.parent_id:
            obj["parentId"] = s.parent_id
        obj["name"] = s.name or ""
        if s.timestamp and not s.shared:
            obj["timestamp"] = s.timestamp
        if s.duration and not s.shared:
            obj["duration"] = s.duration
        ep = endpoint_to_dict(s.local_endpoint) if s.local_endpoint else None
        anns: List[Dict[str, Any]] = []
        begin_end = {
            Kind.CLIENT: ("cs", "cr"),
            Kind.SERVER: ("sr", "ss"),
            Kind.PRODUCER: ("ms", None),
            Kind.CONSUMER: ("mr", None),
        }.get(s.kind) if s.kind else None
        if begin_end and s.timestamp:
            begin, end = begin_end
            anns.append({"timestamp": s.timestamp, "value": begin, "endpoint": ep})
            if end and s.duration:
                anns.append(
                    {"timestamp": s.timestamp + s.duration, "value": end, "endpoint": ep}
                )
        for a in s.annotations:
            anns.append({"timestamp": a.timestamp, "value": a.value, "endpoint": ep})
        if anns:
            obj["annotations"] = anns
        bins: List[Dict[str, Any]] = []
        for k, v in s.tags.items():
            bins.append({"key": k, "value": v, "endpoint": ep})
        if ep is not None and not anns and not s.tags:
            # A bare local span would otherwise lose its endpoint: emit the
            # "lc" (local component) convention the decoder understands.
            bins.append({"key": "lc", "value": "", "endpoint": ep})
        if s.remote_endpoint is not None and s.kind is not None:
            addr = {
                Kind.CLIENT: "sa",
                Kind.SERVER: "ca",
                Kind.PRODUCER: "ma",
                Kind.CONSUMER: "ma",
            }[s.kind]
            bins.append(
                {
                    "key": addr,
                    "value": True,
                    "type": "BOOL",
                    "endpoint": endpoint_to_dict(s.remote_endpoint),
                }
            )
        if bins:
            obj["binaryAnnotations"] = bins
        if s.debug:
            obj["debug"] = True
        out.append(obj)
    return json.dumps(out, separators=(",", ":")).encode()
