"""Zipkin v2 JSON codec.

Reference semantics: ``zipkin2/codec/SpanBytesEncoder.java`` /
``SpanBytesDecoder.java`` (JSON_V2) and ``zipkin2/internal/V2SpanWriter.java``
(SURVEY.md §2.1). The wire shape is the public v2 span JSON; fields that are
null/empty are omitted on encode, unknown fields are ignored on decode, and
decoding runs the same normalization as :meth:`Span.create` so a decoded span
is always canonical.

The reference hand-rolls a streaming writer for speed; here the oracle path
uses the stdlib json module, and the throughput path decodes straight into
columnar arrays (:mod:`zipkin_tpu.tpu.columnar`) instead of objects — the
TPU-native answer to ``WriteBuffer``.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

from zipkin_tpu.model.span import Annotation, DependencyLink, Endpoint, Kind, Span


def endpoint_to_dict(ep: Endpoint) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    if ep.service_name is not None:
        out["serviceName"] = ep.service_name
    if ep.ipv4 is not None:
        out["ipv4"] = ep.ipv4
    if ep.ipv6 is not None:
        out["ipv6"] = ep.ipv6
    if ep.port is not None:
        out["port"] = ep.port
    return out


def span_to_dict(span: Span) -> Dict[str, Any]:
    out: Dict[str, Any] = {"traceId": span.trace_id}
    if span.parent_id is not None:
        out["parentId"] = span.parent_id
    out["id"] = span.id
    if span.kind is not None:
        out["kind"] = span.kind.value
    if span.name is not None:
        out["name"] = span.name
    if span.timestamp is not None:
        out["timestamp"] = span.timestamp
    if span.duration is not None:
        out["duration"] = span.duration
    if span.local_endpoint is not None:
        out["localEndpoint"] = endpoint_to_dict(span.local_endpoint)
    if span.remote_endpoint is not None:
        out["remoteEndpoint"] = endpoint_to_dict(span.remote_endpoint)
    if span.annotations:
        out["annotations"] = [
            {"timestamp": a.timestamp, "value": a.value} for a in span.annotations
        ]
    if span.tags:
        out["tags"] = dict(span.tags)
    if span.debug:
        out["debug"] = True
    if span.shared:
        out["shared"] = True
    return out


def _endpoint_from_dict(obj: Optional[Dict[str, Any]]) -> Optional[Endpoint]:
    if not obj:
        return None
    port = obj.get("port")
    if port is not None:
        port = int(port)
    return Endpoint.create(
        service_name=obj.get("serviceName"),
        ipv4=obj.get("ipv4"),
        ipv6=obj.get("ipv6"),
        port=port,
    )


def span_from_dict(obj: Dict[str, Any]) -> Span:
    if "traceId" not in obj or "id" not in obj:
        raise ValueError(f"span missing traceId/id: {obj!r}")
    annotations = [
        Annotation(int(a["timestamp"]), str(a["value"]))
        for a in obj.get("annotations", ())
    ]
    tags = obj.get("tags") or {}
    return Span.create(
        trace_id=obj["traceId"],
        id=obj["id"],
        parent_id=obj.get("parentId"),
        kind=Kind.parse(obj.get("kind")),
        name=obj.get("name"),
        timestamp=int(obj["timestamp"]) if obj.get("timestamp") else None,
        duration=int(obj["duration"]) if obj.get("duration") else None,
        local_endpoint=_endpoint_from_dict(obj.get("localEndpoint")),
        remote_endpoint=_endpoint_from_dict(obj.get("remoteEndpoint")),
        annotations=annotations,
        tags={str(k): str(v) for k, v in tags.items()},
        debug=bool(obj.get("debug")) or None,
        shared=bool(obj.get("shared")) or None,
    )


# -- bytes-level API (the codec surface storage/server use) ----------------


def encode_span(span: Span) -> bytes:
    return json.dumps(span_to_dict(span), separators=(",", ":")).encode()


def encode_span_list(spans: Sequence[Span]) -> bytes:
    return json.dumps(
        [span_to_dict(s) for s in spans], separators=(",", ":")
    ).encode()


def encode_traces(traces: Sequence[Sequence[Span]]) -> bytes:
    return json.dumps(
        [[span_to_dict(s) for s in t] for t in traces], separators=(",", ":")
    ).encode()


def decode_span_list(data: bytes) -> List[Span]:
    parsed = json.loads(data)
    if not isinstance(parsed, list):
        raise ValueError("expected a JSON array of spans")
    return [span_from_dict(o) for o in parsed]


def decode_one_span(data: bytes) -> Span:
    return span_from_dict(json.loads(data))


# -- dependency links ------------------------------------------------------


def link_to_dict(link: DependencyLink) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "parent": link.parent,
        "child": link.child,
        "callCount": link.call_count,
    }
    if link.error_count:
        out["errorCount"] = link.error_count
    return out


def encode_link_list(links: Sequence[DependencyLink]) -> bytes:
    return json.dumps([link_to_dict(x) for x in links], separators=(",", ":")).encode()


def decode_link_list(data: bytes) -> List[DependencyLink]:
    return [
        DependencyLink(
            parent=o["parent"],
            child=o["child"],
            call_count=int(o.get("callCount", 0)),
            error_count=int(o.get("errorCount", 0)),
        )
        for o in json.loads(data)
    ]
