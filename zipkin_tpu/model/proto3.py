"""Hand-rolled protobuf codec for ``zipkin.proto`` (no protoc runtime).

Reference semantics: ``zipkin2/internal/Proto3Codec.java``,
``Proto3Fields.java``, ``Proto3ZipkinFields.java`` (SURVEY.md §2.1). Encodes
and decodes the proto3 ``ListOfSpans`` message used by ``POST /api/v2/spans``
with content-type ``application/x-protobuf`` and by the gRPC
``zipkin.proto3.SpanService/Report`` endpoint.

Message schema (zipkin.proto):

- ``Span``: trace_id=1 bytes(8|16), parent_id=2 bytes(8), id=3 bytes(8),
  kind=4 enum, name=5 string, timestamp=6 fixed64, duration=7 uint64,
  local_endpoint=8, remote_endpoint=9, annotations=10 repeated,
  tags=11 map<string,string>, debug=12 bool, shared=13 bool
- ``Endpoint``: service_name=1 string, ipv4=2 bytes(4), ipv6=3 bytes(16),
  port=4 int32
- ``Annotation``: timestamp=1 fixed64, value=2 string
- ``ListOfSpans``: spans=1 repeated Span
"""

from __future__ import annotations

import ipaddress
import struct
from typing import List, Optional, Sequence, Tuple

from zipkin_tpu.model.span import Annotation, Endpoint, Kind, Span

_WIRE_VARINT = 0
_WIRE_FIXED64 = 1
_WIRE_LEN = 2
_WIRE_FIXED32 = 5

_KIND_TO_ENUM = {Kind.CLIENT: 1, Kind.SERVER: 2, Kind.PRODUCER: 3, Kind.CONSUMER: 4}
_ENUM_TO_KIND = {v: k for k, v in _KIND_TO_ENUM.items()}


# -- primitive writers -----------------------------------------------------


def _write_varint(buf: bytearray, value: int) -> None:
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            buf.append(bits | 0x80)
        else:
            buf.append(bits)
            return


def _key(field: int, wire: int) -> int:
    return (field << 3) | wire


def _write_len_field(buf: bytearray, field: int, payload: bytes) -> None:
    _write_varint(buf, _key(field, _WIRE_LEN))
    _write_varint(buf, len(payload))
    buf.extend(payload)


def _write_string(buf: bytearray, field: int, value: str) -> None:
    _write_len_field(buf, field, value.encode())


def _write_bool(buf: bytearray, field: int, value: bool) -> None:
    _write_varint(buf, _key(field, _WIRE_VARINT))
    buf.append(1 if value else 0)


def _write_fixed64(buf: bytearray, field: int, value: int) -> None:
    _write_varint(buf, _key(field, _WIRE_FIXED64))
    buf.extend(struct.pack("<Q", value))


# -- encode ----------------------------------------------------------------


def _encode_endpoint(ep: Endpoint) -> bytes:
    buf = bytearray()
    if ep.service_name:
        _write_string(buf, 1, ep.service_name)
    if ep.ipv4:
        _write_len_field(buf, 2, ipaddress.IPv4Address(ep.ipv4).packed)
    if ep.ipv6:
        _write_len_field(buf, 3, ipaddress.IPv6Address(ep.ipv6).packed)
    if ep.port:
        _write_varint(buf, _key(4, _WIRE_VARINT))
        _write_varint(buf, ep.port)
    return bytes(buf)


def encode_span(span: Span) -> bytes:
    buf = bytearray()
    _write_len_field(buf, 1, bytes.fromhex(span.trace_id))
    if span.parent_id:
        _write_len_field(buf, 2, bytes.fromhex(span.parent_id))
    _write_len_field(buf, 3, bytes.fromhex(span.id))
    if span.kind is not None:
        _write_varint(buf, _key(4, _WIRE_VARINT))
        _write_varint(buf, _KIND_TO_ENUM[span.kind])
    if span.name:
        _write_string(buf, 5, span.name)
    if span.timestamp:
        _write_fixed64(buf, 6, span.timestamp)
    if span.duration:
        _write_varint(buf, _key(7, _WIRE_VARINT))
        _write_varint(buf, span.duration)
    if span.local_endpoint is not None:
        _write_len_field(buf, 8, _encode_endpoint(span.local_endpoint))
    if span.remote_endpoint is not None:
        _write_len_field(buf, 9, _encode_endpoint(span.remote_endpoint))
    for a in span.annotations:
        ann = bytearray()
        _write_fixed64(ann, 1, a.timestamp)
        _write_string(ann, 2, a.value)
        _write_len_field(buf, 10, bytes(ann))
    for k, v in span.tags.items():
        entry = bytearray()
        _write_string(entry, 1, k)
        _write_string(entry, 2, v)
        _write_len_field(buf, 11, bytes(entry))
    if span.debug:
        _write_bool(buf, 12, True)
    if span.shared:
        _write_bool(buf, 13, True)
    return bytes(buf)


def encode_span_list(spans: Sequence[Span]) -> bytes:
    """Encode ``ListOfSpans`` (each span is field 1, length-delimited)."""
    buf = bytearray()
    for span in spans:
        _write_len_field(buf, 1, encode_span(span))
    return bytes(buf)


# -- decode ----------------------------------------------------------------


class _Reader:
    __slots__ = ("data", "pos", "end")

    def __init__(self, data: bytes, pos: int = 0, end: Optional[int] = None) -> None:
        self.data = data
        self.pos = pos
        self.end = len(data) if end is None else end

    def done(self) -> bool:
        return self.pos >= self.end

    def varint(self) -> int:
        result = 0
        shift = 0
        while True:
            if self.pos >= self.end:
                raise ValueError("truncated varint")
            b = self.data[self.pos]
            self.pos += 1
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                return result
            shift += 7
            if shift > 63:
                raise ValueError("varint too long")

    def fixed64(self) -> int:
        if self.pos + 8 > self.end:
            raise ValueError("truncated fixed64")
        (value,) = struct.unpack_from("<Q", self.data, self.pos)
        self.pos += 8
        return value

    def bytes_field(self) -> bytes:
        n = self.varint()
        if self.pos + n > self.end:
            raise ValueError("truncated length-delimited field")
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def skip(self, wire: int) -> None:
        if wire == _WIRE_VARINT:
            self.varint()
        elif wire == _WIRE_FIXED64:
            self.pos += 8
        elif wire == _WIRE_LEN:
            self.bytes_field()
        elif wire == _WIRE_FIXED32:
            self.pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")


def _decode_endpoint(data: bytes) -> Optional[Endpoint]:
    r = _Reader(data)
    service = ipv4 = ipv6 = None
    port = None
    while not r.done():
        tag = r.varint()
        field, wire = tag >> 3, tag & 7
        if field == 1 and wire == _WIRE_LEN:
            service = r.bytes_field().decode()
        elif field == 2 and wire == _WIRE_LEN:
            raw = r.bytes_field()
            ipv4 = str(ipaddress.IPv4Address(raw)) if len(raw) == 4 else None
        elif field == 3 and wire == _WIRE_LEN:
            raw = r.bytes_field()
            ipv6 = str(ipaddress.IPv6Address(raw)) if len(raw) == 16 else None
        elif field == 4 and wire == _WIRE_VARINT:
            port = r.varint()
        else:
            r.skip(wire)
    return Endpoint.create(service_name=service, ipv4=ipv4, ipv6=ipv6, port=port)


def _decode_annotation(data: bytes) -> Optional[Annotation]:
    r = _Reader(data)
    timestamp = 0
    value = ""
    while not r.done():
        tag = r.varint()
        field, wire = tag >> 3, tag & 7
        if field == 1 and wire == _WIRE_FIXED64:
            timestamp = r.fixed64()
        elif field == 2 and wire == _WIRE_LEN:
            value = r.bytes_field().decode()
        else:
            r.skip(wire)
    if timestamp <= 0 or not value:
        return None
    return Annotation(timestamp, value)


def decode_span(data: bytes) -> Span:
    r = _Reader(data)
    trace_id = span_id = ""
    parent_id = name = None
    kind = None
    timestamp = duration = None
    local = remote = None
    annotations: List[Annotation] = []
    tags = {}
    debug = shared = None
    while not r.done():
        tag = r.varint()
        field, wire = tag >> 3, tag & 7
        if field == 1 and wire == _WIRE_LEN:
            trace_id = r.bytes_field().hex()
        elif field == 2 and wire == _WIRE_LEN:
            parent_id = r.bytes_field().hex()
        elif field == 3 and wire == _WIRE_LEN:
            span_id = r.bytes_field().hex()
        elif field == 4 and wire == _WIRE_VARINT:
            kind = _ENUM_TO_KIND.get(r.varint())
        elif field == 5 and wire == _WIRE_LEN:
            name = r.bytes_field().decode()
        elif field == 6 and wire == _WIRE_FIXED64:
            timestamp = r.fixed64()
        elif field == 7 and wire == _WIRE_VARINT:
            duration = r.varint()
        elif field == 8 and wire == _WIRE_LEN:
            local = _decode_endpoint(r.bytes_field())
        elif field == 9 and wire == _WIRE_LEN:
            remote = _decode_endpoint(r.bytes_field())
        elif field == 10 and wire == _WIRE_LEN:
            ann = _decode_annotation(r.bytes_field())
            if ann is not None:
                annotations.append(ann)
        elif field == 11 and wire == _WIRE_LEN:
            er = _Reader(r.bytes_field())
            key = value = ""
            while not er.done():
                etag = er.varint()
                efield, ewire = etag >> 3, etag & 7
                if efield == 1 and ewire == _WIRE_LEN:
                    key = er.bytes_field().decode()
                elif efield == 2 and ewire == _WIRE_LEN:
                    value = er.bytes_field().decode()
                else:
                    er.skip(ewire)
            if key:
                tags[key] = value
        elif field == 12 and wire == _WIRE_VARINT:
            debug = bool(r.varint())
        elif field == 13 and wire == _WIRE_VARINT:
            shared = bool(r.varint())
        else:
            r.skip(wire)
    return Span.create(
        trace_id=trace_id,
        id=span_id,
        parent_id=parent_id,
        kind=kind,
        name=name,
        timestamp=timestamp,
        duration=duration,
        local_endpoint=local,
        remote_endpoint=remote,
        annotations=annotations,
        tags=tags,
        debug=debug,
        shared=shared,
    )


def decode_span_list(data: bytes) -> List[Span]:
    r = _Reader(data)
    spans: List[Span] = []
    while not r.done():
        tag = r.varint()
        field, wire = tag >> 3, tag & 7
        if field == 1 and wire == _WIRE_LEN:
            spans.append(decode_span(r.bytes_field()))
        else:
            r.skip(wire)
    return spans
