"""The Zipkin v2 data model: Span, Endpoint, Annotation, DependencyLink.

Reference semantics: ``zipkin2/Span.java``, ``zipkin2/Endpoint.java``,
``zipkin2/Annotation.java``, ``zipkin2/DependencyLink.java`` (SURVEY.md §2.1).

Normalization contract (applied at construction, so equality and storage keys
are canonical everywhere downstream):

- trace ids: 16 or 32 lower-hex chars, left zero-padded; span ids 16 chars;
  an all-zero parentId means "no parent" (None);
- service names and span names are lowercased; empty strings become None;
- timestamps are epoch **microseconds**, durations microseconds (0 -> None);
- annotations are sorted by (timestamp, value) and de-duplicated;
- Endpoint ports of 0 mean None; IPv6-mapped IPv4 addresses are stored as
  their IPv4 form, matching ``Endpoint.Builder#parseIp``.

These are plain frozen dataclasses — the row-oriented form used by codecs,
the oracle store, and tests. The TPU ingest path uses the columnar
struct-of-arrays form in :mod:`zipkin_tpu.tpu.columnar` instead.
"""

from __future__ import annotations

import dataclasses
import enum
import ipaddress
from typing import Dict, Mapping, Optional, Sequence, Tuple

from zipkin_tpu.internal.hex import (
    lower_64,
    normalize_parent_id,
    normalize_span_id,
    normalize_trace_id,
)


class Kind(enum.Enum):
    """The role a span plays in an RPC or messaging exchange."""

    CLIENT = "CLIENT"
    SERVER = "SERVER"
    PRODUCER = "PRODUCER"
    CONSUMER = "CONSUMER"

    @staticmethod
    def parse(value: Optional[str]) -> Optional["Kind"]:
        if value is None or value == "":
            return None
        try:
            return Kind[value.upper()]
        except KeyError:
            raise ValueError(f"unknown kind: {value!r}") from None


def _lower_or_none(value: Optional[str]) -> Optional[str]:
    if value is None or value == "":
        return None
    return value.lower()


@dataclasses.dataclass(frozen=True, order=True)
class Annotation:
    """A timestamped event of interest within a span (epoch-µs, value)."""

    timestamp: int
    value: str

    def __post_init__(self) -> None:
        if self.timestamp <= 0:
            raise ValueError("annotation timestamp must be positive epoch µs")
        if not self.value:
            raise ValueError("annotation value is required")


@dataclasses.dataclass(frozen=True)
class Endpoint:
    """The network context of a node in the service graph.

    ``service_name`` is the primary join key of the whole system (lowercase).
    """

    service_name: Optional[str] = None
    ipv4: Optional[str] = None
    ipv6: Optional[str] = None
    port: Optional[int] = None

    @staticmethod
    def create(
        service_name: Optional[str] = None,
        ip: Optional[str] = None,
        port: Optional[int] = None,
        *,
        ipv4: Optional[str] = None,
        ipv6: Optional[str] = None,
    ) -> Optional["Endpoint"]:
        """Build a normalized endpoint; returns None if every field is empty.

        ``ip`` may be either address family and is routed to the right slot
        (mirrors ``Endpoint.Builder#parseIp``). Unparseable IPs are dropped,
        not raised — matching the reference's lenient ingest posture.
        """
        name = _lower_or_none(service_name)
        v4: Optional[str] = None
        v6: Optional[str] = None
        for candidate in (ip, ipv4, ipv6):
            if candidate is None or candidate == "":
                continue
            try:
                parsed = ipaddress.ip_address(candidate)
            except ValueError:
                continue
            if isinstance(parsed, ipaddress.IPv6Address):
                mapped = parsed.ipv4_mapped
                if mapped is not None:
                    v4 = v4 or str(mapped)
                else:
                    v6 = v6 or str(parsed)
            else:
                v4 = v4 or str(parsed)
        if port is not None:
            if not 0 <= port <= 0xFFFF:
                raise ValueError(f"port out of range: {port}")
            if port == 0:
                port = None
        if name is None and v4 is None and v6 is None and port is None:
            return None
        return Endpoint(service_name=name, ipv4=v4, ipv6=v6, port=port)


@dataclasses.dataclass(frozen=True)
class Span:
    """One unit of work in a trace, normalized per the module docstring.

    Construct via :meth:`Span.create` (which normalizes) rather than the raw
    dataclass constructor, unless the fields are already canonical.
    """

    trace_id: str
    id: str
    parent_id: Optional[str] = None
    kind: Optional[Kind] = None
    name: Optional[str] = None
    timestamp: Optional[int] = None  # epoch µs
    duration: Optional[int] = None  # µs
    local_endpoint: Optional[Endpoint] = None
    remote_endpoint: Optional[Endpoint] = None
    annotations: Tuple[Annotation, ...] = ()
    tags: Mapping[str, str] = dataclasses.field(default_factory=dict)
    debug: Optional[bool] = None
    shared: Optional[bool] = None

    @staticmethod
    def create(
        trace_id: str,
        id: str,
        *,
        parent_id: Optional[str] = None,
        kind: Optional[Kind | str] = None,
        name: Optional[str] = None,
        timestamp: Optional[int] = None,
        duration: Optional[int] = None,
        local_endpoint: Optional[Endpoint] = None,
        remote_endpoint: Optional[Endpoint] = None,
        annotations: Sequence[Annotation | Tuple[int, str]] = (),
        tags: Optional[Mapping[str, str]] = None,
        debug: Optional[bool] = None,
        shared: Optional[bool] = None,
    ) -> "Span":
        norm_annotations = tuple(
            sorted(
                {
                    a if isinstance(a, Annotation) else Annotation(a[0], a[1])
                    for a in annotations
                }
            )
        )
        if isinstance(kind, str):
            kind = Kind.parse(kind)
        if timestamp is not None and timestamp <= 0:
            timestamp = None
        if duration is not None and duration <= 0:
            duration = None
        return Span(
            trace_id=normalize_trace_id(trace_id),
            id=normalize_span_id(id),
            parent_id=normalize_parent_id(parent_id),
            kind=kind,
            name=_lower_or_none(name),
            timestamp=timestamp,
            duration=duration,
            local_endpoint=local_endpoint,
            remote_endpoint=remote_endpoint,
            annotations=norm_annotations,
            tags=dict(tags) if tags else {},
            debug=debug if debug else None,
            shared=shared if shared else None,
        )

    # -- derived accessors ------------------------------------------------

    @property
    def local_service_name(self) -> Optional[str]:
        ep = self.local_endpoint
        return ep.service_name if ep is not None else None

    @property
    def remote_service_name(self) -> Optional[str]:
        ep = self.remote_endpoint
        return ep.service_name if ep is not None else None

    @property
    def trace_id_low64(self) -> int:
        return lower_64(self.trace_id)

    @property
    def is_error(self) -> bool:
        """Zipkin's error convention: presence of an ``error`` tag."""
        return "error" in self.tags

    def timestamp_as_long(self) -> int:
        return self.timestamp or 0

    def duration_as_long(self) -> int:
        return self.duration or 0

    # -- hashing for columnar/device keys ---------------------------------

    def __hash__(self) -> int:
        return hash((self.trace_id, self.id, self.shared, self.timestamp))

    def key(self) -> Tuple[str, str, Optional[bool], Optional[str]]:
        """Identity used for de-dup/merge: a client span and the shared
        server half of the same RPC have equal ids but distinct keys.

        Reference: the merge keying inside ``zipkin2/internal/Trace.java``.
        """
        return (self.trace_id, self.id, self.shared, self.local_service_name)


def merge_spans(left: Span, right: Span) -> Span:
    """Merge two reports of the same span (same :meth:`Span.key`).

    Field-wise union preferring the earlier-known value, mirroring
    ``Span.Builder#merge`` as used by ``Trace.merge``: annotations and tags
    union; timestamp takes the smaller nonzero; duration the larger; flags OR.
    """
    if left.key() != right.key():
        raise ValueError("cannot merge spans with different identities")
    tags: Dict[str, str] = dict(left.tags)
    for k, v in right.tags.items():
        tags.setdefault(k, v)
    ts_candidates = [t for t in (left.timestamp, right.timestamp) if t]
    return Span(
        trace_id=left.trace_id,
        id=left.id,
        parent_id=left.parent_id or right.parent_id,
        kind=left.kind or right.kind,
        name=left.name or right.name,
        timestamp=min(ts_candidates) if ts_candidates else None,
        duration=max(left.duration or 0, right.duration or 0) or None,
        local_endpoint=left.local_endpoint or right.local_endpoint,
        remote_endpoint=left.remote_endpoint or right.remote_endpoint,
        annotations=tuple(sorted(set(left.annotations) | set(right.annotations))),
        tags=tags,
        debug=left.debug or right.debug,
        shared=left.shared or right.shared,
    )


@dataclasses.dataclass(frozen=True)
class DependencyLink:
    """An aggregated parent->child service edge with call/error counts."""

    parent: str
    child: str
    call_count: int = 0
    error_count: int = 0

    @staticmethod
    def create(parent: str, child: str, call_count: int, error_count: int = 0) -> "DependencyLink":
        return DependencyLink(parent.lower(), child.lower(), call_count, error_count)


def merge_links(links: Sequence[DependencyLink]) -> Tuple[DependencyLink, ...]:
    """Sum call/error counts across links sharing (parent, child).

    The read-side merge for daily-rollup dependency queries.
    """
    acc: Dict[Tuple[str, str], Tuple[int, int]] = {}
    order = []
    for link in links:
        k = (link.parent, link.child)
        if k not in acc:
            acc[k] = (0, 0)
            order.append(k)
        calls, errors = acc[k]
        acc[k] = (calls + link.call_count, errors + link.error_count)
    return tuple(
        DependencyLink(parent=k[0], child=k[1], call_count=acc[k][0], error_count=acc[k][1])
        for k in order
    )
