"""Legacy thrift (TBinaryProtocol) codec for v1 spans — the Scribe path.

Reference semantics: ``zipkin2/internal/ThriftCodec.java`` (SURVEY.md §2.1).
Decodes a thrift list of v1 Span structs (the payload Scribe delivered
base64-encoded) into v2 spans via :mod:`zipkin_tpu.model.json_v1`'s
converter. Struct schema (zipkinCore.thrift):

- Span: 1:i64 trace_id, 3:string name, 4:i64 id, 5:i64 parent_id,
  6:list<Annotation> annotations, 8:list<BinaryAnnotation> binary_annotations,
  9:bool debug, 10:i64 timestamp, 11:i64 duration, 12:i64 trace_id_high
- Annotation: 1:i64 timestamp, 2:string value, 3:Endpoint host
- BinaryAnnotation: 1:string key, 2:binary value, 3:i32 annotation_type,
  4:Endpoint host  (types: 0=BOOL, 6=STRING; others stringified)
- Endpoint: 1:i32 ipv4, 2:i16 port, 3:string service_name, 4:binary ipv6
"""

from __future__ import annotations

import ipaddress
import struct
from typing import List, Optional

from zipkin_tpu.internal.hex import to_lower_hex
from zipkin_tpu.model.json_v1 import (
    V1Annotation,
    V1BinaryAnnotation,
    V1Span,
    convert_v1_spans,
)
from zipkin_tpu.model.span import Endpoint, Span

_T_STOP = 0
_T_BOOL = 2
_T_BYTE = 3
_T_DOUBLE = 4
_T_I16 = 6
_T_I32 = 8
_T_I64 = 10
_T_STRING = 11
_T_STRUCT = 12
_T_MAP = 13
_T_SET = 14
_T_LIST = 15


class _Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes, pos: int = 0) -> None:
        self.data = data
        self.pos = pos

    def u8(self) -> int:
        v = self.data[self.pos]
        self.pos += 1
        return v

    def i16(self) -> int:
        (v,) = struct.unpack_from(">h", self.data, self.pos)
        self.pos += 2
        return v

    def i32(self) -> int:
        (v,) = struct.unpack_from(">i", self.data, self.pos)
        self.pos += 4
        return v

    def i64(self) -> int:
        (v,) = struct.unpack_from(">q", self.data, self.pos)
        self.pos += 8
        return v

    def binary(self) -> bytes:
        n = self.i32()
        if n < 0 or self.pos + n > len(self.data):
            raise ValueError("truncated thrift binary")
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def _count(self) -> int:
        """Container element count, validated against the readable buffer.

        Attacker-controlled i32 counts (up to 2^31) must be bounded by the
        bytes remaining — every element occupies >= 1 byte — or a ~20-byte
        payload declaring ``list<bool>`` count=0x7FFFFFFF burns minutes of
        CPU per request. Mirrors ThriftCodec's guard of lengths against the
        readable buffer (SURVEY.md §2.1).
        """
        n = self.i32()
        if n < 0 or n > len(self.data) - self.pos:
            raise ValueError("thrift container count exceeds buffer")
        return n

    def skip(self, ttype: int) -> None:
        if ttype in (_T_BOOL, _T_BYTE):
            self.pos += 1
        elif ttype == _T_I16:
            self.pos += 2
        elif ttype == _T_I32:
            self.pos += 4
        elif ttype in (_T_I64, _T_DOUBLE):
            self.pos += 8
        elif ttype == _T_STRING:
            self.binary()
        elif ttype == _T_STRUCT:
            while True:
                ft = self.u8()
                if ft == _T_STOP:
                    return
                self.i16()
                self.skip(ft)
        elif ttype in (_T_LIST, _T_SET):
            et = self.u8()
            for _ in range(self._count()):
                self.skip(et)
        elif ttype == _T_MAP:
            kt, vt = self.u8(), self.u8()
            for _ in range(self._count()):
                self.skip(kt)
                self.skip(vt)
        else:
            raise ValueError(f"unknown thrift type {ttype}")
        if self.pos > len(self.data):
            raise ValueError("truncated thrift payload")


def _read_endpoint(r: _Reader) -> Optional[Endpoint]:
    ipv4 = None
    port = None
    service = None
    ipv6 = None
    while True:
        ftype = r.u8()
        if ftype == _T_STOP:
            break
        fid = r.i16()
        if fid == 1 and ftype == _T_I32:
            raw = r.i32() & 0xFFFFFFFF
            ipv4 = str(ipaddress.IPv4Address(raw)) if raw else None
        elif fid == 2 and ftype == _T_I16:
            port = r.i16() & 0xFFFF
        elif fid == 3 and ftype == _T_STRING:
            service = r.binary().decode(errors="replace")
        elif fid == 4 and ftype == _T_STRING:
            raw = r.binary()
            ipv6 = str(ipaddress.IPv6Address(raw)) if len(raw) == 16 else None
        else:
            r.skip(ftype)
    return Endpoint.create(service_name=service, ipv4=ipv4, ipv6=ipv6, port=port)


def _read_annotation(r: _Reader) -> Optional[V1Annotation]:
    ts = 0
    value = ""
    host = None
    while True:
        ftype = r.u8()
        if ftype == _T_STOP:
            break
        fid = r.i16()
        if fid == 1 and ftype == _T_I64:
            ts = r.i64()
        elif fid == 2 and ftype == _T_STRING:
            value = r.binary().decode(errors="replace")
        elif fid == 3 and ftype == _T_STRUCT:
            host = _read_endpoint(r)
        else:
            r.skip(ftype)
    if ts <= 0 or not value:
        return None
    return V1Annotation(ts, value, host)


_TYPE_BOOL = 0
_TYPE_STRING = 6


def _read_binary_annotation(r: _Reader) -> Optional[V1BinaryAnnotation]:
    key = None
    raw: bytes = b""
    ann_type = _TYPE_STRING
    host = None
    while True:
        ftype = r.u8()
        if ftype == _T_STOP:
            break
        fid = r.i16()
        if fid == 1 and ftype == _T_STRING:
            key = r.binary().decode(errors="replace")
        elif fid == 2 and ftype == _T_STRING:
            raw = r.binary()
        elif fid == 3 and ftype == _T_I32:
            ann_type = r.i32()
        elif fid == 4 and ftype == _T_STRUCT:
            host = _read_endpoint(r)
        else:
            r.skip(ftype)
    if key is None:
        return None
    if ann_type == _TYPE_BOOL:
        return V1BinaryAnnotation(key, raw == b"\x01" or raw == b"\x00\x01" or bool(raw and raw[-1]), host)
    return V1BinaryAnnotation(key, raw.decode(errors="replace"), host)


def _read_v1_span(r: _Reader) -> V1Span:
    trace_id = 0
    trace_id_high = 0
    span_id = 0
    parent_id = 0
    name = None
    annotations: List[V1Annotation] = []
    binary: List[V1BinaryAnnotation] = []
    debug = None
    timestamp = None
    duration = None
    while True:
        ftype = r.u8()
        if ftype == _T_STOP:
            break
        fid = r.i16()
        if fid == 1 and ftype == _T_I64:
            trace_id = r.i64()
        elif fid == 3 and ftype == _T_STRING:
            name = r.binary().decode(errors="replace")
        elif fid == 4 and ftype == _T_I64:
            span_id = r.i64()
        elif fid == 5 and ftype == _T_I64:
            parent_id = r.i64()
        elif fid == 6 and ftype == _T_LIST:
            r.u8()  # element type (struct)
            for _ in range(r._count()):
                ann = _read_annotation(r)
                if ann is not None:
                    annotations.append(ann)
        elif fid == 8 and ftype == _T_LIST:
            r.u8()
            for _ in range(r._count()):
                b = _read_binary_annotation(r)
                if b is not None:
                    binary.append(b)
        elif fid == 9 and ftype == _T_BOOL:
            debug = bool(r.u8())
        elif fid == 10 and ftype == _T_I64:
            timestamp = r.i64()
        elif fid == 11 and ftype == _T_I64:
            duration = r.i64()
        elif fid == 12 and ftype == _T_I64:
            trace_id_high = r.i64()
        else:
            r.skip(ftype)
    if trace_id_high:
        tid = to_lower_hex(trace_id_high) + to_lower_hex(trace_id)
    else:
        tid = to_lower_hex(trace_id)
    return V1Span(
        trace_id=tid,
        id=to_lower_hex(span_id),
        parent_id=to_lower_hex(parent_id) if parent_id else None,
        name=name,
        timestamp=timestamp,
        duration=duration,
        annotations=tuple(annotations),
        binary_annotations=tuple(binary),
        debug=debug,
    )


def decode_span_list(data: bytes) -> List[Span]:
    """Decode a thrift list<Span> (first byte 0x0c = T_STRUCT element type)."""
    r = _Reader(data)
    etype = r.u8()
    if etype != _T_STRUCT:
        raise ValueError("expected thrift list of structs")
    count = r._count()
    v1_spans = [_read_v1_span(r) for _ in range(count)]
    return convert_v1_spans(v1_spans)


# -- writer (SpanBytesEncoder.THRIFT parity) -------------------------------


class _Writer:
    """Minimal TBinaryProtocol writer."""

    def __init__(self) -> None:
        self.parts: List[bytes] = []

    def u8(self, v: int) -> None:
        self.parts.append(struct.pack(">B", v))

    def i16(self, v: int) -> None:
        self.parts.append(struct.pack(">h", v))

    def i32(self, v: int) -> None:
        self.parts.append(struct.pack(">i", v))

    def i64(self, v: int) -> None:
        self.parts.append(struct.pack(">q", v & 0xFFFFFFFFFFFFFFFF if v >= 0 else v))

    def binary(self, v: bytes) -> None:
        self.i32(len(v))
        self.parts.append(v)

    def field(self, ftype: int, fid: int) -> None:
        self.u8(ftype)
        self.i16(fid)

    def stop(self) -> None:
        self.u8(_T_STOP)

    def bytes(self) -> bytes:
        return b"".join(self.parts)


def _u64(hex_id: Optional[str]) -> int:
    return int(hex_id, 16) if hex_id else 0


def _signed64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _write_endpoint(w: _Writer, ep: Optional[Endpoint]) -> None:
    if ep is None:
        ep = Endpoint()
    if ep.ipv4:
        w.field(_T_I32, 1)
        w.i32(int(ipaddress.IPv4Address(ep.ipv4)) - (1 << 32) if int(ipaddress.IPv4Address(ep.ipv4)) >= (1 << 31) else int(ipaddress.IPv4Address(ep.ipv4)))
    if ep.port:
        w.field(_T_I16, 2)
        w.i16(ep.port - (1 << 16) if ep.port >= (1 << 15) else ep.port)
    w.field(_T_STRING, 3)
    w.binary((ep.service_name or "").encode())
    if ep.ipv6:
        w.field(_T_STRING, 4)
        w.binary(ipaddress.IPv6Address(ep.ipv6).packed)
    w.stop()


_BEGIN_END = {
    "CLIENT": ("cs", "cr"),
    "SERVER": ("sr", "ss"),
    "PRODUCER": ("ms", None),
    "CONSUMER": ("mr", None),
}
_ADDR = {"CLIENT": "sa", "SERVER": "ca", "PRODUCER": "ma", "CONSUMER": "ma"}


def encode_span(span: Span) -> bytes:
    """One v2 span as a thrift v1 Span struct (the scribe message body).

    Same v2->v1 mapping as the JSON v1 encoder: kind becomes cs/cr/sr/ss
    core annotations, tags become string binary annotations,
    remoteEndpoint the matching address annotation.
    """
    w = _Writer()
    w.field(_T_I64, 1)
    w.i64(_signed64(_u64(span.trace_id[-16:])))
    w.field(_T_STRING, 3)
    w.binary((span.name or "").encode())
    w.field(_T_I64, 4)
    w.i64(_signed64(_u64(span.id)))
    if span.parent_id:
        w.field(_T_I64, 5)
        w.i64(_signed64(_u64(span.parent_id)))

    anns = []
    kind = span.kind.value if span.kind else None
    begin_end = _BEGIN_END.get(kind) if kind else None
    if begin_end and span.timestamp:
        begin, end = begin_end
        anns.append((span.timestamp, begin))
        if end and span.duration:
            anns.append((span.timestamp + span.duration, end))
    for a in span.annotations:
        anns.append((a.timestamp, a.value))
    w.field(_T_LIST, 6)
    w.u8(_T_STRUCT)
    w.i32(len(anns))
    for ts, value in anns:
        w.field(_T_I64, 1)
        w.i64(ts)
        w.field(_T_STRING, 2)
        w.binary(value.encode())
        w.field(_T_STRUCT, 3)
        _write_endpoint(w, span.local_endpoint)
        w.stop()

    bins = [(k, v.encode(), 6, span.local_endpoint) for k, v in span.tags.items()]
    if span.remote_endpoint is not None and kind:
        bins.append((_ADDR[kind], b"\x01", 0, span.remote_endpoint))
    w.field(_T_LIST, 8)
    w.u8(_T_STRUCT)
    w.i32(len(bins))
    for key, value, btype, ep in bins:
        w.field(_T_STRING, 1)
        w.binary(key.encode())
        w.field(_T_STRING, 2)
        w.binary(value)
        w.field(_T_I32, 3)
        w.i32(btype)
        w.field(_T_STRUCT, 4)
        _write_endpoint(w, ep)
        w.stop()

    if span.debug:
        w.field(_T_BOOL, 9)
        w.u8(1)
    if span.timestamp and not span.shared:
        w.field(_T_I64, 10)
        w.i64(span.timestamp)
    if span.duration and not span.shared:
        w.field(_T_I64, 11)
        w.i64(span.duration)
    if len(span.trace_id) == 32:
        w.field(_T_I64, 12)
        w.i64(_signed64(_u64(span.trace_id[:16])))
    w.stop()
    return w.bytes()


def encode_span_list(spans: List[Span]) -> bytes:
    """thrift list<Span> (first byte 0x0c), the ingest wire shape."""
    w = _Writer()
    w.u8(_T_STRUCT)
    w.i32(len(spans))
    out = [w.bytes()]
    out.extend(encode_span(s) for s in spans)
    return b"".join(out)
