"""Native host tier: the C fast-path codec.

Where the reference's performance-critical inner loops live in
hand-rolled Java (``zipkin2/internal/{ReadBuffer,WriteBuffer}.java``),
this package holds the C equivalents, compiled on demand with the
system toolchain and loaded via ctypes — no pip dependencies.

Graceful degradation is part of the contract: if no compiler is
available, or the payload uses features the fast path doesn't cover
(escaped strings, unknown kinds), callers fall back to the pure-Python
codec, which is the semantic reference.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "span_json.c")
_BUILD_DIR = os.path.join(_DIR, "build")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _compile() -> Optional[str]:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    so_path = os.path.join(_BUILD_DIR, f"span_json-{digest}.so")
    if os.path.exists(so_path):
        return so_path
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tmp = so_path + ".tmp"
    for cc in ("cc", "gcc", "clang"):
        try:
            subprocess.run(
                [cc, "-O2", "-fPIC", "-shared", "-o", tmp, _SRC],
                check=True, capture_output=True, timeout=120,
            )
            os.replace(tmp, so_path)
            return so_path
        except FileNotFoundError:
            continue
        except subprocess.CalledProcessError as e:
            logger.warning("native codec build failed with %s: %s", cc, e.stderr)
            return None
    logger.warning("no C compiler found; native codec disabled")
    return None


def _load():
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        so_path = _compile()
        if so_path is None:
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(so_path)
        except OSError:
            # A stale cached .so (e.g. built on a different arch/libc)
            # would otherwise disable the native codec forever, since the
            # source digest still matches. Evict it and rebuild once.
            try:
                os.unlink(so_path)
            except OSError:
                pass
            so_path = _compile()
            if so_path is None:
                _build_failed = True
                return None
            try:
                lib = ctypes.CDLL(so_path)
            except OSError as e:
                logger.warning("native codec load failed (%s); disabled", e)
                _build_failed = True
                return None
        u32p = ctypes.POINTER(ctypes.c_uint32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        base = (
            [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_long]
            + [u32p] * 8  # id lanes
            + [u8p] * 4   # shared, kind, err, has_dur
            + [u64p, u32p, u8p]  # ts, dur, debug
            + [u32p] * 6  # string slices
            + [u32p] * 2  # span byte extents
        )
        lib.zt_parse_spans.restype = ctypes.c_long
        lib.zt_parse_spans.argtypes = base
        lib.zt_parse_spans_interned.restype = ctypes.c_long
        lib.zt_parse_spans_interned.argtypes = (
            base[:3] + [ctypes.c_void_p] + base[3:] + [i32p] * 4
        )
        lib.zt_parse_proto3.restype = ctypes.c_long
        lib.zt_parse_proto3.argtypes = base
        lib.zt_parse_proto3_interned.restype = ctypes.c_long
        lib.zt_parse_proto3_interned.argtypes = (
            base[:3] + [ctypes.c_void_p] + base[3:] + [i32p] * 4
        )
        lib.zt_vocab_new.restype = ctypes.c_void_p
        lib.zt_vocab_new.argtypes = [ctypes.c_uint32] * 3
        lib.zt_vocab_free.argtypes = [ctypes.c_void_p]
        lib.zt_vocab_drain_strings.restype = ctypes.c_long
        lib.zt_vocab_drain_strings.argtypes = [
            ctypes.c_void_p, ctypes.c_int, u8p, ctypes.c_size_t,
        ]
        lib.zt_vocab_drain_pairs.restype = ctypes.c_long
        lib.zt_vocab_drain_pairs.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_long,
        ]
        lib.zt_vocab_overflow.restype = ctypes.c_long
        lib.zt_vocab_overflow.argtypes = [ctypes.c_void_p]
        lib.zt_vocab_counts.argtypes = [ctypes.c_void_p] + [u32p] * 3
        for fn in (lib.zt_intern_service, lib.zt_intern_name):
            fn.restype = ctypes.c_long
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
        for fn in (lib.zt_intern_pair, lib.zt_intern_pair_raw):
            fn.restype = ctypes.c_long
            fn.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32,
            ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


#: every per-span column of a parse result (the slice set chunking and
#: sampler filtering iterate over; ``data``/``n`` are handled separately)
PARSED_FIELDS = (
    "tl0", "tl1", "th0", "th1", "s0", "s1", "p0", "p1",
    "shared", "kind", "err", "has_dur", "ts_us", "dur_us",
    "debug", "svc_off", "svc_len", "rsvc_off", "rsvc_len",
    "name_off", "name_len", "span_off", "span_len",
    "svc_id", "rsvc_id", "name_id", "key_id",
)


class ParsedColumns:
    """Raw columnar parse result; string fields are (offset, len) slices
    into ``data`` (kept alive here). When parsed against a NativeVocab,
    the ``*_id`` columns are filled and interning is already done."""

    __slots__ = ("data", "n") + PARSED_FIELDS


def sampler_keep(parsed, n: int, boundary: int) -> np.ndarray:
    """[n] bool: which parsed spans a boundary sampler keeps — the exact
    numpy mirror of CollectorSampler.is_sampled on the trace id's low 64
    bits (Java parity: abs(MIN_VALUE) maps to MAX_VALUE so it drops at
    every rate < 1.0); debug spans always pass. Shared by the sync fast
    path and the multi-process workers so the two tiers drop identically.
    """
    lo = (
        parsed.tl1[:n].astype(np.uint64) << np.uint64(32)
    ) | parsed.tl0[:n].astype(np.uint64)
    signed = lo.view(np.int64)
    t = np.abs(signed)
    t = np.where(t == np.iinfo(np.int64).min, np.iinfo(np.int64).max, t)
    return (t <= boundary) | (parsed.debug[:n] != 0)


class NativeVocab:
    """C-side interning tables mirroring a Python Vocab.

    Ids are assigned by C in first-seen order; :meth:`sync` drains the
    insertion journal into the Python Vocab and asserts the ids line up,
    so everything downstream (lookup tables, snapshots) keeps working.
    Not thread-safe: callers serialize parse+sync (the store does).
    """

    def __init__(self, vocab) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError("native codec unavailable")
        self._lib = lib
        self.vocab = vocab
        self.handle = lib.zt_vocab_new(
            vocab.services.capacity - 1,
            vocab.span_names.capacity - 1,
            vocab.max_keys - 1,
        )
        if not self.handle:
            raise MemoryError("zt_vocab_new failed")
        self._drain_buf = np.zeros(1 << 20, np.uint8)
        self._pair_buf = np.zeros(1 << 16, np.uint64)

    @property
    def overflow(self) -> int:
        """Total intern attempts the C tables rejected at capacity (the
        fast path's analog of Interner.overflow — overflowing entries
        never reach the Python journal, so they must be read from C)."""
        return int(self._lib.zt_vocab_overflow(self.handle))

    def counts(self):
        a = ctypes.c_uint32()
        b = ctypes.c_uint32()
        c = ctypes.c_uint32()
        self._lib.zt_vocab_counts(
            self.handle, ctypes.byref(a), ctypes.byref(b), ctypes.byref(c)
        )
        return a.value, b.value, c.value

    def ensure_synced(self) -> None:
        """Bring the C tables up to date with the Python vocab.

        The two id spaces must be identical (both assign sequentially in
        first-seen order). If the object path interned entries the C side
        hasn't seen, replay the missing tail in id order; if the C side
        somehow diverged (should not happen), rebuild it from Python.
        """
        c_svc, c_name, c_pair = self.counts()
        v = self.vocab
        py_svc = len(v.services) - 1
        py_name = len(v.span_names) - 1
        py_pair = v.num_keys - 1
        if (c_svc, c_name, c_pair) == (py_svc, py_name, py_pair):
            return
        if c_svc > py_svc or c_name > py_name or c_pair > py_pair:
            # C ahead of Python: a sync() was missed; drain it now.
            self.sync()
            c_svc, c_name, c_pair = self.counts()
        lib = self._lib
        for nid in range(c_svc + 1, len(v.services._names)):
            raw = v.services._names[nid].encode()
            got = lib.zt_intern_service(self.handle, raw, len(raw))
            assert got == nid, (got, nid, raw)
        for nid in range(c_name + 1, len(v.span_names._names)):
            raw = v.span_names._names[nid].encode()
            got = lib.zt_intern_name(self.handle, raw, len(raw))
            assert got == nid, (got, nid, raw)
        for kid in range(c_pair + 1, len(v._key_list)):
            s, n = v._key_list[kid]
            # _raw: position-faithful replay — the Python list records
            # the exact id order (including or excluding catch-all rows,
            # per the build that wrote it); the live interning rules
            # must not re-derive insertions here or ids shift
            got = lib.zt_intern_pair_raw(self.handle, s, n)
            assert got == kid, (got, kid, (s, n))
        # drain journals so the replay isn't re-reported as new
        self.sync()

    def sync(self) -> None:
        """Mirror newly interned strings/pairs into the Python vocab."""
        lib = self._lib
        for table, interner in ((0, self.vocab.services), (1, self.vocab.span_names)):
            while True:
                n = lib.zt_vocab_drain_strings(
                    self.handle, table,
                    self._drain_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                    self._drain_buf.nbytes,
                )
                if n <= 0:
                    break
                pos = 0
                raw = self._drain_buf
                for _ in range(n):
                    ln = int.from_bytes(raw[pos : pos + 4], "little")
                    s = bytes(raw[pos + 4 : pos + 4 + ln]).decode("utf-8", "replace")
                    got = interner.intern(s)
                    pos += 4 + ln
                if n < 16384:
                    break
        while True:
            n = lib.zt_vocab_drain_pairs(
                self.handle,
                self._pair_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                len(self._pair_buf),
            )
            if n <= 0:
                break
            for i in range(n):
                v = int(self._pair_buf[i])
                self.vocab.key_id(v >> 32, v & 0xFFFFFFFF)
            if n < len(self._pair_buf):
                break

    def __del__(self):  # pragma: no cover - interpreter teardown
        try:
            if self.handle:
                self._lib.zt_vocab_free(self.handle)
                self.handle = None
        except Exception:
            pass


def parse_spans(
    data: bytes, cap: Optional[int] = None, nvocab: Optional[NativeVocab] = None
) -> Optional[ParsedColumns]:
    """Parse a JSON v2 span array OR a proto3 ``ListOfSpans`` into
    columns; None => use the Python codec (parse error, unsupported
    feature, or no native lib). Format is sniffed the same way the
    object-path codec dispatcher does: '[' selects JSON, a 0x0A first
    byte (ListOfSpans field-1 tag) selects proto3.

    With ``nvocab``, interning happens inside the parse (the ``*_id``
    columns are filled); the caller must hold the store's intern lock and
    call ``nvocab.sync()`` afterwards.
    """
    lib = _load()
    if lib is None:
        return None
    # Route by the SAME structural sniff the object-path dispatcher uses:
    # 0x0A is ambiguous (proto3 field-1 tag AND a newline), and a naive
    # first-byte test misroutes e.g. a ListOfSpans whose first span is
    # 0x5B ('[') bytes long. codec.detect resolves it with a frame walk
    # over the proto3 headers (O(#spans), no payload copy).
    from zipkin_tpu.model import codec as _codec

    try:
        enc = _codec.detect(data)
    except ValueError:
        return None
    if enc is _codec.Encoding.JSON_V2:
        fn_plain, fn_interned = lib.zt_parse_spans, lib.zt_parse_spans_interned
    elif enc is _codec.Encoding.PROTO3:
        fn_plain, fn_interned = (
            lib.zt_parse_proto3, lib.zt_parse_proto3_interned
        )
    else:
        return None
    if cap is None:
        # every span object contributes >= ~20 bytes; this bound never
        # truncates and keeps allocation linear in payload size
        cap = max(len(data) // 20, 16)

    u32 = lambda: np.zeros(cap, np.uint32)
    u8 = lambda: np.zeros(cap, np.uint8)
    out = ParsedColumns()
    out.data = data
    out.tl0, out.tl1, out.th0, out.th1 = u32(), u32(), u32(), u32()
    out.s0, out.s1, out.p0, out.p1 = u32(), u32(), u32(), u32()
    out.shared, out.kind, out.err, out.has_dur = u8(), u8(), u8(), u8()
    out.ts_us = np.zeros(cap, np.uint64)
    out.dur_us = u32()
    out.debug = u8()
    out.svc_off, out.svc_len = u32(), u32()
    out.rsvc_off, out.rsvc_len = u32(), u32()
    out.name_off, out.name_len = u32(), u32()
    out.span_off, out.span_len = u32(), u32()

    p32 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))
    p8 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    p64 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))
    pi32 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))

    common = (
        p32(out.tl0), p32(out.tl1), p32(out.th0), p32(out.th1),
        p32(out.s0), p32(out.s1), p32(out.p0), p32(out.p1),
        p8(out.shared), p8(out.kind), p8(out.err), p8(out.has_dur),
        p64(out.ts_us), p32(out.dur_us), p8(out.debug),
        p32(out.svc_off), p32(out.svc_len),
        p32(out.rsvc_off), p32(out.rsvc_len),
        p32(out.name_off), p32(out.name_len),
        p32(out.span_off), p32(out.span_len),
    )
    if nvocab is not None:
        out.svc_id = np.zeros(cap, np.int32)
        out.rsvc_id = np.zeros(cap, np.int32)
        out.name_id = np.zeros(cap, np.int32)
        out.key_id = np.zeros(cap, np.int32)
        n = fn_interned(
            data, len(data), cap, nvocab.handle, *common,
            pi32(out.svc_id), pi32(out.rsvc_id),
            pi32(out.name_id), pi32(out.key_id),
        )
    else:
        out.svc_id = None
        n = fn_plain(data, len(data), cap, *common)
    if n < 0:
        return None
    out.n = int(n)
    return out
