/* Fast columnar decoder for Zipkin v2 JSON span arrays.
 *
 * The TPU-native analog of the reference's hand-rolled zero-copy codec
 * tier (zipkin2/internal/ReadBuffer.java + V2SpanReader): the generic
 * python json module tops out around 30k spans/s/core, far below the
 * >=125k spans/s/chip ingest target, so the hot path parses straight
 * from the wire bytes into the struct-of-arrays layout the device batch
 * wants - no intermediate objects, strings returned as (offset, length)
 * slices into the input buffer for host-side interning.
 *
 * Scope: exactly the fields the aggregation tier consumes. Unknown keys
 * are skipped structurally (objects/arrays/strings/numbers), so any
 * valid v2 payload parses. On any malformed input the decoder returns a
 * negative error and the caller falls back to the python codec, which
 * produces the authoritative error message.
 *
 * Built with: cc -O2 -fPIC -shared (see build.py); called via ctypes.
 */

#include <stdint.h>
#include <stddef.h>
#include <string.h>

typedef struct {
  /* per-span columns, caller-allocated with capacity `cap` */
  uint32_t *tl0, *tl1;   /* trace id low-64 lanes */
  uint32_t *th0, *th1;   /* trace id high-64 lanes (0 for 64-bit ids) */
  uint32_t *s0, *s1;     /* span id lanes */
  uint32_t *p0, *p1;     /* parent id lanes */
  uint8_t  *shared_flag;
  uint8_t  *kind;        /* 0 none, 1 client, 2 server, 3 producer, 4 consumer */
  uint8_t  *err;         /* tags contain an "error" key */
  uint8_t  *has_dur;
  uint64_t *ts_us;
  uint32_t *dur_us;      /* clamped to u32 */
  uint8_t  *debug_flag;
  /* string slices into the input buffer: offset/length pairs */
  uint32_t *svc_off, *svc_len;
  uint32_t *rsvc_off, *rsvc_len;
  uint32_t *name_off, *name_len;
  /* byte extent of each span's own JSON object in the input: lets the
   * caller re-decode an exact sampled subset at full fidelity (tags,
   * annotations) without re-parsing the whole payload */
  uint32_t *span_off, *span_len;
} columns_t;

typedef struct {
  const uint8_t *buf;
  size_t pos, n;
} cursor_t;

#define ERR_TRUNC  (-1)
#define ERR_SYNTAX (-2)
#define ERR_CAP    (-3)

static void skip_ws(cursor_t *c) {
  while (c->pos < c->n) {
    uint8_t b = c->buf[c->pos];
    if (b == ' ' || b == '\t' || b == '\n' || b == '\r') c->pos++;
    else break;
  }
}

static int skip_string(cursor_t *c) { /* cursor at opening quote */
  if (c->buf[c->pos] != '"') return ERR_SYNTAX;
  c->pos++;
  while (c->pos < c->n) {
    uint8_t b = c->buf[c->pos];
    if (b == '\\') { c->pos += 2; continue; }
    if (b == '"') { c->pos++; return 0; }
    c->pos++;
  }
  return ERR_TRUNC;
}

/* string contents as a raw slice (escapes NOT decoded: service/span names
 * with escapes are rare; the python fallback below handles them) */
static int read_string_slice(cursor_t *c, uint32_t *off, uint32_t *len,
                             int *has_escape) {
  if (c->pos >= c->n || c->buf[c->pos] != '"') return ERR_SYNTAX;
  c->pos++;
  size_t start = c->pos;
  *has_escape = 0;
  while (c->pos < c->n) {
    uint8_t b = c->buf[c->pos];
    if (b == '\\') { *has_escape = 1; c->pos += 2; continue; }
    if (b == '"') {
      *off = (uint32_t)start;
      *len = (uint32_t)(c->pos - start);
      c->pos++;
      return 0;
    }
    c->pos++;
  }
  return ERR_TRUNC;
}

static int skip_value(cursor_t *c);

static int skip_object(cursor_t *c) {
  c->pos++; /* '{' */
  skip_ws(c);
  if (c->pos < c->n && c->buf[c->pos] == '}') { c->pos++; return 0; }
  for (;;) {
    skip_ws(c);
    int rc = skip_string(c); if (rc) return rc;
    skip_ws(c);
    if (c->pos >= c->n || c->buf[c->pos] != ':') return ERR_SYNTAX;
    c->pos++;
    rc = skip_value(c); if (rc) return rc;
    skip_ws(c);
    if (c->pos >= c->n) return ERR_TRUNC;
    if (c->buf[c->pos] == ',') { c->pos++; continue; }
    if (c->buf[c->pos] == '}') { c->pos++; return 0; }
    return ERR_SYNTAX;
  }
}

static int skip_array(cursor_t *c) {
  c->pos++; /* '[' */
  skip_ws(c);
  if (c->pos < c->n && c->buf[c->pos] == ']') { c->pos++; return 0; }
  for (;;) {
    int rc = skip_value(c); if (rc) return rc;
    skip_ws(c);
    if (c->pos >= c->n) return ERR_TRUNC;
    if (c->buf[c->pos] == ',') { c->pos++; continue; }
    if (c->buf[c->pos] == ']') { c->pos++; return 0; }
    return ERR_SYNTAX;
  }
}

static int skip_value(cursor_t *c) {
  skip_ws(c);
  if (c->pos >= c->n) return ERR_TRUNC;
  uint8_t b = c->buf[c->pos];
  if (b == '"') return skip_string(c);
  if (b == '{') return skip_object(c);
  if (b == '[') return skip_array(c);
  /* number / true / false / null */
  while (c->pos < c->n) {
    b = c->buf[c->pos];
    if (b == ',' || b == '}' || b == ']' || b == ' ' || b == '\t' ||
        b == '\n' || b == '\r')
      return 0;
    c->pos++;
  }
  return 0;
}

static int hex_val(uint8_t b) {
  if (b >= '0' && b <= '9') return b - '0';
  if (b >= 'a' && b <= 'f') return b - 'a' + 10;
  if (b >= 'A' && b <= 'F') return b - 'A' + 10;
  return -1;
}

/* parse a quoted hex id of up to 32 chars into hi64/lo64 */
static int read_hex_id(cursor_t *c, uint64_t *hi, uint64_t *lo) {
  uint32_t off, len; int esc;
  int rc = read_string_slice(c, &off, &len, &esc);
  if (rc) return rc;
  if (esc || len == 0 || len > 32) return ERR_SYNTAX;
  uint64_t h = 0, l = 0;
  const uint8_t *p = c->buf + off;
  uint32_t lo_start = len > 16 ? len - 16 : 0;
  for (uint32_t i = 0; i < len; i++) {
    int v = hex_val(p[i]);
    if (v < 0) return ERR_SYNTAX;
    if (i < lo_start) h = (h << 4) | (uint64_t)v;
    else l = (l << 4) | (uint64_t)v;
  }
  *hi = h; *lo = l;
  return 0;
}

static int read_u64(cursor_t *c, uint64_t *out) {
  skip_ws(c);
  uint64_t v = 0;
  int any = 0;
  while (c->pos < c->n) {
    uint8_t b = c->buf[c->pos];
    if (b >= '0' && b <= '9') {
      v = v * 10 + (uint64_t)(b - '0');
      any = 1;
      c->pos++;
    } else if (any && (b == '.' || b == 'e' || b == 'E')) {
      /* fractional timestamps are out of spec; bail to python */
      return ERR_SYNTAX;
    } else break;
  }
  if (!any) return ERR_SYNTAX;
  *out = v;
  return 0;
}

static int key_is(const uint8_t *buf, uint32_t off, uint32_t len,
                  const char *name) {
  size_t n = strlen(name);
  return len == n && memcmp(buf + off, name, n) == 0;
}

/* parse an endpoint object; returns serviceName slice (len 0 if absent) */
static int read_endpoint(cursor_t *c, uint32_t *soff, uint32_t *slen) {
  *soff = 0; *slen = 0;
  skip_ws(c);
  if (c->pos + 4 <= c->n && memcmp(c->buf + c->pos, "null", 4) == 0) {
    c->pos += 4;
    return 0;
  }
  if (c->pos >= c->n || c->buf[c->pos] != '{') return ERR_SYNTAX;
  c->pos++;
  skip_ws(c);
  if (c->pos < c->n && c->buf[c->pos] == '}') { c->pos++; return 0; }
  for (;;) {
    skip_ws(c);
    uint32_t koff, klen; int esc;
    int rc = read_string_slice(c, &koff, &klen, &esc); if (rc) return rc;
    skip_ws(c);
    if (c->pos >= c->n || c->buf[c->pos] != ':') return ERR_SYNTAX;
    c->pos++;
    skip_ws(c);
    if (!esc && key_is(c->buf, koff, klen, "serviceName") &&
        c->pos < c->n && c->buf[c->pos] == '"') {
      int esc2;
      rc = read_string_slice(c, soff, slen, &esc2); if (rc) return rc;
      if (esc2) return ERR_SYNTAX; /* escaped service names: python path */
    } else {
      rc = skip_value(c); if (rc) return rc;
    }
    skip_ws(c);
    if (c->pos >= c->n) return ERR_TRUNC;
    if (c->buf[c->pos] == ',') { c->pos++; continue; }
    if (c->buf[c->pos] == '}') { c->pos++; return 0; }
    return ERR_SYNTAX;
  }
}

/* tags object: only "error"-key presence matters for the columns */
static int read_tags(cursor_t *c, uint8_t *has_error) {
  skip_ws(c);
  if (c->pos >= c->n || c->buf[c->pos] != '{') return ERR_SYNTAX;
  c->pos++;
  skip_ws(c);
  if (c->pos < c->n && c->buf[c->pos] == '}') { c->pos++; return 0; }
  for (;;) {
    skip_ws(c);
    uint32_t koff, klen; int esc;
    int rc = read_string_slice(c, &koff, &klen, &esc); if (rc) return rc;
    if (!esc && key_is(c->buf, koff, klen, "error")) *has_error = 1;
    skip_ws(c);
    if (c->pos >= c->n || c->buf[c->pos] != ':') return ERR_SYNTAX;
    c->pos++;
    rc = skip_value(c); if (rc) return rc;
    skip_ws(c);
    if (c->pos >= c->n) return ERR_TRUNC;
    if (c->buf[c->pos] == ',') { c->pos++; continue; }
    if (c->buf[c->pos] == '}') { c->pos++; return 0; }
    return ERR_SYNTAX;
  }
}

static int read_kind(cursor_t *c, uint8_t *kind) {
  uint32_t off, len; int esc;
  int rc = read_string_slice(c, &off, &len, &esc); if (rc) return rc;
  if (esc) return ERR_SYNTAX;
  if (key_is(c->buf, off, len, "CLIENT")) *kind = 1;
  else if (key_is(c->buf, off, len, "SERVER")) *kind = 2;
  else if (key_is(c->buf, off, len, "PRODUCER")) *kind = 3;
  else if (key_is(c->buf, off, len, "CONSUMER")) *kind = 4;
  else return ERR_SYNTAX; /* unknown kind: python path decides */
  return 0;
}

static int read_bool(cursor_t *c, uint8_t *out) {
  skip_ws(c);
  if (c->pos + 4 <= c->n && memcmp(c->buf + c->pos, "true", 4) == 0) {
    *out = 1; c->pos += 4; return 0;
  }
  if (c->pos + 5 <= c->n && memcmp(c->buf + c->pos, "false", 5) == 0) {
    *out = 0; c->pos += 5; return 0;
  }
  return ERR_SYNTAX;
}

static int parse_span(cursor_t *c, columns_t *cols, long i) {
  skip_ws(c);
  if (c->pos >= c->n || c->buf[c->pos] != '{') return ERR_SYNTAX;
  cols->span_off[i] = (uint32_t)c->pos;
  c->pos++;
  skip_ws(c);
  if (c->pos < c->n && c->buf[c->pos] == '}') return ERR_SYNTAX; /* id req */
  int have_trace = 0, have_id = 0;
  for (;;) {
    skip_ws(c);
    uint32_t koff, klen; int esc;
    int rc = read_string_slice(c, &koff, &klen, &esc); if (rc) return rc;
    skip_ws(c);
    if (c->pos >= c->n || c->buf[c->pos] != ':') return ERR_SYNTAX;
    c->pos++;
    skip_ws(c);
    const uint8_t *b = c->buf;
    if (esc) { rc = skip_value(c); }
    else if (key_is(b, koff, klen, "traceId")) {
      uint64_t hi, lo;
      rc = read_hex_id(c, &hi, &lo);
      cols->th0[i] = (uint32_t)hi; cols->th1[i] = (uint32_t)(hi >> 32);
      cols->tl0[i] = (uint32_t)lo; cols->tl1[i] = (uint32_t)(lo >> 32);
      have_trace = 1;
    } else if (key_is(b, koff, klen, "id")) {
      uint64_t hi, lo;
      rc = read_hex_id(c, &hi, &lo);
      if (!rc && hi) rc = ERR_SYNTAX; /* span id must be 64-bit */
      cols->s0[i] = (uint32_t)lo; cols->s1[i] = (uint32_t)(lo >> 32);
      have_id = 1;
    } else if (key_is(b, koff, klen, "parentId")) {
      if (c->pos + 4 <= c->n && memcmp(b + c->pos, "null", 4) == 0) {
        c->pos += 4; rc = 0;
      } else {
        uint64_t hi, lo;
        rc = read_hex_id(c, &hi, &lo);
        if (!rc && hi) rc = ERR_SYNTAX;
        cols->p0[i] = (uint32_t)lo; cols->p1[i] = (uint32_t)(lo >> 32);
      }
    } else if (key_is(b, koff, klen, "name")) {
      int esc2;
      rc = read_string_slice(c, &cols->name_off[i], &cols->name_len[i], &esc2);
      if (!rc && esc2) rc = ERR_SYNTAX;
    } else if (key_is(b, koff, klen, "kind")) {
      rc = read_kind(c, &cols->kind[i]);
    } else if (key_is(b, koff, klen, "timestamp")) {
      rc = read_u64(c, &cols->ts_us[i]);
    } else if (key_is(b, koff, klen, "duration")) {
      uint64_t d;
      rc = read_u64(c, &d);
      cols->dur_us[i] = d > 0xFFFFFFFFull ? 0xFFFFFFFFu : (uint32_t)d;
      cols->has_dur[i] = 1;
    } else if (key_is(b, koff, klen, "localEndpoint")) {
      rc = read_endpoint(c, &cols->svc_off[i], &cols->svc_len[i]);
    } else if (key_is(b, koff, klen, "remoteEndpoint")) {
      rc = read_endpoint(c, &cols->rsvc_off[i], &cols->rsvc_len[i]);
    } else if (key_is(b, koff, klen, "tags")) {
      rc = read_tags(c, &cols->err[i]);
    } else if (key_is(b, koff, klen, "shared")) {
      rc = read_bool(c, &cols->shared_flag[i]);
    } else if (key_is(b, koff, klen, "debug")) {
      rc = read_bool(c, &cols->debug_flag[i]);
    } else {
      rc = skip_value(c);
    }
    if (rc) return rc;
    skip_ws(c);
    if (c->pos >= c->n) return ERR_TRUNC;
    if (c->buf[c->pos] == ',') { c->pos++; continue; }
    if (c->buf[c->pos] == '}') { c->pos++; break; }
    return ERR_SYNTAX;
  }
  cols->span_len[i] = (uint32_t)(c->pos - cols->span_off[i]);
  return (have_trace && have_id) ? 0 : ERR_SYNTAX;
}

/* entry point: parse a JSON array of spans into the columns.
 * Returns span count >= 0, or a negative error code. */
long zt_parse_spans(const uint8_t *buf, size_t n, long cap,
                    uint32_t *tl0, uint32_t *tl1, uint32_t *th0, uint32_t *th1,
                    uint32_t *s0, uint32_t *s1, uint32_t *p0, uint32_t *p1,
                    uint8_t *shared_flag, uint8_t *kind, uint8_t *err,
                    uint8_t *has_dur, uint64_t *ts_us, uint32_t *dur_us,
                    uint8_t *debug_flag,
                    uint32_t *svc_off, uint32_t *svc_len,
                    uint32_t *rsvc_off, uint32_t *rsvc_len,
                    uint32_t *name_off, uint32_t *name_len,
                    uint32_t *span_off, uint32_t *span_len) {
  columns_t cols = {
    tl0, tl1, th0, th1, s0, s1, p0, p1, shared_flag, kind, err, has_dur,
    ts_us, dur_us, debug_flag, svc_off, svc_len, rsvc_off, rsvc_len,
    name_off, name_len, span_off, span_len,
  };
  cursor_t c = {buf, 0, n};
  skip_ws(&c);
  if (c.pos >= c.n || c.buf[c.pos] != '[') return ERR_SYNTAX;
  c.pos++;
  skip_ws(&c);
  long count = 0;
  if (c.pos < c.n && c.buf[c.pos] == ']') return 0;
  for (;;) {
    if (count >= cap) return ERR_CAP;
    int rc = parse_span(&c, &cols, count);
    if (rc) return rc;
    count++;
    skip_ws(&c);
    if (c.pos >= c.n) return ERR_TRUNC;
    if (c.buf[c.pos] == ',') { c.pos++; continue; }
    if (c.buf[c.pos] == ']') return count;
    return ERR_SYNTAX;
  }
}

/* ---------------- native vocab: interning at parse time ----------------
 *
 * The Python interning loop costs ~2.7us/span - the single largest host
 * cost at line rate - so the parser can intern service names, span names
 * and (service, name) key pairs itself. Ids are assigned sequentially in
 * first-seen order; the Python Vocab mirrors them by draining the
 * insertion journal after each parse (ids must match exactly, which the
 * wrapper asserts).
 *
 * ASCII-lowercase normalization matches the model's .lower() for ASCII;
 * non-ASCII bytes pass through unchanged (documented deviation).
 */

#include <stdlib.h>

typedef struct {
  uint32_t *hash, *off, *len, *id; /* open-addressing slots, 0 id = empty */
  size_t slots;                    /* power of two */
  uint8_t *arena;
  size_t arena_cap, arena_used;
  uint32_t next_id, max_ids;
  uint32_t *journal;               /* arena offsets in insertion order */
  uint32_t *journal_len;
  uint32_t journal_count, drained;
  uint32_t overflow;
} strtab_t;

typedef struct {
  uint64_t *key; uint32_t *id;
  size_t slots;
  uint32_t next_id, max_ids;
  uint64_t *journal;
  uint32_t journal_count, drained;
  uint32_t overflow;
} pairtab_t;

typedef struct {
  strtab_t services, names;
  pairtab_t pairs;
} vocab_t;

static size_t pow2_at_least(size_t n) {
  size_t p = 64;
  while (p < n) p <<= 1;
  return p;
}

static int strtab_init(strtab_t *t, uint32_t max_ids) {
  t->slots = pow2_at_least((size_t)max_ids * 4);
  t->hash = calloc(t->slots, 4);
  t->off = calloc(t->slots, 4);
  t->len = calloc(t->slots, 4);
  t->id = calloc(t->slots, 4);
  t->arena_cap = (size_t)max_ids * 64 + 4096;
  t->arena = malloc(t->arena_cap);
  t->arena_used = 0;
  t->next_id = 1;
  t->max_ids = max_ids;
  t->journal = calloc(max_ids + 1, 4);
  t->journal_len = calloc(max_ids + 1, 4);
  t->journal_count = t->drained = 0;
  t->overflow = 0;
  return (t->hash && t->off && t->len && t->id && t->arena && t->journal &&
          t->journal_len) ? 0 : -1;
}

static uint32_t fnv1a(const uint8_t *s, uint32_t len) {
  uint32_t h = 2166136261u;
  for (uint32_t i = 0; i < len; i++) { h ^= s[i]; h *= 16777619u; }
  return h ? h : 1u;
}

static uint8_t lower_ascii(uint8_t b) {
  return (b >= 'A' && b <= 'Z') ? (uint8_t)(b + 32) : b;
}

/* intern the ASCII-lowercased string; 0 on overflow */
static uint32_t strtab_intern(strtab_t *t, const uint8_t *s, uint32_t len) {
  uint8_t tmp[512];
  if (len == 0) return 0;
  if (len > sizeof(tmp)) { t->overflow++; return 0; }
  for (uint32_t i = 0; i < len; i++) tmp[i] = lower_ascii(s[i]);
  uint32_t h = fnv1a(tmp, len);
  size_t mask = t->slots - 1;
  size_t slot = h & mask;
  for (;;) {
    if (t->id[slot] == 0) break; /* empty */
    if (t->hash[slot] == h && t->len[slot] == len &&
        memcmp(t->arena + t->off[slot], tmp, len) == 0)
      return t->id[slot];
    slot = (slot + 1) & mask;
  }
  if (t->next_id > t->max_ids || t->arena_used + len > t->arena_cap) {
    t->overflow++;
    return 0;
  }
  memcpy(t->arena + t->arena_used, tmp, len);
  t->hash[slot] = h;
  t->off[slot] = (uint32_t)t->arena_used;
  t->len[slot] = len;
  t->id[slot] = t->next_id;
  t->journal[t->journal_count] = (uint32_t)t->arena_used;
  t->journal_len[t->journal_count] = len;
  t->journal_count++;
  t->arena_used += len;
  return t->next_id++;
}

static int pairtab_init(pairtab_t *t, uint32_t max_ids) {
  t->slots = pow2_at_least((size_t)max_ids * 4);
  t->key = calloc(t->slots, 8);
  t->id = calloc(t->slots, 4);
  t->next_id = 1;
  t->max_ids = max_ids;
  t->journal = calloc(max_ids + 1, 8);
  t->journal_count = t->drained = 0;
  t->overflow = 0;
  return (t->key && t->id && t->journal) ? 0 : -1;
}

static uint32_t pairtab_find(const pairtab_t *t, uint32_t a, uint32_t b) {
  uint64_t k = ((uint64_t)a << 32) | b | 0x8000000000000000ull;
  size_t mask = t->slots - 1;
  uint64_t h = k * 0x9E3779B97F4A7C15ull;
  size_t slot = (size_t)(h >> 32) & mask;
  for (;;) {
    if (t->id[slot] == 0) return 0;
    if (t->key[slot] == k) return t->id[slot];
    slot = (slot + 1) & mask;
  }
}

/* raw probe+insert: NO derived insertions, so replay paths can
   reproduce a historical id assignment verbatim whatever interning
   rules the writing build used (position-faithful). count_overflow=0
   for the derived catch-all pre-reserve, so one rejected intern counts
   exactly once — matching the Python interner's accounting. */
static uint32_t pairtab_put(pairtab_t *t, uint32_t a, uint32_t b,
                            int count_overflow) {
  uint64_t k = ((uint64_t)a << 32) | b | 0x8000000000000000ull; /* nonzero */
  size_t mask = t->slots - 1;
  uint64_t h = k * 0x9E3779B97F4A7C15ull;
  size_t slot = (size_t)(h >> 32) & mask;
  for (;;) {
    if (t->id[slot] == 0) break;
    if (t->key[slot] == k) return t->id[slot];
    slot = (slot + 1) & mask;
  }
  if (t->next_id > t->max_ids) {
    if (count_overflow) t->overflow++;
    return 0;
  }
  t->key[slot] = k;
  t->id[slot] = t->next_id;
  t->journal[t->journal_count++] = ((uint64_t)a << 32) | b;
  return t->next_id++;
}

static uint32_t pairtab_intern(pairtab_t *t, uint32_t a, uint32_t b) {
  uint32_t got = pairtab_find(t, a, b);
  if (got) return got;
  /* pre-reserve the per-service catch-all (a, 0) BEFORE the named
     pair — the Python interner does the same, in the same order, so
     the two id streams stay identical. Past capacity, span-name churn
     then aggregates under its SERVICE's catch-all row instead of the
     global unknown row 0 (VERDICT r3 order 5). service 0 is the
     global unknown itself: no catch-all (a shadow (0,0) row would
     hijack unknown-service mass from row 0). */
  if (b != 0 && a != 0) pairtab_put(t, a, 0, 0);
  got = pairtab_put(t, a, b, 1);
  if (got) return got;
  if (b != 0 && a != 0) return pairtab_find(t, a, 0);
  return 0;
}

void *zt_vocab_new(uint32_t max_services, uint32_t max_names,
                   uint32_t max_keys) {
  vocab_t *v = calloc(1, sizeof(vocab_t));
  if (!v) return NULL;
  if (strtab_init(&v->services, max_services) ||
      strtab_init(&v->names, max_names) || pairtab_init(&v->pairs, max_keys)) {
    return NULL;
  }
  return v;
}

void zt_vocab_free(void *vp) {
  vocab_t *v = (vocab_t *)vp;
  if (!v) return;
  free(v->services.hash); free(v->services.off); free(v->services.len);
  free(v->services.id); free(v->services.arena); free(v->services.journal);
  free(v->services.journal_len);
  free(v->names.hash); free(v->names.off); free(v->names.len);
  free(v->names.id); free(v->names.arena); free(v->names.journal);
  free(v->names.journal_len);
  free(v->pairs.key); free(v->pairs.id); free(v->pairs.journal);
  free(v);
}

/* journal draining: returns count of new entries since the last drain;
 * table 0 = services, 1 = names. Strings are copied into out (layout:
 * u32 len + bytes, packed), which must hold out_cap bytes. */
long zt_vocab_drain_strings(void *vp, int table, uint8_t *out,
                            size_t out_cap) {
  vocab_t *v = (vocab_t *)vp;
  strtab_t *t = table == 0 ? &v->services : &v->names;
  size_t pos = 0;
  long produced = 0;
  while (t->drained < t->journal_count) {
    uint32_t off = t->journal[t->drained];
    uint32_t len = t->journal_len[t->drained];
    if (pos + 4 + len > out_cap) break;
    memcpy(out + pos, &len, 4);
    memcpy(out + pos + 4, t->arena + off, len);
    pos += 4 + len;
    t->drained++;
    produced++;
  }
  return produced;
}

long zt_vocab_drain_pairs(void *vp, uint64_t *out, long max) {
  vocab_t *v = (vocab_t *)vp;
  pairtab_t *t = &v->pairs;
  long produced = 0;
  while (t->drained < t->journal_count && produced < max) {
    out[produced++] = t->journal[t->drained++];
  }
  return produced;
}

long zt_vocab_overflow(void *vp) {
  vocab_t *v = (vocab_t *)vp;
  return (long)(v->services.overflow + v->names.overflow + v->pairs.overflow);
}

/* parse + intern in one pass: same as zt_parse_spans plus id columns.
 * vocab may be NULL (ids left zero). */
long zt_parse_spans_interned(
    const uint8_t *buf, size_t n, long cap, void *vocabp,
    uint32_t *tl0, uint32_t *tl1, uint32_t *th0, uint32_t *th1,
    uint32_t *s0, uint32_t *s1, uint32_t *p0, uint32_t *p1,
    uint8_t *shared_flag, uint8_t *kind, uint8_t *err,
    uint8_t *has_dur, uint64_t *ts_us, uint32_t *dur_us, uint8_t *debug_flag,
    uint32_t *svc_off, uint32_t *svc_len,
    uint32_t *rsvc_off, uint32_t *rsvc_len,
    uint32_t *name_off, uint32_t *name_len,
    uint32_t *span_off, uint32_t *span_len,
    int32_t *svc_id, int32_t *rsvc_id, int32_t *name_id, int32_t *key_id) {
  long count = zt_parse_spans(buf, n, cap, tl0, tl1, th0, th1, s0, s1, p0, p1,
                              shared_flag, kind, err, has_dur, ts_us, dur_us,
                              debug_flag, svc_off, svc_len, rsvc_off, rsvc_len,
                              name_off, name_len, span_off, span_len);
  if (count <= 0 || vocabp == NULL) return count;
  vocab_t *v = (vocab_t *)vocabp;
  for (long i = 0; i < count; i++) {
    uint32_t sid = strtab_intern(&v->services, buf + svc_off[i], svc_len[i]);
    uint32_t rid = strtab_intern(&v->services, buf + rsvc_off[i], rsvc_len[i]);
    uint32_t nid = strtab_intern(&v->names, buf + name_off[i], name_len[i]);
    svc_id[i] = (int32_t)sid;
    rsvc_id[i] = (int32_t)rid;
    name_id[i] = (int32_t)nid;
    key_id[i] = (int32_t)pairtab_intern(&v->pairs, sid, nid);
  }
  return count;
}

void zt_vocab_counts(void *vp, uint32_t *services, uint32_t *names,
                     uint32_t *pairs) {
  vocab_t *v = (vocab_t *)vp;
  *services = v->services.next_id - 1;
  *names = v->names.next_id - 1;
  *pairs = v->pairs.next_id - 1;
}

/* direct interning entry points (vocab seeding from the python side) */
long zt_intern_service(void *vp, const uint8_t *s, uint32_t len) {
  return (long)strtab_intern(&((vocab_t *)vp)->services, s, len);
}
long zt_intern_name(void *vp, const uint8_t *s, uint32_t len) {
  return (long)strtab_intern(&((vocab_t *)vp)->names, s, len);
}
long zt_intern_pair(void *vp, uint32_t svc, uint32_t name) {
  return (long)pairtab_intern(&((vocab_t *)vp)->pairs, svc, name);
}
/* position-faithful insert for replay (ensure_synced): records the pair
   at the next id with NO catch-all derivation, so a vocabulary written
   by any build — including pre-catch-all layouts — replays to identical
   ids. */
long zt_intern_pair_raw(void *vp, uint32_t svc, uint32_t name) {
  return (long)pairtab_put(&((vocab_t *)vp)->pairs, svc, name, 1);
}

/* ====================================================================
 * proto3 ListOfSpans parser (VERDICT r4 order 6): the binary analog of
 * the JSON columnar parser above, so gRPC/proto3 ingest rides the same
 * line-rate path. Wire layout per zipkin.proto (mirrored by the
 * reference's hand-rolled Proto3Codec — SURVEY.md §2.1): ListOfSpans =
 * repeated Span field 1; Span fields: 1 trace_id bytes(8|16),
 * 2 parent_id bytes(8), 3 id bytes(8), 4 kind enum, 5 name string,
 * 6 timestamp fixed64, 7 duration varint, 8/9 endpoints (1 service
 * string), 10 annotations, 11 tags entries (1 key, 2 value),
 * 12 debug, 13 shared. Anything structurally surprising returns an
 * error so the caller falls back to the strict object codec.
 * ==================================================================== */

typedef struct { const uint8_t *buf; size_t pos, n; } p3cur_t;

static int p3_varint(p3cur_t *c, uint64_t *out) {
  uint64_t v = 0; int shift = 0;
  while (c->pos < c->n && shift < 64) {
    uint8_t b = c->buf[c->pos++];
    v |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) { *out = v; return 0; }
    shift += 7;
  }
  return ERR_TRUNC;
}

static int p3_skip(p3cur_t *c, int wire) {
  uint64_t tmp;
  switch (wire) {
    case 0: return p3_varint(c, &tmp);
    case 1: if (c->pos + 8 > c->n) return ERR_TRUNC; c->pos += 8; return 0;
    case 2:
      if (p3_varint(c, &tmp)) return ERR_TRUNC;
      if (tmp > c->n - c->pos) return ERR_TRUNC;
      c->pos += (size_t)tmp; return 0;
    case 5: if (c->pos + 4 > c->n) return ERR_TRUNC; c->pos += 4; return 0;
    default: return ERR_SYNTAX; /* groups / reserved: punt to fallback */
  }
}

static uint64_t p3_be64(const uint8_t *p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
  return v;
}

/* extract the serviceName slice (field 1) from an Endpoint submessage */
static int p3_endpoint(const uint8_t *buf, size_t off, size_t len,
                       uint32_t *sv_off, uint32_t *sv_len) {
  p3cur_t c = {buf, off, off + len};
  while (c.pos < c.n) {
    uint64_t tag;
    if (p3_varint(&c, &tag)) return ERR_TRUNC;
    int field = (int)(tag >> 3), wire = (int)(tag & 7);
    if (field == 1 && wire == 2) {
      uint64_t sl;
      if (p3_varint(&c, &sl)) return ERR_TRUNC;
      if (sl > c.n - c.pos) return ERR_TRUNC;
      *sv_off = (uint32_t)c.pos; *sv_len = (uint32_t)sl;
      c.pos += (size_t)sl;
    } else if (p3_skip(&c, wire)) {
      return ERR_SYNTAX;
    }
  }
  return 0;
}

/* tag entry (field 11): key "error" present => err flag */
static int p3_tag_entry(const uint8_t *buf, size_t off, size_t len,
                        uint8_t *err) {
  p3cur_t c = {buf, off, off + len};
  while (c.pos < c.n) {
    uint64_t tag;
    if (p3_varint(&c, &tag)) return ERR_TRUNC;
    int field = (int)(tag >> 3), wire = (int)(tag & 7);
    if (field == 1 && wire == 2) {
      uint64_t sl;
      if (p3_varint(&c, &sl)) return ERR_TRUNC;
      if (sl > c.n - c.pos) return ERR_TRUNC;
      if (sl == 5 && memcmp(buf + c.pos, "error", 5) == 0) *err = 1;
      c.pos += (size_t)sl;
    } else if (p3_skip(&c, wire)) {
      return ERR_SYNTAX;
    }
  }
  return 0;
}

static int p3_span(const uint8_t *buf, size_t off, size_t len,
                   columns_t *cols, long i) {
  p3cur_t c = {buf, off, off + len};
  int have_trace = 0, have_id = 0;
  cols->span_off[i] = (uint32_t)off;
  cols->span_len[i] = (uint32_t)len;
  while (c.pos < c.n) {
    uint64_t tag;
    if (p3_varint(&c, &tag)) return ERR_TRUNC;
    int field = (int)(tag >> 3), wire = (int)(tag & 7);
    uint64_t sl = 0;
    size_t soff = 0;
    if (wire == 2) {
      if (p3_varint(&c, &sl)) return ERR_TRUNC;
      if (sl > c.n - c.pos) return ERR_TRUNC;
      soff = c.pos;
      c.pos += (size_t)sl;
    }
    switch (field) {
      case 1: /* trace_id: 16 (128-bit) or 8 (64-bit) bytes */
        if (wire != 2) return ERR_SYNTAX;
        if (sl == 16) {
          uint64_t hi = p3_be64(buf + soff), lo = p3_be64(buf + soff + 8);
          cols->th0[i] = (uint32_t)hi; cols->th1[i] = (uint32_t)(hi >> 32);
          cols->tl0[i] = (uint32_t)lo; cols->tl1[i] = (uint32_t)(lo >> 32);
        } else if (sl == 8) {
          uint64_t lo = p3_be64(buf + soff);
          cols->th0[i] = 0; cols->th1[i] = 0;
          cols->tl0[i] = (uint32_t)lo; cols->tl1[i] = (uint32_t)(lo >> 32);
        } else {
          return ERR_SYNTAX;
        }
        have_trace = 1;
        break;
      case 2: /* parent_id */
        if (wire != 2 || sl != 8) return ERR_SYNTAX;
        {
          uint64_t lo = p3_be64(buf + soff);
          cols->p0[i] = (uint32_t)lo; cols->p1[i] = (uint32_t)(lo >> 32);
        }
        break;
      case 3: /* id */
        if (wire != 2 || sl != 8) return ERR_SYNTAX;
        {
          uint64_t lo = p3_be64(buf + soff);
          cols->s0[i] = (uint32_t)lo; cols->s1[i] = (uint32_t)(lo >> 32);
        }
        have_id = 1;
        break;
      case 4: { /* kind enum (matches internal KIND ids 0..4) */
        if (wire != 0) return ERR_SYNTAX;
        uint64_t k;
        if (p3_varint(&c, &k)) return ERR_TRUNC;
        cols->kind[i] = k <= 4 ? (uint8_t)k : 0;
        break;
      }
      case 5: /* name */
        if (wire != 2) return ERR_SYNTAX;
        cols->name_off[i] = (uint32_t)soff;
        cols->name_len[i] = (uint32_t)sl;
        break;
      case 6: { /* timestamp fixed64 (LE) */
        if (wire != 1) return ERR_SYNTAX;
        if (c.pos + 8 > c.n) return ERR_TRUNC;
        uint64_t v = 0;
        for (int b = 7; b >= 0; b--) v = (v << 8) | buf[c.pos + b];
        cols->ts_us[i] = v;
        c.pos += 8;
        break;
      }
      case 7: { /* duration varint */
        if (wire != 0) return ERR_SYNTAX;
        uint64_t d;
        if (p3_varint(&c, &d)) return ERR_TRUNC;
        if (d > 0) {
          cols->dur_us[i] = d > 0xFFFFFFFFull ? 0xFFFFFFFFu : (uint32_t)d;
          cols->has_dur[i] = 1;
        }
        break;
      }
      case 8: /* local endpoint */
        if (wire != 2) return ERR_SYNTAX;
        if (p3_endpoint(buf, soff, (size_t)sl,
                        &cols->svc_off[i], &cols->svc_len[i]))
          return ERR_SYNTAX;
        break;
      case 9: /* remote endpoint */
        if (wire != 2) return ERR_SYNTAX;
        if (p3_endpoint(buf, soff, (size_t)sl,
                        &cols->rsvc_off[i], &cols->rsvc_len[i]))
          return ERR_SYNTAX;
        break;
      case 11: /* tag entry: detect "error" */
        if (wire != 2) return ERR_SYNTAX;
        if (p3_tag_entry(buf, soff, (size_t)sl, &cols->err[i]))
          return ERR_SYNTAX;
        break;
      case 12: case 13: { /* debug / shared */
        if (wire != 0) return ERR_SYNTAX;
        uint64_t b;
        if (p3_varint(&c, &b)) return ERR_TRUNC;
        if (field == 12) cols->debug_flag[i] = b ? 1 : 0;
        else cols->shared_flag[i] = b ? 1 : 0;
        break;
      }
      default:
        if (wire != 2 && p3_skip(&c, wire)) return ERR_SYNTAX;
        break; /* wire==2 slices were consumed above */
    }
  }
  return (have_trace && have_id) ? 0 : ERR_SYNTAX;
}

long zt_parse_proto3(const uint8_t *buf, size_t n, long cap,
                     uint32_t *tl0, uint32_t *tl1, uint32_t *th0,
                     uint32_t *th1, uint32_t *s0, uint32_t *s1,
                     uint32_t *p0, uint32_t *p1, uint8_t *shared_flag,
                     uint8_t *kind, uint8_t *err, uint8_t *has_dur,
                     uint64_t *ts_us, uint32_t *dur_us, uint8_t *debug_flag,
                     uint32_t *svc_off, uint32_t *svc_len,
                     uint32_t *rsvc_off, uint32_t *rsvc_len,
                     uint32_t *name_off, uint32_t *name_len,
                     uint32_t *span_off, uint32_t *span_len) {
  columns_t cols = {
    tl0, tl1, th0, th1, s0, s1, p0, p1, shared_flag, kind, err, has_dur,
    ts_us, dur_us, debug_flag, svc_off, svc_len, rsvc_off, rsvc_len,
    name_off, name_len, span_off, span_len,
  };
  p3cur_t c = {buf, 0, n};
  long i = 0;
  while (c.pos < c.n) {
    uint64_t tag;
    if (p3_varint(&c, &tag)) return ERR_TRUNC;
    int field = (int)(tag >> 3), wire = (int)(tag & 7);
    if (field != 1 || wire != 2) return ERR_SYNTAX;
    uint64_t sl;
    if (p3_varint(&c, &sl)) return ERR_TRUNC;
    if (sl > c.n - c.pos) return ERR_TRUNC;
    if (i >= cap) return ERR_CAP;
    int rc = p3_span(buf, c.pos, (size_t)sl, &cols, i);
    if (rc) return rc;
    c.pos += (size_t)sl;
    i++;
  }
  return i;
}

long zt_parse_proto3_interned(
    const uint8_t *buf, size_t n, long cap, void *vocabp,
    uint32_t *tl0, uint32_t *tl1, uint32_t *th0, uint32_t *th1,
    uint32_t *s0, uint32_t *s1, uint32_t *p0, uint32_t *p1,
    uint8_t *shared_flag, uint8_t *kind, uint8_t *err,
    uint8_t *has_dur, uint64_t *ts_us, uint32_t *dur_us, uint8_t *debug_flag,
    uint32_t *svc_off, uint32_t *svc_len,
    uint32_t *rsvc_off, uint32_t *rsvc_len,
    uint32_t *name_off, uint32_t *name_len,
    uint32_t *span_off, uint32_t *span_len,
    int32_t *svc_id, int32_t *rsvc_id, int32_t *name_id, int32_t *key_id) {
  long count = zt_parse_proto3(buf, n, cap, tl0, tl1, th0, th1, s0, s1,
                               p0, p1, shared_flag, kind, err, has_dur,
                               ts_us, dur_us, debug_flag, svc_off, svc_len,
                               rsvc_off, rsvc_len, name_off, name_len,
                               span_off, span_len);
  if (count < 0 || !vocabp) return count;
  vocab_t *v = (vocab_t *)vocabp;
  for (long i = 0; i < count; i++) {
    uint32_t sid = strtab_intern(&v->services, buf + svc_off[i], svc_len[i]);
    uint32_t rid = strtab_intern(&v->services, buf + rsvc_off[i], rsvc_len[i]);
    uint32_t nid = strtab_intern(&v->names, buf + name_off[i], name_len[i]);
    svc_id[i] = (int32_t)sid;
    rsvc_id[i] = (int32_t)rid;
    name_id[i] = (int32_t)nid;
    key_id[i] = (int32_t)pairtab_intern(&v->pairs, sid, nid);
  }
  return count;
}
