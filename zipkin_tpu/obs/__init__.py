"""Pipeline observability: flight recorder + slow-dispatch self-spans.

``RECORDER`` is the process-wide stage recorder; instrumented hot paths
call ``obs.record(stage, dur_s)`` with a stage-name literal from
:mod:`zipkin_tpu.obs.stages` (lint rule ZT08 enforces both the literal
and that no record call hides inside jit'd/device-traced code).
Disable with ``TPU_OBS=0`` — every record becomes one predicate check.

``record_relayed`` is the histogram-only sibling for stage walls
measured elsewhere (worker processes) and relayed to the recording
thread — no budget/self-span path, so relayed time is never B3-linked
to the dispatcher's unrelated request context.

``selfspans``, ``windows``, ``device`` and ``slo`` are imported lazily
by the server (they pull in more machinery); low-level modules
importing ``obs`` pay only for the recorder.
"""

import os

from zipkin_tpu.obs.stages import (  # noqa: F401
    DEFAULT_BUDGETS_US,
    NUM_STAGES,
    STAGE_INDEX,
    STAGES,
)
from zipkin_tpu.obs.recorder import (  # noqa: F401
    NUM_BUCKETS,
    Snapshot,
    StageRecorder,
    StageStat,
    bucket_index,
    bucket_le_us,
)

RECORDER = StageRecorder(
    enabled=os.environ.get("TPU_OBS", "1").strip().lower()
    not in ("0", "false", "no"),
)

record = RECORDER.record
record_relayed = RECORDER.record_relayed
