"""Accuracy observatory: relative-error estimators over the shadow.

The evaluation half of the accuracy plane (see ``obs/shadow.py`` for
the ground-truth half). At rollup cadence — driven by the windowed
telemetry ticker, rate-limited to ``rollup_s`` — the estimator drains
the shadow's pending taps, queries the device plane through the
existing one-transfer read paths (``merged_digest`` / ``cardinalities``
/ ``dependency_edges``, each ONE packed pull through the readpack
chokepoint), and publishes relative-error gauges:

- ``accuracyDigestP50RelErr`` / ``accuracyDigestP99RelErr``: worst
  per-service |digest quantile − reservoir quantile| / reservoir
  quantile. The per-service device quantile is re-derived host-side
  from the pulled [K, C, 2] digest by merging the service's key rows —
  standard t-digest midpoint interpolation, no extra transfer.
- ``accuracyDigestP99Bound``: the STATED confidence bound for the
  worst service — the reservoir evaluated at ``q ± (digest cluster
  width + 3σ reservoir rank noise)``, i.e. distribution-free and
  recomputed per rollup (ops/tdigest.cluster_q_width).
- ``accuracyDigestP50Drift`` / ``accuracyDigestP99Drift``: the ALERT
  gauges — relative error in excess of what reservoir sampling noise
  alone explains (``max(0, relerr - noise_bound)``). The noise bound
  deliberately EXCLUDES the digest's cluster width: an undersized
  digest widens its own stated bound, so excess-over-full-bound could
  never page on it, while excess-over-noise does. Conversely, on
  heavy-tailed streams the sample p99 is noisy even when the digest is
  perfect — raw relerr reads 30%+ there — and the noise bound absorbs
  exactly that, so drift stays at 0 for a healthy digest.
- ``accuracyHllRelErr`` / ``accuracyHllBound`` /
  ``accuracyHllDrift``: global device HLL estimate vs the shadow's
  exact-on-substream distinct estimate; bound = 3·stderr(p) +
  measured bias fraction + substream noise, drift = excess over it.
- ``accuracyLinkRecall``: fraction of edges the host linker oracle
  derives from the shadow's sampled traces that the device's
  compacted dependency matrix also reports.
- ``accuracyRetentionBias``: |shadow verdict keep-rate − live
  sampledKept/(sampledKept+sampledDropped)| — drift between the
  published sampling tables and what retention actually did.

Estimators degrade to NO SIGNAL, never to false alerts: when the
shadow's coverage (spans drained / spans ingested) falls under
``min_coverage`` — lossy taps, or a restore that re-fed the device
with history the shadow never saw — error gauges report 0.0 and
recall 1.0, with ``accuracyShadowCoverage`` telling the operator why.

The gauges merge into ``TpuStorage.ingest_counters()`` and from there
flow everywhere counters flow: ``/metrics``, flat
``zipkin_tpu_accuracy_*`` gauges on ``/prometheus``, the statusz
accuracy section, and the windowed-telemetry counter source — which is
what lets the PR 9 burn-rate watchdog alert on accuracy drift through
the two default gauge ``SloSpec``s (digest_p99_relerr, hll_relerr)
exactly like it alerts on latency.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from zipkin_tpu import obs
from zipkin_tpu.obs.shadow import HostShadow
from zipkin_tpu.ops import hll, ttmerge
from zipkin_tpu.ops.tdigest import cluster_q_width

_FULL_LO_MIN = 0
_FULL_HI_MIN = (1 << 32) - 1


def _digest_quantile(rows: np.ndarray, q: float) -> Tuple[float, float]:
    """(quantile, total weight) of one service's merged centroid rows
    ``[m, C, 2]`` — the same midpoint interpolation ops/tdigest.quantile
    runs on device, host-side over the already-pulled read."""
    means = rows[..., 0].ravel()
    w = rows[..., 1].ravel()
    live = w > 0
    if not live.any():
        return 0.0, 0.0
    m_, w_ = means[live], w[live]
    order = np.argsort(m_, kind="stable")
    m_, w_ = m_[order], w_[order]
    cum = np.cumsum(w_) - 0.5 * w_
    total = float(w_.sum())
    return float(np.interp(q * total, cum, m_)), total


class AccuracyEstimator:
    """Rollup-cadence accuracy evaluation for one storage instance."""

    QS = (0.5, 0.99)

    def __init__(
        self,
        storage,
        shadow: HostShadow,
        *,
        rollup_s: float = 5.0,
        min_count: int = 64,
        min_coverage: float = 0.9,
        clock=time.monotonic,
    ) -> None:
        self._store = storage
        self._shadow = shadow
        self.rollup_s = float(rollup_s)
        self.min_count = int(min_count)
        self.min_coverage = float(min_coverage)
        self._clock = clock
        self._last = float("-inf")
        self._lock = threading.Lock()
        self._roll_lock = threading.Lock()
        self.rollups = 0
        self._detail: Dict = {"services": [], "suppressed": False}
        self._gauges: Dict[str, float] = {
            "accuracyDigestP50RelErr": 0.0,
            "accuracyDigestP99RelErr": 0.0,
            "accuracyDigestP99Bound": 0.0,
            "accuracyDigestP50Drift": 0.0,
            "accuracyDigestP99Drift": 0.0,
            "accuracyHllRelErr": 0.0,
            "accuracyHllBound": 0.0,
            "accuracyHllDrift": 0.0,
            "accuracyWindowedDigestP99RelErr": 0.0,
            "accuracyWindowedDigestP99Drift": 0.0,
            "accuracyWindowedHllRelErr": 0.0,
            "accuracyWindowedHllDrift": 0.0,
            "accuracyLinkRecall": 1.0,
            "accuracyRetentionBias": 0.0,
            "accuracyShadowCoverage": 1.0,
            "accuracyRollups": 0,
            "accuracyRollupMs": 0.0,
        }

    # -- scheduling ----------------------------------------------------

    def maybe_rollup(self, now: Optional[float] = None) -> bool:
        """Rate-limited rollup; safe to call from the ticker thread and
        read handlers concurrently (overlapping calls no-op)."""
        now = self._clock() if now is None else now
        if now - self._last < self.rollup_s:
            return False
        if not self._roll_lock.acquire(blocking=False):
            return False
        try:
            self._last = now
            self.rollup()
            return True
        finally:
            self._roll_lock.release()

    # -- evaluation ----------------------------------------------------

    def rollup(self) -> Dict[str, float]:
        """Drain the shadow, read the device plane, publish gauges."""
        t0 = time.perf_counter()
        shadow = self._shadow
        store = self._store
        shadow.drain()

        spans_total = int(store.agg.host_counters.get("spans", 0))
        coverage = (
            min(1.0, shadow.total_seen / spans_total)
            if spans_total > 0 else 1.0
        )
        suppressed = coverage < self.min_coverage

        services: List[Dict] = []
        p50_err = p99_err = p99_bound = 0.0
        p50_drift = p99_drift = 0.0
        hll_err = hll_bound = 0.0
        recall = 1.0
        ret_bias = 0.0
        w_digest_err = w_digest_drift = 0.0
        w_hll_err = w_hll_drift = 0.0
        links_detail: Dict = {}
        distinct_detail: Dict = {}
        windowed_detail: Dict = {}

        if not suppressed:
            (services, p50_err, p99_err, p99_bound,
             p50_drift, p99_drift) = self._digest_errors()
            hll_err, hll_bound, distinct_detail = self._hll_error()
            (w_digest_err, w_digest_drift, w_hll_err, w_hll_drift,
             windowed_detail) = self._windowed_errors()
            recall, links_detail = self._link_recall()
            ret_bias = self._retention_bias()

        self.rollups += 1
        roll_ms = (time.perf_counter() - t0) * 1000.0
        obs.record("accuracy_rollup", time.perf_counter() - t0)
        gauges = {
            "accuracyDigestP50RelErr": p50_err,
            "accuracyDigestP99RelErr": p99_err,
            "accuracyDigestP99Bound": p99_bound,
            "accuracyDigestP50Drift": p50_drift,
            "accuracyDigestP99Drift": p99_drift,
            "accuracyHllRelErr": hll_err,
            "accuracyHllBound": hll_bound,
            "accuracyHllDrift": max(0.0, hll_err - hll_bound),
            "accuracyWindowedDigestP99RelErr": w_digest_err,
            "accuracyWindowedDigestP99Drift": w_digest_drift,
            "accuracyWindowedHllRelErr": w_hll_err,
            "accuracyWindowedHllDrift": w_hll_drift,
            "accuracyLinkRecall": recall,
            "accuracyRetentionBias": ret_bias,
            "accuracyShadowCoverage": coverage,
            "accuracyRollups": self.rollups,
            "accuracyRollupMs": roll_ms,
        }
        with self._lock:
            self._gauges = gauges
            self._detail = {
                "services": services,
                "links": links_detail,
                "distinct": distinct_detail,
                "windowed": windowed_detail,
                "suppressed": suppressed,
            }
        return gauges

    def _digest_errors(
        self,
    ) -> Tuple[List[Dict], float, float, float, float, float]:
        """Per-service digest-vs-reservoir relative errors; worst-case
        aggregates for the gauges. One device transfer when any service
        is eligible, zero at rest."""
        shadow = self._shadow
        store = self._store
        eligible = [
            s for s in shadow.services()
            if (res := shadow.reservoir(s)) is not None
            and res.seen >= self.min_count
        ]
        if not eligible:
            return [], 0.0, 0.0, 0.0, 0.0, 0.0
        digest = np.asarray(store.agg.merged_digest())  # [K, C, 2]
        c = digest.shape[1]
        with store.vocab._lock:
            pairs = np.asarray(store.vocab._key_list, np.int64)
        rows: List[Dict] = []
        p50_err = p99_err = p99_bound = 0.0
        p50_drift = p99_drift = 0.0
        for svc in eligible:
            kids = np.nonzero(pairs[:, 0] == svc)[0]
            kids = kids[kids >= 1]
            if not len(kids):
                continue
            res = shadow.reservoir(svc)
            vals = res.values()
            k = len(vals)
            errs = {}
            bounds = {}
            drifts = {}
            skip = False
            for q in self.QS:
                dev_q, total = _digest_quantile(digest[kids], q)
                if total < self.min_count:
                    skip = True
                    break
                sq = float(np.quantile(vals, q))
                errs[q] = abs(dev_q - sq) / max(sq, 1.0)
                # stated bound: reservoir evaluated at q widened by the
                # digest's own rank resolution plus 3σ of reservoir
                # rank noise — both in rank space, converted to a value
                # bound by the exact sample itself
                noise = 3.0 * math.sqrt(max(q * (1.0 - q), 0.0) / k)
                half = cluster_q_width(c, q) + noise
                vlo, vhi = np.quantile(
                    vals, [max(0.0, q - half), min(1.0, q + half)]
                )
                bounds[q] = (
                    max(float(vhi) - sq, sq - float(vlo)) / max(sq, 1.0)
                    + 0.005
                )
                # drift = error the SAMPLING noise can't explain. The
                # digest's cluster width is excluded on purpose: an
                # undersized digest must not widen the bound it is
                # judged against (it would never page), while a noisy
                # sample p99 on a heavy-tailed stream must not page a
                # digest that is actually fine.
                nlo, nhi = np.quantile(
                    vals, [max(0.0, q - noise), min(1.0, q + noise)]
                )
                noise_bound = (
                    max(float(nhi) - sq, sq - float(nlo)) / max(sq, 1.0)
                    + 0.005
                )
                drifts[q] = max(0.0, errs[q] - noise_bound)
            if skip:
                continue
            name = store.vocab.services.lookup(int(svc)) or str(svc)
            rows.append({
                "service": name,
                "reservoirSeen": res.seen,
                "p50RelErr": round(errs[0.5], 6),
                "p99RelErr": round(errs[0.99], 6),
                "p99Bound": round(bounds[0.99], 6),
                "p99Drift": round(drifts[0.99], 6),
            })
            p50_err = max(p50_err, errs[0.5])
            p50_drift = max(p50_drift, drifts[0.5])
            p99_drift = max(p99_drift, drifts[0.99])
            if errs[0.99] >= p99_err:
                p99_err = errs[0.99]
                p99_bound = bounds[0.99]
        return rows, p50_err, p99_err, p99_bound, p50_drift, p99_drift

    def _hll_error(self) -> Tuple[float, float, Dict]:
        shadow = self._shadow
        store = self._store
        kept = shadow.counters()["shadowDistinctKept"]
        if kept < self.min_count:
            return 0.0, 0.0, {}
        est = np.asarray(store.agg.cardinalities())  # [S+1], last global
        dev = float(est[store.config.global_hll_row])
        sh = shadow.distinct_estimate()
        err = abs(dev - sh) / max(sh, 1.0)
        bound = (
            3.0 * hll.standard_error(store.config.hll_precision)
            + hll.bias_fraction(max(dev, 1.0))
            + shadow.distinct_bound()
        )
        return err, bound, {
            "device": dev,
            "shadow": sh,
            "kept": int(kept),
        }

    def _windowed_errors(self) -> Tuple[float, float, float, float, Dict]:
        """Windowed accuracy (ISSUE 15): audit the time tier's newest
        SEALED bucket for which the windowed shadow holds exact
        sub-streams — the tier's per-bucket digest p99 vs the bucket's
        exact reservoir, and the bucket's HLL estimate vs the bucket's
        KMV sketch. Same estimator shapes (and the same drift-over-
        noise alert semantics) as the cumulative pair, so the default
        windowed SloSpecs page on real sketch drift, not sampling
        noise. Sealed-only by construction: a sealed segment never
        changes, so this read takes no aggregator lock."""
        shadow = self._shadow
        store = self._store
        tier = getattr(store, "timetier", None)
        if tier is None or shadow.bucket_minutes <= 0:
            return 0.0, 0.0, 0.0, 0.0, {}
        eps = [
            e for e in shadow.window_epochs() if e <= tier.sealed_through
        ]
        if not eps:
            return 0.0, 0.0, 0.0, 0.0, {}
        epoch = eps[-1]
        ans = tier.window(store.agg, epoch, epoch)
        d_err = d_drift = h_err = h_drift = 0.0
        detail: Dict = {"epoch": int(epoch)}
        res = shadow.window_reservoir(epoch)
        if res is not None and res.seen >= self.min_count:
            vals = res.values()
            k = len(vals)
            q = 0.99
            dev_q, total = _digest_quantile(np.asarray(ans.digest)[1:], q)
            if total >= self.min_count:
                sq = float(np.quantile(vals, q))
                d_err = abs(dev_q - sq) / max(sq, 1.0)
                noise = 3.0 * math.sqrt(q * (1.0 - q) / k)
                nlo, nhi = np.quantile(
                    vals, [max(0.0, q - noise), min(1.0, q + noise)]
                )
                noise_bound = (
                    max(float(nhi) - sq, sq - float(nlo)) / max(sq, 1.0)
                    + 0.005
                )
                d_drift = max(0.0, d_err - noise_bound)
                detail["digest"] = {
                    "device": dev_q, "shadow": sq, "reservoirSeen": res.seen,
                }
        sk = shadow.window_distinct(epoch)
        if sk is not None and len(sk.ids) >= self.min_count:
            dev = float(
                ttmerge.hll_estimate(np.asarray(ans.hll))[
                    store.config.global_hll_row
                ]
            )
            sh = sk.estimate()
            h_err = abs(dev - sh) / max(sh, 1.0)
            bound = (
                3.0 * hll.standard_error(store.config.hll_precision)
                + hll.bias_fraction(max(dev, 1.0))
                + sk.rel_bound()
            )
            h_drift = max(0.0, h_err - bound)
            detail["distinct"] = {"device": dev, "shadow": sh}
        return d_err, d_drift, h_err, h_drift, detail

    def _link_recall(self) -> Tuple[float, Dict]:
        """Replay the shadow's sampled traces through the host linker
        oracle and check every derived edge against the device's
        compacted dependency read (full window, one transfer)."""
        shadow_edges = self._shadow_edges()
        if not shadow_edges:
            return 1.0, {}
        store = self._store
        s = store.config.max_services
        idx, calls, _errors = store.agg.dependency_edges(
            _FULL_LO_MIN, _FULL_HI_MIN
        )
        live = calls > 0
        dev_edges: Set[Tuple[int, int]] = {
            (int(f) // s, int(f) % s) for f in idx[live]
        }
        hit = len(shadow_edges & dev_edges)
        return hit / len(shadow_edges), {
            "shadowEdges": len(shadow_edges),
            "deviceEdges": len(dev_edges),
            "matched": hit,
        }

    def _shadow_edges(self) -> Set[Tuple[int, int]]:
        from zipkin_tpu.internal.dependency_linker import DependencyLinker
        from zipkin_tpu.model.span import Endpoint, Span
        from zipkin_tpu.tpu.columnar import ID_TO_KIND

        traces = self._shadow.link_traces()
        if not traces:
            return set()
        vocab = self._store.vocab
        linker = DependencyLinker()
        for tid, recs in traces.items():
            spans = []
            for (s0, s1, p0, p1, shared, kind, svc, rsvc, err) in recs:
                local = vocab.services.lookup(int(svc))
                if not local:
                    continue
                remote = vocab.services.lookup(int(rsvc)) if rsvc else None
                sid = (s1 << 32) | s0
                pid = (p1 << 32) | p0
                spans.append(Span(
                    trace_id=f"{tid:016x}",
                    id=f"{sid:016x}",
                    parent_id=f"{pid:016x}" if pid else None,
                    kind=ID_TO_KIND.get(kind),
                    local_endpoint=Endpoint(service_name=local),
                    remote_endpoint=(
                        Endpoint(service_name=remote) if remote else None
                    ),
                    tags={"error": "true"} if err else {},
                    shared=bool(shared),
                ))
            if spans:
                linker.put_trace(spans)
        edges: Set[Tuple[int, int]] = set()
        for link in linker.link():
            p = vocab.services.get(link.parent)
            child = vocab.services.get(link.child)
            if p and child:
                edges.add((int(p), int(child)))
        return edges

    def _retention_bias(self) -> float:
        seen, kept = self._shadow.retention()
        if seen < self.min_count:
            return 0.0
        counters = self._store.agg.host_counters
        live_kept = int(counters.get("sampledKept", 0))
        live_dropped = int(counters.get("sampledDropped", 0))
        live_total = live_kept + live_dropped
        if live_total <= 0:
            return 0.0
        return abs(kept / seen - live_kept / live_total)

    # -- export --------------------------------------------------------

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def export_counters(self) -> Dict[str, float]:
        """Flat numeric dict for the ingest_counters merge: the accuracy
        gauges plus the shadow's own occupancy counters."""
        out = self.gauges()
        out.update(self._shadow.counters())
        return out

    def status(self) -> Dict:
        """Full dict for the ``/statusz`` accuracy section."""
        with self._lock:
            detail = dict(self._detail)
            gauges = dict(self._gauges)
        return {
            "gauges": gauges,
            "rollupS": self.rollup_s,
            "minCount": self.min_count,
            "minCoverage": self.min_coverage,
            "shadow": self._shadow.counters(),
            **detail,
        }
