"""Ingest critical-path tracer: cross-process wire-to-durable timelines.

The fan-out tier (tpu/mp_ingest.py) splits one ingest request across
three clock domains — the server boundary thread, a spawn parse worker,
and the dispatcher thread — so the per-stage recorder totals cannot say
*where* a slow chunk spent its time: queue-wait and service are folded
together, and ``mp_record`` hides four very different substages. This
module is the instrument that separates them:

- A **chunk-scoped trace context** is assigned at the server boundary
  (``WIRE_T0_NS`` contextvar, stamped before the body leaves the event
  loop) and threaded through ``submit()`` into the worker queue item.
- A **fixed-slot shared-memory interval ledger** holds one slot per
  in-flight traced payload. Each slot has two independently
  generation-stamped regions — one written only by the owning worker
  process, one written only by main-process threads (boundary stamps
  happen-before the queue put; dispatcher stamps happen-after the
  worker's result message, so main-side writers are causally serialized)
  — the same single-writer seqlock idiom as ``obs/recorder.py``, over
  raw int64 words so nothing pickles on the dispatch-critical path.
- **Clock-domain alignment**: every process publishes a seqlocked
  ``(perf_counter_ns, time_ns)`` calibration pair; worker timestamps map
  into the main monotonic domain via the wall-clock bridge
  ``t_main = t_worker + (wall_w - mono_w) - (wall_m - mono_m)``.
- A **stitcher** folds DONE slots at windows-tick cadence into exact
  wire-to-durable percentiles (relayed into the ``wire_to_durable``
  recorder stage so the windowed/SLO planes see it), a per-segment
  queue-wait vs service decomposition with Little's-law occupancy and
  saturation gauges, and per-chunk timelines whose segments must sum to
  the measured wall within a conservation bound — the bound is what
  absorbs residual cross-domain clock noise. The slowest timeline per
  stitch is emitted as a self-span tree through the SelfSpanEmitter, so
  a slow chunk is a retrievable trace in the server's own UI.

Orphan safety: a SIGKILL'd worker leaves its slots OPEN forever; the
stitcher reclaims OPEN slots older than ``reclaim_age_s`` and the
dispatcher's fallback path abandons slots explicitly, so timelines can
skew but never stick. Late stamps against a reclaimed-and-reused slot
are rejected by the payload-id guard.

This module is imported by spawn workers: keep it free of jax and of
anything heavier than numpy.
"""

from __future__ import annotations

import contextvars
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

# Set at the server boundary (HTTP body read / gRPC request
# deserialization) in the main monotonic domain; read by
# MultiProcessIngester.submit() on the same context (contextvars
# propagate through asyncio.to_thread). 0 = no boundary stamp.
WIRE_T0_NS: contextvars.ContextVar[int] = contextvars.ContextVar(
    "zipkin_tpu_wire_t0_ns", default=0
)

# -- segment taxonomy ----------------------------------------------------
# Stamped segments carry measured intervals; derived segments are the
# gaps between them, classified by pipeline phase. ``kind`` drives the
# queue-wait vs service rollup.

SEG_BOUNDARY = 0        # derived: wire receipt -> submit registration
SEG_ENQUEUE = 1         # stamped (boundary thread): registration + queue put
SEG_QUEUE_WAIT = 2      # derived: queue put -> worker first touch
SEG_PARSE = 3           # stamped (worker): native parse + intern + sample
SEG_SLOT_WAIT = 4       # stamped (worker): waiting on a free shm slot
SEG_PACK = 5            # stamped (worker): columnar pack
SEG_ROUTE = 6           # stamped (worker): shard routing
SEG_WORKER_OTHER = 7    # derived: unstamped time inside the worker phase
SEG_HANDOFF_WAIT = 8    # derived: worker done -> dispatcher first touch
SEG_SHM_COPY = 9        # stamped (dispatcher): shm slot -> private copy
SEG_VOCAB_REPLAY = 10   # stamped (dispatcher): vocab journal replay
SEG_LUT_REMAP = 11      # stamped (dispatcher): local->global LUT remap
SEG_DEVICE_FEED = 12    # stamped (dispatcher): ingest_fused dispatch wall
SEG_WAL_APPEND = 13     # stamped (dispatcher, via wal.py): append sans fsync
SEG_WAL_FSYNC = 14      # stamped (dispatcher, via wal.py): the fsync
SEG_DISPATCH_OTHER = 15  # derived: unstamped time inside dispatcher phase
SEG_ACK = 16            # derived: last stamped interval -> ack bookkeeping
SEG_RING_WAIT = 17      # stamped (worker): waiting on a free span-ring slot
SEG_COALESCE = 18       # stamped (dispatcher): multi-chunk concat+remap gather
N_SEGS = 19

SEG_NAMES = (
    "boundary", "enqueue", "queue_wait", "parse", "slot_wait", "pack",
    "route", "worker_other", "handoff_wait", "shm_copy", "vocab_replay",
    "lut_remap", "device_feed", "wal_append", "wal_fsync",
    "dispatch_other", "ack", "ring_wait", "coalesce",
)
_WAIT = frozenset((SEG_QUEUE_WAIT, SEG_SLOT_WAIT, SEG_WORKER_OTHER,
                   SEG_HANDOFF_WAIT, SEG_DISPATCH_OTHER, SEG_RING_WAIT))
SEG_KIND = tuple("wait" if i in _WAIT else "service" for i in range(N_SEGS))
_WORKER_SEGS = frozenset((SEG_PARSE, SEG_SLOT_WAIT, SEG_PACK, SEG_ROUTE,
                          SEG_RING_WAIT))

# -- shared-memory layout (int64 words) ----------------------------------
# header | calibration rows (main + one per worker) | slots
#
# slot: [state gen_d pid widx wire_t0 ack_t open_t flags n_d
#        d_intervals(3*MAX_D) gen_w n_w w_intervals(3*MAX_W) tenant]
# The main-side region (gen_d guards pid..d_intervals) and the worker
# region (gen_w guards n_w..w_intervals) have disjoint writers, so each
# keeps the single-writer seqlock invariant even while a worker packs
# the payload the dispatcher has not yet seen.

MAX_W_IV = 25   # 1 parse + 3 per chunk: covers 8 packed chunks
MAX_D_IV = 28   # enqueue + 3 per chunk + feed/wal stamps per flush

_ST_FREE, _ST_OPEN, _ST_DONE = 0, 1, 2

_OFF_STATE = 0
_OFF_GEN_D = 1
_OFF_PID = 2
_OFF_WIDX = 3
_OFF_WIRE_T0 = 4
_OFF_ACK_T = 5
_OFF_OPEN_T = 6
_OFF_FLAGS = 7
_OFF_N_D = 8
_OFF_D_IV = 9
_OFF_GEN_W = _OFF_D_IV + 3 * MAX_D_IV
_OFF_N_W = _OFF_GEN_W + 1
_OFF_W_IV = _OFF_N_W + 1
# tenant intern idx (ISSUE 18): written once at alloc while the slot is
# still FREE (invisible), so it needs no gen bracket of its own
_OFF_TENANT = _OFF_W_IV + 3 * MAX_W_IV
SLOT_WORDS = _OFF_TENANT + 1

_HDR_WORDS = 8
_CAL_WORDS = 4          # [gen, perf_counter_ns, time_ns, pad]
_MAGIC = 0x43504C44     # 'CPLD'

_FLAG_TRUNC_D = 1       # dispatcher region ran out of interval capacity
_FLAG_DEGRADED = 2      # timeline known-incomplete (fallback path touched it)

_TORN_RETRIES = 1000


def _now_ns() -> int:
    return time.perf_counter_ns()


class CritPathLedger:
    """Fixed-slot shm interval ledger. Create in the main process before
    the worker pool spawns; workers attach via :class:`CritPathWorkerView`
    with ``params()``. Slot lifecycle: FREE -> OPEN (``alloc``, boundary
    thread) -> DONE (``ack``, dispatcher) -> FREE (stitcher fold), or
    OPEN -> FREE (``abandon``: fallback/reclaim)."""

    def __init__(self, n_workers: int, slots: int = 256, *,
                 name: Optional[str] = None) -> None:
        from multiprocessing import shared_memory

        self.n_workers = int(n_workers)
        self.slots = int(slots)
        self._base = _HDR_WORDS + _CAL_WORDS * (self.n_workers + 1)
        words = self._base + self.slots * SLOT_WORDS
        if name is None:
            self._shm = shared_memory.SharedMemory(create=True, size=words * 8)
            self._owner = True
        else:  # attach (tests exercising cross-process views)
            self._shm = shared_memory.SharedMemory(name=name)
            self._owner = False
        self._a = np.frombuffer(self._shm.buf, np.int64, count=words)
        if self._owner:
            self._a[:] = 0
            self._a[0] = _MAGIC
            self._a[1] = self.slots
            self._a[2] = self.n_workers + 1
            self.calibrate()
        self._lock = threading.Lock()
        self._free: List[int] = list(range(self.slots - 1, -1, -1))
        self.alloc_failed = 0
        self.abandoned = 0
        self._closed = False

    def params(self) -> dict:
        """Spawn-safe attach info for :class:`CritPathWorkerView`."""
        return {"name": self._shm.name, "slots": self.slots,
                "n_workers": self.n_workers}

    # -- clock calibration ------------------------------------------------

    def calibrate(self) -> None:
        """Publish the main process's (mono, wall) pair (seqlocked)."""
        _write_cal(self._a, _HDR_WORDS)

    def _cal(self, row: int):
        return _read_cal(self._a, _HDR_WORDS + _CAL_WORDS * row)

    def worker_offset_ns(self, widx: int) -> int:
        """Additive correction mapping worker ``widx`` perf_counter_ns
        stamps into the main process's monotonic domain."""
        mono_m, wall_m = self._cal(0)
        mono_w, wall_w = self._cal(1 + widx)
        if mono_w == 0:  # worker never calibrated: assume shared clock
            return 0
        return (wall_w - mono_w) - (wall_m - mono_m)

    # -- slot lifecycle (main process only) -------------------------------

    def alloc(self, pid: int, widx: int, wire_t0_ns: int, tenant: int = 0) -> int:  # zt-lint: disable=ZT11 — the slot is FREE (invisible to readers) until the trailing _OFF_STATE=_ST_OPEN store publishes it; interval counts are RESET here, not mutated under readers, so no gen bracket applies
        """Claim a slot for payload ``pid`` routed to worker ``widx``.
        Returns -1 (trace skipped, counted) when the ledger is full."""
        with self._lock:
            if not self._free:
                self.alloc_failed += 1
                return -1
            s = self._free.pop()
        a, b = self._a, self._base + s * SLOT_WORDS
        a[b + _OFF_GEN_D] = 0
        a[b + _OFF_GEN_W] = 0
        a[b + _OFF_N_D] = 0
        a[b + _OFF_N_W] = 0
        a[b + _OFF_PID] = pid
        a[b + _OFF_WIDX] = widx
        a[b + _OFF_WIRE_T0] = wire_t0_ns
        a[b + _OFF_TENANT] = tenant
        a[b + _OFF_ACK_T] = 0
        a[b + _OFF_FLAGS] = 0
        a[b + _OFF_OPEN_T] = _now_ns()
        a[b + _OFF_STATE] = _ST_OPEN
        return s

    def stamp(self, slot: int, code: int, t0_ns: int, t1_ns: int, pid: int = -1) -> None:  # zt-dispatch-critical: appends one interval on the dispatcher/boundary hot path; seqlock bump + 3 word stores, no allocation
        if slot < 0 or self._closed:
            return
        a, b = self._a, self._base + slot * SLOT_WORDS
        if a[b + _OFF_STATE] != _ST_OPEN:
            return  # slot reclaimed out from under a straggler
        if pid >= 0 and a[b + _OFF_PID] != pid:
            return  # reclaimed AND reallocated: don't pollute the new owner
        n = int(a[b + _OFF_N_D])
        if n >= MAX_D_IV:
            a[b + _OFF_FLAGS] |= _FLAG_TRUNC_D
            return
        a[b + _OFF_GEN_D] += 1
        iv = b + _OFF_D_IV + 3 * n
        a[iv] = code
        a[iv + 1] = t0_ns
        a[iv + 2] = t1_ns
        a[b + _OFF_N_D] = n + 1
        a[b + _OFF_GEN_D] += 1

    def ack(self, slot: int, pid: int = -1, t_ns: int = 0) -> None:  # zt-dispatch-critical: final durable-ack stamp; two word stores
        if slot < 0 or self._closed:
            return
        a, b = self._a, self._base + slot * SLOT_WORDS
        if a[b + _OFF_STATE] != _ST_OPEN:
            return
        if pid >= 0 and a[b + _OFF_PID] != pid:
            return
        a[b + _OFF_ACK_T] = t_ns or _now_ns()
        a[b + _OFF_STATE] = _ST_DONE

    def flag_degraded(self, slot: int) -> None:
        if slot < 0 or self._closed:
            return
        b = self._base + slot * SLOT_WORDS
        with self._lock:
            self._a[b + _OFF_FLAGS] |= _FLAG_DEGRADED

    def abandon(self, slot: int) -> None:
        """Free an OPEN slot whose timeline will never complete."""
        if slot < 0 or self._closed:
            return
        b = self._base + slot * SLOT_WORDS
        with self._lock:
            if self._a[b + _OFF_STATE] != _ST_FREE:
                self._a[b + _OFF_STATE] = _ST_FREE
                self._free.append(slot)
                self.abandoned += 1

    def release(self, slot: int) -> None:
        """Return a folded DONE slot to the free list (stitcher only)."""
        b = self._base + slot * SLOT_WORDS
        with self._lock:
            if self._a[b + _OFF_STATE] == _ST_DONE:
                self._a[b + _OFF_STATE] = _ST_FREE
                self._free.append(slot)

    # -- reader side ------------------------------------------------------

    def state(self, slot: int) -> int:
        return int(self._a[self._base + slot * SLOT_WORDS + _OFF_STATE])

    def open_age_ns(self, slot: int, now_ns: int) -> int:
        b = self._base + slot * SLOT_WORDS
        return now_ns - int(self._a[b + _OFF_OPEN_T])

    def read_slot(self, slot: int) -> Optional[np.ndarray]:
        """Generation-consistent copy of one slot (both regions), or
        None if a writer kept it torn for the whole retry budget."""
        a, b = self._a, self._base + slot * SLOT_WORDS
        for _ in range(_TORN_RETRIES):
            gd = int(a[b + _OFF_GEN_D])
            gw = int(a[b + _OFF_GEN_W])
            if gd % 2 or gw % 2:
                continue
            blk = a[b:b + SLOT_WORDS].copy()
            if (int(a[b + _OFF_GEN_D]) == gd
                    and int(a[b + _OFF_GEN_W]) == gw):
                return blk
        return None

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        with self._lock:
            self._a = None
        self._shm.close()
        if self._owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:  # pragma: no cover
                pass


def _write_cal(a: np.ndarray, off: int) -> None:
    a[off] += 1
    a[off + 1] = time.perf_counter_ns()
    a[off + 2] = time.time_ns()
    a[off] += 1


def _read_cal(a: np.ndarray, off: int):
    mono = wall = 0
    for _ in range(_TORN_RETRIES):
        g = int(a[off])
        mono, wall = int(a[off + 1]), int(a[off + 2])
        if g % 2 == 0 and int(a[off]) == g:
            break
    return mono, wall


class CritPathWorkerView:
    """The worker-process half of the ledger: calibration + worker-region
    stamps for slots handed to this worker. Single writer per region —
    a payload is owned by exactly one worker."""

    def __init__(self, params: dict, widx: int) -> None:
        from multiprocessing import shared_memory

        self.widx = int(widx)
        self._shm = shared_memory.SharedMemory(name=params["name"])
        base = _HDR_WORDS + _CAL_WORDS * (params["n_workers"] + 1)
        words = base + params["slots"] * SLOT_WORDS
        self._a = np.frombuffer(self._shm.buf, np.int64, count=words)
        self._base = base
        self._cal_off = _HDR_WORDS + _CAL_WORDS * (1 + self.widx)

    def calibrate(self) -> None:
        """Refresh this worker's clock pair; called per payload so the
        alignment bridge tracks NTP slew instead of drifting from it."""
        _write_cal(self._a, self._cal_off)

    def stamp(self, slot: int, code: int, t0_ns: int, t1_ns: int) -> None:  # zt-dispatch-critical: worker-region interval append on the parse hot path; seqlock bump + 3 word stores, no allocation
        if slot < 0:
            return
        a, b = self._a, self._base + slot * SLOT_WORDS
        n = int(a[b + _OFF_N_W])
        if n >= MAX_W_IV:
            return  # stitcher detects truncation via n_w at capacity
        a[b + _OFF_GEN_W] += 1
        iv = b + _OFF_W_IV + 3 * n
        a[iv] = code
        a[iv + 1] = t0_ns
        a[iv + 2] = t1_ns
        a[b + _OFF_N_W] = n + 1
        a[b + _OFF_GEN_W] += 1

    def close(self) -> None:
        self._a = None
        self._shm.close()


# -- dispatcher-thread active slot (wal.py stamps ride this) --------------

_active = threading.local()


def set_active(ledger: Optional[CritPathLedger], slot: int, pid: int) -> None:
    """Arm ``stamp_active`` for the current thread while a traced
    payload's device/durability feed runs (dispatcher's flush)."""
    _active.ledger = ledger if slot >= 0 else None
    _active.slot = slot
    _active.pid = pid
    _active.group = None


def set_active_group(ledger: Optional[CritPathLedger], pairs) -> None:  # zt-dispatch-critical: arms the coalesced-flush timeline map on the dispatch core
    """Arm ``stamp_active`` for a COALESCED flush: ``pairs`` is a list of
    ``(slot, pid)`` timelines sharing one device/WAL interval. Each
    traced member gets the same stamped wall window — the flush really
    did serve all of them at once, so per-timeline conservation holds."""
    pairs = [(s, p) for s, p in pairs if s >= 0]  # zt-lint: disable=ZT09 — per traced group MEMBER (≤ coalesce_max), tuple filter only
    _active.ledger = ledger if pairs else None
    _active.slot = -1
    _active.pid = -1
    _active.group = pairs or None


def clear_active() -> None:
    _active.ledger = None
    _active.slot = -1
    _active.group = None


def stamp_active(code: int, t0_ns: int, t1_ns: int) -> None:  # zt-dispatch-critical: no-op unless a traced payload is being flushed on this thread
    led = getattr(_active, "ledger", None)
    if led is None:
        return
    group = getattr(_active, "group", None)
    if group is None:
        led.stamp(_active.slot, code, t0_ns, t1_ns, _active.pid)
        return
    for slot, pid in group:  # zt-lint: disable=ZT09 — bounded by coalesce_max traced members, word stores only
        led.stamp(slot, code, t0_ns, t1_ns, pid)


def _pctl(sorted_vals: List[int], q: float) -> int:
    if not sorted_vals:
        return 0
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


class CritPathStitcher:
    """Folds completed ledger slots into aggregate critical-path surfaces.

    Runs at windows-tick cadence (``on_tick``) and on-demand from the
    statusz/bench report path; both entrances serialize on one lock —
    nothing here touches the dispatch-critical path."""

    def __init__(self, ledger: CritPathLedger, *,
                 queue_capacity: int = 1,
                 recorder=None,
                 reclaim_age_s: float = 60.0,
                 gauge_stale_s: float = 60.0) -> None:
        self._ledger = ledger
        self._queue_capacity = max(1, int(queue_capacity))
        self._recorder = recorder
        self.emitter = None  # SelfSpanEmitter, attached by the server
        self._reclaim_age_ns = int(reclaim_age_s * 1e9)
        self._gauge_stale_ns = int(gauge_stale_s * 1e9)
        self._gauges_at_ns = 0
        self._lock = threading.Lock()
        self.seg_count = [0] * N_SEGS
        self.seg_sum_us = [0] * N_SEGS
        self.seg_max_us = [0] * N_SEGS
        self.timelines = 0
        self.degraded = 0
        self.truncated = 0
        self.reclaimed = 0
        self.wall_sum_us = 0
        self._walls: deque = deque(maxlen=16384)
        self._cons: deque = deque(maxlen=4096)
        self._last_ns = _now_ns()
        self.lambda_cps = 0.0
        self.little_l = 0.0
        self.worker_occupancy = 0.0
        self.queue_saturation = 0.0
        self._slowest: Optional[dict] = None

    def on_tick(self, _windows=None) -> None:
        self.stitch()

    # -- folding ----------------------------------------------------------

    def stitch(self) -> int:
        """Fold every DONE slot, reclaim orphaned OPEN slots, refresh the
        Little's-law gauges. Returns timelines folded."""
        with self._lock:
            return self._stitch_locked()

    def _stitch_locked(self) -> int:
        led = self._ledger
        now = _now_ns()
        folded = 0
        walls_us: List[int] = []
        qwait_us = 0
        wserv_us = 0
        slow: Optional[dict] = None
        for s in range(led.slots):
            st = led.state(s)
            if st == _ST_DONE:
                blk = led.read_slot(s)
                tl = self._fold(blk) if blk is not None else None
                led.release(s)
                if tl is None:
                    self.degraded += 1
                    continue
                folded += 1
                self.timelines += 1
                if tl["truncated"]:
                    self.truncated += 1
                durs = tl["durs_us"]
                for i in range(N_SEGS):
                    d = durs[i]
                    if d <= 0:
                        continue
                    self.seg_count[i] += 1
                    self.seg_sum_us[i] += d
                    if d > self.seg_max_us[i]:
                        self.seg_max_us[i] = d
                wall = tl["wall_us"]
                self.wall_sum_us += wall
                walls_us.append(wall)
                self._walls.append(wall)
                self._cons.append(tl["conservation"])
                qwait_us += (durs[SEG_QUEUE_WAIT] + durs[SEG_SLOT_WAIT]
                             + durs[SEG_RING_WAIT])
                wserv_us += (durs[SEG_PARSE] + durs[SEG_PACK]
                             + durs[SEG_ROUTE])
                if self._recorder is not None:
                    self._recorder.record_relayed(
                        "wire_to_durable", wall / 1e6
                    )
                if slow is None or wall > slow["wall_us"]:
                    slow = tl
            elif (st == _ST_OPEN
                    and led.open_age_ns(s, now) > self._reclaim_age_ns):
                led.abandon(s)
                self.reclaimed += 1
        # Little's law over this stitch window: L = lambda * W. The
        # gauges describe the most recent non-idle window; an idle tick
        # KEEPS them (INGEST_r08 read all zeros because the report-path
        # stitch after a drained load was always idle and clobbered the
        # real window) and only a sustained idle spell past the
        # staleness horizon zeroes them, so a stale saturation reading
        # still cannot hold an SLO alert forever.
        dt_s = max(1e-9, (now - self._last_ns) / 1e9)
        self._last_ns = now
        if folded:
            lam = folded / dt_s
            mean_wall_s = (sum(walls_us) / folded) / 1e6
            self.lambda_cps = lam
            self.little_l = lam * mean_wall_s
            self.worker_occupancy = (
                lam * (wserv_us / folded) / 1e6 / led.n_workers
            )
            self.queue_saturation = (
                lam * (qwait_us / folded) / 1e6 / self._queue_capacity
            )
            self._gauges_at_ns = now
        elif (self._gauges_at_ns
                and now - self._gauges_at_ns > self._gauge_stale_ns):
            self.lambda_cps = 0.0
            self.little_l = 0.0
            self.worker_occupancy = 0.0
            self.queue_saturation = 0.0
        if slow is not None:
            self._slowest = slow
            if self.emitter is not None:
                try:
                    self.emitter.emit_spans(self._spans_for(slow))
                except Exception:  # pragma: no cover - surface never fatal
                    pass
        return folded

    def _fold(self, blk: np.ndarray) -> Optional[dict]:
        """One slot -> a timeline dict, or None when the slot cannot be
        decomposed (no ack, non-positive wall after alignment, flagged
        degraded by the fallback path)."""
        wire = int(blk[_OFF_WIRE_T0])
        ack = int(blk[_OFF_ACK_T])
        widx = int(blk[_OFF_WIDX])
        flags = int(blk[_OFF_FLAGS])
        if flags & _FLAG_DEGRADED or ack <= wire or wire <= 0:
            return None
        wall_ns = ack - wire
        off = self._ledger.worker_offset_ns(widx)
        n_d = min(int(blk[_OFF_N_D]), MAX_D_IV)
        n_w = min(int(blk[_OFF_N_W]), MAX_W_IV)
        truncated = bool(flags & _FLAG_TRUNC_D) or n_w >= MAX_W_IV
        ivs: List[tuple] = []
        for i in range(n_d):
            o = _OFF_D_IV + 3 * i
            ivs.append((int(blk[o]), int(blk[o + 1]), int(blk[o + 2])))
        for i in range(n_w):
            o = _OFF_W_IV + 3 * i
            ivs.append((int(blk[o]), int(blk[o + 1]) + off,
                        int(blk[o + 2]) + off))
        # raw service durations, with the two known nestings deduped:
        # wal stamps land inside the device_feed window (the WAL append
        # rides ingest_fused), so feed's own time excludes them
        durs_ns = [0] * N_SEGS
        for code, t0, t1 in ivs:
            if 0 <= code < N_SEGS and t1 > t0:
                durs_ns[code] += t1 - t0
        durs_ns[SEG_DEVICE_FEED] = max(
            0, durs_ns[SEG_DEVICE_FEED]
            - durs_ns[SEG_WAL_APPEND] - durs_ns[SEG_WAL_FSYNC]
        )
        # phase boundaries for gap classification
        w_ts = [(t0, t1) for c, t0, t1 in ivs if c in _WORKER_SEGS]
        d_ts = [(t0, t1) for c, t0, t1 in ivs
                if c not in _WORKER_SEGS and c != SEG_ENQUEUE]
        enq = [(t0, t1) for c, t0, t1 in ivs if c == SEG_ENQUEUE]
        enq_t0 = enq[0][0] if enq else wire
        w_t0 = min(t[0] for t in w_ts) if w_ts else 0
        w_t1 = max(t[1] for t in w_ts) if w_ts else 0
        d_t0 = min(t[0] for t in d_ts) if d_ts else 0
        d_t1 = max(t[1] for t in d_ts) if d_ts else 0
        # sweep the stamped intervals clipped to [wire, ack]; every
        # uncovered range is a derived wait, classified by phase
        clipped = sorted(
            (max(t0, wire), min(t1, ack)) for _, t0, t1 in ivs
        )
        cursor = wire
        for t0, t1 in clipped:
            if t0 > cursor:
                self._classify_gap(durs_ns, cursor, t0, enq_t0,
                                   w_ts, w_t0, w_t1, d_ts, d_t0, d_t1)
            if t1 > cursor:
                cursor = t1
        if cursor < ack:
            durs_ns[SEG_ACK] += ack - cursor
        durs_us = [d // 1000 for d in durs_ns]
        wall_us = wall_ns // 1000
        conservation = sum(durs_ns) / wall_ns
        return {
            "wall_us": wall_us,
            "conservation": conservation,
            "durs_us": durs_us,
            "pid": int(blk[_OFF_PID]),
            "widx": widx,
            "tenant": int(blk[_OFF_TENANT]),
            "wire_ns": wire,
            "ack_ns": ack,
            "intervals": ivs,
            "truncated": truncated,
        }

    @staticmethod
    def _classify_gap(durs_ns, a, b, enq_t0, w_ts, w_t0, w_t1,
                      d_ts, d_t0, d_t1) -> None:
        dur = b - a
        if b <= enq_t0:
            durs_ns[SEG_BOUNDARY] += dur
        elif w_ts and b <= w_t0:
            durs_ns[SEG_QUEUE_WAIT] += dur
        elif w_ts and a < w_t1:
            durs_ns[SEG_WORKER_OTHER] += dur
        elif d_ts and b <= d_t0:
            durs_ns[SEG_HANDOFF_WAIT] += dur
        elif d_ts and a < d_t1:
            durs_ns[SEG_DISPATCH_OTHER] += dur
        else:
            durs_ns[SEG_ACK] += dur

    # -- self-span emission ----------------------------------------------

    def _spans_for(self, tl: dict) -> list:
        """A slowest-chunk timeline as a root wire_to_durable span plus
        one child per stamped interval — retrievable in the server's own
        trace UI like any user trace."""
        from zipkin_tpu.model import Endpoint, Span
        from zipkin_tpu.obs.selfspans import SERVICE_NAME, _new_id

        mono_m, wall_m = self._ledger._cal(0)
        bridge_ns = wall_m - mono_m
        ep = Endpoint.create(service_name=SERVICE_NAME, ip="127.0.0.1")
        trace_id = _new_id()
        root_id = _new_id()
        root_ts = max(1, (tl["wire_ns"] + bridge_ns) // 1000)
        spans = [Span.create(
            trace_id=trace_id, id=root_id, name="wire_to_durable",
            timestamp=root_ts, duration=max(1, tl["wall_us"]),
            local_endpoint=ep,
            tags={
                "obs.critpath.conservation": "%.3f" % tl["conservation"],
                "obs.critpath.pid": str(tl["pid"]),
                "obs.critpath.worker": str(tl["widx"]),
                "obs.critpath.tenant": str(tl.get("tenant", 0)),
                "obs.critpath.queue_wait_us":
                    str(tl["durs_us"][SEG_QUEUE_WAIT]),
            },
        )]
        for code, t0, t1 in tl["intervals"]:
            if not (0 <= code < N_SEGS) or t1 <= t0:
                continue
            spans.append(Span.create(
                trace_id=trace_id, id=_new_id(), parent_id=root_id,
                name=SEG_NAMES[code],
                timestamp=max(1, (t0 + bridge_ns) // 1000),
                duration=max(1, (t1 - t0) // 1000),
                local_endpoint=ep,
                tags={"obs.critpath.kind": SEG_KIND[code]},
            ))
        return spans

    # -- surfaces ---------------------------------------------------------

    def counters(self) -> Dict[str, object]:
        """Flat gauges for the counter/SLO plane plus one nested
        segment table (scalar-only consumers skip it)."""
        with self._lock:
            cons = sorted(self._cons)
            segs = {
                SEG_NAMES[i]: {
                    "kind": SEG_KIND[i],
                    "count": self.seg_count[i],
                    "sumUs": self.seg_sum_us[i],
                    "maxUs": self.seg_max_us[i],
                }
                for i in range(N_SEGS)
            }
            return {
                "critpathTimelines": self.timelines,
                "critpathSkipped": self._ledger.alloc_failed,
                "critpathAbandoned": self._ledger.abandoned,
                "critpathReclaimed": self.reclaimed,
                "critpathDegraded": self.degraded,
                "critpathTruncated": self.truncated,
                "critpathLambdaCps": round(self.lambda_cps, 3),
                "critpathLittleL": round(self.little_l, 4),
                "critpathWorkerOccupancy": round(self.worker_occupancy, 4),
                "critpathQueueSaturation": round(self.queue_saturation, 4),
                "critpathConservationP50Milli": int(
                    _pctl(cons, 0.50) * 1000
                ),
                "critpathSegments": segs,
            }

    def waterfall(self) -> Dict[str, object]:
        """The statusz/bench report: wire-to-durable percentiles, the
        ordered segment decomposition, wait-vs-service rollup, gauges,
        and the slowest stitched timeline."""
        self.stitch()  # fold anything completed since the last tick
        with self._lock:
            walls = sorted(self._walls)
            cons = sorted(self._cons)
            wait_us = sum(self.seg_sum_us[i] for i in range(N_SEGS)
                          if SEG_KIND[i] == "wait")
            serv_us = sum(self.seg_sum_us[i] for i in range(N_SEGS)
                          if SEG_KIND[i] == "service")
            segments = [
                {
                    "segment": SEG_NAMES[i],
                    "kind": SEG_KIND[i],
                    "count": self.seg_count[i],
                    "sumUs": self.seg_sum_us[i],
                    "maxUs": self.seg_max_us[i],
                    "meanUs": round(
                        self.seg_sum_us[i] / max(1, self.seg_count[i]), 1
                    ),
                }
                for i in range(N_SEGS) if self.seg_count[i]
            ]
            slow = None
            if self._slowest is not None:
                tl = self._slowest
                slow = {
                    "wallUs": tl["wall_us"],
                    "pid": tl["pid"],
                    "worker": tl["widx"],
                    "conservation": round(tl["conservation"], 3),
                    "segments": [
                        {"segment": SEG_NAMES[i], "kind": SEG_KIND[i],
                         "us": tl["durs_us"][i]}
                        for i in range(N_SEGS) if tl["durs_us"][i] > 0
                    ],
                }
            return {
                "timelines": self.timelines,
                "skipped": self._ledger.alloc_failed,
                "abandoned": self._ledger.abandoned,
                "reclaimed": self.reclaimed,
                "degraded": self.degraded,
                "wireToDurable": {
                    "count": len(walls),
                    "p50Us": _pctl(walls, 0.50),
                    "p99Us": _pctl(walls, 0.99),
                    "maxUs": walls[-1] if walls else 0,
                },
                "conservation": {
                    "p50": round(_pctl(cons, 0.50), 4) if cons else 0.0,
                    "min": round(cons[0], 4) if cons else 0.0,
                    "max": round(cons[-1], 4) if cons else 0.0,
                },
                "queueWaitVsService": {
                    "waitUs": wait_us,
                    "serviceUs": serv_us,
                    "waitFraction": round(
                        wait_us / max(1, wait_us + serv_us), 4
                    ),
                },
                "littlesLaw": {
                    "lambdaCps": round(self.lambda_cps, 3),
                    "littleL": round(self.little_l, 4),
                    "workerOccupancy": round(self.worker_occupancy, 4),
                    "queueSaturation": round(self.queue_saturation, 4),
                },
                "segments": segments,
                "slowest": slow,
            }
