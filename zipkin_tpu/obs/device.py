"""Device-program observatory: runtime visibility into jitted programs.

The static lint (ZT03) proves no *avoidable* recompile triggers exist in
the source; this is the dynamic complement. Every jitted/shard_map
entrypoint (ingest step variants, rollup, the spmd_* read programs) is
wrapped at build time in :func:`DeviceObservatory.wrap`, which captures:

- **call count + per-call device wall** (dispatch-to-ready, host view);
- **compile count + compile wall** via the jit cache-size delta: jax's
  ``jitted._cache_size()`` grows once per distinct input-shape
  signature, so ``after > before`` around a call means that call paid a
  trace+compile — a *runtime recompile detector*. Steady state must
  show zero growth after warmup;
- **``cost_analysis()`` / ``memory_analysis()`` at first compile**,
  captured best-effort through an AOT ``lower().compile()`` of the same
  arguments (one extra compile per program per process; disable with
  ``TPU_OBS_DEVICE_ANALYSIS=0`` where compiles are expensive). The AOT
  path does not populate the jit dispatch cache, so it never perturbs
  the recompile detector;
- **live-HBM and host-transfer gauges**: accelerator
  ``memory_stats()`` (absent on CPU) and the readpack transfer
  count/bytes, surfaced next to the existing ``hostTransfers`` counter.

Counter updates are plain attribute writes: device dispatches are
serialized under the aggregator lock, and these are debug gauges — a
rare torn increment from an exotic caller skews a count, nothing more.
The registry is process-global and name-keyed; ``_compiled_programs``
is lru-cached per (config, mesh), so one name may accumulate several
entries over a test run — reads merge them.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from zipkin_tpu.obs import querytrace


class ProgramStats:
    """Counters for one wrapped program build (one jit'd callable)."""

    __slots__ = ("name", "calls", "compiles", "call_wall_s",
                 "compile_wall_s", "last_compile_s", "max_call_s",
                 "cache_size", "cost", "memory", "analysis_wall_s",
                 "_analysis_tried", "_cache_size_fn")

    def __init__(self, name: str, fn: Callable) -> None:
        self.name = name
        self.calls = 0
        self.compiles = 0
        self.call_wall_s = 0.0
        self.compile_wall_s = 0.0
        self.last_compile_s = 0.0
        self.max_call_s = 0.0
        self.cache_size = 0
        self.cost: Optional[Dict[str, float]] = None
        self.memory: Optional[Dict[str, int]] = None
        self.analysis_wall_s = 0.0
        self._analysis_tried = False
        # private jax API, probed once; absent -> no recompile detection
        self._cache_size_fn = getattr(fn, "_cache_size", None)

    @property
    def recompiles(self) -> int:
        """Compiles beyond the first: shape churn after warmup."""
        return max(0, self.compiles - 1)

    def observe(self, fn: Callable, args: tuple, kw: dict,
                analysis: bool) -> Any:
        size_fn = self._cache_size_fn
        before = size_fn() if size_fn is not None else -1
        t0 = time.perf_counter()
        out = fn(*args, **kw)
        dt = time.perf_counter() - t0
        # query-plane observatory: when the calling thread carries an
        # armed QueryTrace (read path only), the enqueue wall of this
        # program is that query's device_dispatch segment. perf_counter
        # and perf_counter_ns share a clock, so the ns conversion is
        # exact enough for the stitcher's gap sweep.
        querytrace.stamp_active(
            querytrace.QSEG_DEVICE_DISPATCH,
            int(t0 * 1e9), int((t0 + dt) * 1e9),
        )
        self.calls += 1
        self.call_wall_s += dt
        if dt > self.max_call_s:
            self.max_call_s = dt
        if size_fn is not None:
            after = size_fn()
            if after > before:
                self.compiles += after - before
                self.compile_wall_s += dt
                self.last_compile_s = dt
                self.cache_size = after
                if analysis and not self._analysis_tried:
                    self._capture_analysis(fn, args, kw)
        return out

    def _capture_analysis(self, fn: Callable, args: tuple,
                          kw: dict) -> None:
        self._analysis_tried = True
        try:
            t0 = time.perf_counter()
            compiled = fn.lower(*args, **kw).compile()
            self.analysis_wall_s = time.perf_counter() - t0
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            if isinstance(ca, dict):
                self.cost = {
                    "flops": float(ca.get("flops", 0.0)),
                    "bytesAccessed": float(ca.get("bytes accessed", 0.0)),
                }
            ma = compiled.memory_analysis()
            if ma is not None:
                self.memory = {
                    "generatedCodeBytes": int(getattr(
                        ma, "generated_code_size_in_bytes", 0)),
                    "argumentBytes": int(getattr(
                        ma, "argument_size_in_bytes", 0)),
                    "outputBytes": int(getattr(
                        ma, "output_size_in_bytes", 0)),
                    "tempBytes": int(getattr(ma, "temp_size_in_bytes", 0)),
                }
        except Exception:
            pass

    def as_dict(self) -> Dict:
        d: Dict = {
            "calls": self.calls,
            "compiles": self.compiles,
            "recompiles": self.recompiles,
            "callWallMs": round(self.call_wall_s * 1e3, 3),
            "compileWallMs": round(self.compile_wall_s * 1e3, 3),
            "lastCompileMs": round(self.last_compile_s * 1e3, 3),
            "maxCallMs": round(self.max_call_s * 1e3, 3),
        }
        if self.cost is not None:
            d["cost"] = self.cost
        if self.memory is not None:
            d["memory"] = self.memory
        return d


class DeviceObservatory:
    """Process-global registry of wrapped device programs."""

    def __init__(self, enabled: bool = True, analysis: bool = True) -> None:
        self._enabled = bool(enabled)
        self._analysis = bool(analysis)
        self._lock = threading.Lock()
        self._programs: Dict[str, List[ProgramStats]] = {}

    def wrap(self, name: str, fn: Callable) -> Callable:
        """Wrap one jitted callable; transparent when disabled."""
        entry = ProgramStats(name, fn)
        with self._lock:
            self._programs.setdefault(name, []).append(entry)
        obs = self

        def wrapper(*args, **kw):
            if not obs._enabled:
                return fn(*args, **kw)
            return entry.observe(fn, args, kw, obs._analysis)

        wrapper.__name__ = name
        wrapper.__wrapped__ = fn
        wrapper.program_stats = entry
        # AOT path stays reachable (benchmarks lower() programs directly)
        lower = getattr(fn, "lower", None)
        if lower is not None:
            wrapper.lower = lower
        return wrapper

    # -- configuration -------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, on: bool) -> None:
        self._enabled = bool(on)

    def set_analysis(self, on: bool) -> None:
        self._analysis = bool(on)

    def reset_counters(self) -> None:
        """Forget per-entry counters (bench A/B helper); keeps wraps."""
        with self._lock:
            entries = [e for lst in self._programs.values() for e in lst]
        for e in entries:
            e.calls = 0
            e.compiles = 0
            e.call_wall_s = 0.0
            e.compile_wall_s = 0.0
            e.last_compile_s = 0.0
            e.max_call_s = 0.0

    # -- query side ----------------------------------------------------

    def totals(self) -> Dict[str, int]:
        calls = compiles = recompiles = 0
        with self._lock:
            entries = [e for lst in self._programs.values() for e in lst]
        for e in entries:
            calls += e.calls
            compiles += e.compiles
            recompiles += e.recompiles
        return {"programs": len(self._programs), "calls": calls,
                "compiles": compiles, "recompiles": recompiles}

    def programs(self) -> Dict[str, Dict]:
        """Per-name merged view (several builds of one name sum up)."""
        with self._lock:
            items = {k: list(v) for k, v in self._programs.items()}
        out: Dict[str, Dict] = {}
        for name, entries in sorted(items.items()):
            merged: Dict = {
                "builds": len(entries), "calls": 0, "compiles": 0,
                "recompiles": 0, "callWallMs": 0.0, "compileWallMs": 0.0,
                "lastCompileMs": 0.0, "maxCallMs": 0.0,
            }
            for e in entries:
                d = e.as_dict()
                merged["calls"] += d["calls"]
                merged["compiles"] += d["compiles"]
                merged["recompiles"] += d["recompiles"]
                merged["callWallMs"] = round(
                    merged["callWallMs"] + d["callWallMs"], 3)
                merged["compileWallMs"] = round(
                    merged["compileWallMs"] + d["compileWallMs"], 3)
                merged["lastCompileMs"] = max(
                    merged["lastCompileMs"], d["lastCompileMs"])
                merged["maxCallMs"] = max(merged["maxCallMs"], d["maxCallMs"])
                if "cost" in d:
                    merged["cost"] = d["cost"]
                if "memory" in d:
                    merged["memory"] = d["memory"]
            out[name] = merged
        return out

    def status(self) -> Dict:
        """Full dict for the ``/statusz`` device section."""
        body = {
            "enabled": self._enabled,
            "analysis": self._analysis,
            "totals": self.totals(),
            "programs": self.programs(),
            "hbm": hbm_stats(),
        }
        try:
            from zipkin_tpu import readpack

            body["transfers"] = {
                "count": readpack.transfer_count(),
                "bytes": readpack.transfer_bytes(),
            }
        except Exception:
            pass
        return body


def hbm_stats() -> Dict:
    """Live accelerator memory across local devices; ``{}`` where the
    backend exposes no ``memory_stats()`` (CPU)."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return {}
    in_use = limit = peak = 0
    seen = 0
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        seen += 1
        in_use += int(stats.get("bytes_in_use", 0))
        limit += int(stats.get("bytes_limit", 0))
        peak += int(stats.get("peak_bytes_in_use", 0))
    if not seen:
        return {}
    return {"devices": seen, "bytesInUse": in_use, "bytesLimit": limit,
            "peakBytesInUse": peak}


def _env_on(name: str, default: str = "1") -> bool:
    return os.environ.get(name, default).strip().lower() \
        not in ("0", "false", "no")


OBSERVATORY = DeviceObservatory(
    enabled=_env_on("TPU_OBS_DEVICE") and _env_on("TPU_OBS"),
    analysis=_env_on("TPU_OBS_DEVICE_ANALYSIS"),
)

wrap = OBSERVATORY.wrap
