"""Incident capture: SLO trips dump a debug bundle that outlives rings.

The observability planes are deliberately volatile — slow rings,
windowed delta rings, stitcher aggregates — so by the time an operator
looks at a tripped SLO, the evidence has often rotated out. The
watchdog's ``on_trip`` hook hands each trip to an
:class:`IncidentRecorder`, which snapshots every registered source
(statusz-equivalent dicts: stage histograms, the slow ring, ingest and
query waterfalls, windowed percentiles, the verdict list) into one JSON
bundle under ``TPU_OBS_INCIDENT_DIR``, with bounded retention so a
flapping SLO cannot fill the disk.

Capture runs on the ticker thread (evaluate → trip → hook), so sources
must be plain dict builders; every source is wrapped in its own
try/except and a failing source degrades to an error note instead of
losing the bundle.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, Optional

_PREFIX = "incident-"


class IncidentRecorder:
    """Writes bounded-retention incident bundles to ``directory``."""

    def __init__(self, directory: str, retention: int = 16,
                 sources: Optional[Dict[str, Callable]] = None) -> None:
        self.directory = directory
        self.retention = max(1, int(retention))
        self.sources: Dict[str, Callable] = dict(sources or {})
        self._lock = threading.Lock()
        self.captured = 0
        self.errors = 0
        os.makedirs(directory, exist_ok=True)

    def add_source(self, name: str, fn: Callable) -> None:
        self.sources[name] = fn

    def on_slo_trip(self, name: str, verdict: Dict) -> Optional[str]:
        """Watchdog ``on_trip`` adapter."""
        return self.capture({"kind": "slo_trip", "name": name,
                             "verdict": verdict})

    def capture(self, trigger: Dict) -> Optional[str]:
        """Snapshot every source into one bundle; returns its path."""
        bundle: Dict = {
            "trigger": trigger,
            "capturedAtMs": int(time.time() * 1000),
        }
        for name, fn in list(self.sources.items()):
            try:
                bundle[name] = fn()
            except Exception as e:
                bundle[name] = {"error": str(e)}
        stem = str(trigger.get("name", "incident")).replace(os.sep, "_")
        with self._lock:
            path = os.path.join(
                self.directory,
                f"{_PREFIX}{bundle['capturedAtMs']:013d}-"
                f"{self.captured:04d}-{stem}.json",
            )
            try:
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(bundle, f, default=str)
                os.replace(tmp, path)
            except Exception:
                self.errors += 1
                return None
            self.captured += 1
            self._prune_locked()
        return path

    def bundles(self):
        """Bundle paths, oldest first (name order == capture order)."""
        try:
            names = sorted(
                n for n in os.listdir(self.directory)
                if n.startswith(_PREFIX) and n.endswith(".json")
            )
        except OSError:
            return []
        return [os.path.join(self.directory, n) for n in names]

    def _prune_locked(self) -> None:
        stale = self.bundles()[:-self.retention]
        for p in stale:
            try:
                os.remove(p)
            except OSError:
                pass

    def counters(self) -> Dict:
        return {
            "incidentsCaptured": self.captured,
            "incidentWriteErrors": self.errors,
            "incidentRetention": self.retention,
        }
