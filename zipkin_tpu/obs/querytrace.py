"""Query-plane observatory: per-query critical paths + lock contention.

The ingest half of the pipeline is observable wire-to-durable
(obs/critpath.py); this module is the read-side mirror. ROADMAP item 4
says the store must serve many concurrent dashboard readers at
p99 < 50 ms; the refactor that got there — the epoch-published read
mirror in ``tpu/mirror.py`` that takes reads off the aggregator lock —
is judged by this instrument: mirror serves stamp the lock-free
``mirror_serve`` segment, and a fresh read that still queues on the
lock shows up as ``lock_wait``. Three pieces:

- A **thread-local :class:`QueryTrace`** armed at the storage read
  entrypoints (``tpu/store.py``) and stamped — without taking any lock
  on the hot path — by the layers a query crosses: the read-cache probe,
  the instrumented aggregator lock (wait only; the hold is ledger
  state), the device-program dispatch (via ``obs/device.py``), the
  dispatch-to-ready device wall, the single packed device→host pull and
  its zero-copy unpack (``readpack.py``), vocab link resolution, and row
  serialization. Stamps are plain list appends on the owning thread;
  an unarmed thread pays one thread-local read.
- An **instrumented re-entrant lock** (:class:`InstrumentedRLock`) that
  replaces the aggregator's bare ``threading.RLock``. The outermost
  acquire measures wait (uncontended acquires take a non-blocking fast
  path), the outermost release measures hold; both land in log2-µs
  histograms next to live waiter depth, a high-water mark, and per-label
  holder attribution (the active query's name, or the label ingest set).
  Every outermost wait is also relayed into the ``query_lock_wait``
  recorder stage so the windowed plane and the SLO watchdog see
  contention the moment it exists. Re-entrant acquires (read paths nest:
  ``dependency_edges`` → ``window_fully_rolled``) are counted but never
  measured — an RLock re-acquire by its holder cannot block.
- A **stitcher** (:class:`QueryObservatory`) folding completed traces at
  windows-tick cadence into per-segment count/sum/max aggregates, query
  wall percentiles, and a conservation check (segments + attributed gaps
  must sum to the measured wall); each folded wall is relayed into the
  ``query_wall`` stage, and the slowest query per stitch is emitted as a
  real self-span timeline through the SelfSpanEmitter.

Lint: ZT04 recognizes :class:`InstrumentedRLock` as a lock constructor
so the aggregator's with-discipline survives the swap, and ZT08 fences
``begin``/``finish``/``stamp_active`` out of jitted/shard_map code.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional

from zipkin_tpu import obs as _obs
from zipkin_tpu.obs.recorder import NUM_BUCKETS, bucket_le_us

# -- segment taxonomy ----------------------------------------------------
# Stamped segments carry measured intervals; QSEG_OTHER is derived — the
# gap sweep attributes every unstamped nanosecond of the query wall to
# it, so conservation holds by construction and "other" shrinking is the
# measure of attribution coverage.

QSEG_LOCK_WAIT = 0          # outermost contended wait on the aggregator lock
QSEG_CACHE_PROBE = 1        # read-cache lock + version check + lookup
QSEG_DEVICE_DISPATCH = 2    # enqueue wall of a wrapped device read program
QSEG_DEVICE_WALL = 3        # dispatch done -> packed result device-ready
QSEG_READPACK_TRANSFER = 4  # the single packed device->host pull
QSEG_UNPACK = 5             # zero-copy view carve of the packed buffer
QSEG_LINK_RESOLVE = 6       # id->name vocab resolution into DependencyLinks
QSEG_SERIALIZE = 7          # row shaping of device output into API objects
QSEG_OTHER = 8              # derived: unstamped query time (gap sweep)
QSEG_MIRROR_SERVE = 9       # lock-free serve from the epoch-published mirror
QSEG_READER_SERVE = 10      # reader-process serve from the shm mirror segment
N_QSEGS = 11

QSEG_NAMES = (
    "lock_wait", "cache_probe", "device_dispatch", "device_wall",
    "readpack_transfer", "unpack", "link_resolve", "serialize", "other",
    "mirror_serve", "reader_serve",
)
_QWAIT = frozenset((QSEG_LOCK_WAIT, QSEG_OTHER))
QSEG_KIND = tuple(
    "wait" if i in _QWAIT else "service" for i in range(N_QSEGS)
)


def _env_on(name: str, default: str = "1") -> bool:
    return os.environ.get(name, default).strip().lower() \
        not in ("0", "false", "no")


def _default_enabled() -> bool:
    return _env_on("TPU_OBS_QUERY") and _env_on("TPU_OBS")


def _pctl(sorted_vals, q: float):
    if not sorted_vals:
        return 0
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


def _hist_quantile_us(hist: List[int], q: float) -> int:
    total = sum(hist)
    if total <= 0:
        return 0
    rank = int(q * (total - 1))
    seen = 0
    for b, n in enumerate(hist):
        seen += n
        if seen > rank:
            return bucket_le_us(b)
    return bucket_le_us(len(hist) - 1)


def _bucket(us: int) -> int:
    return min(NUM_BUCKETS - 1, int(us).bit_length())


# -- thread-local active trace -------------------------------------------

_active = threading.local()
_label = threading.local()


class QueryTrace:
    """One query's interval timeline; owned by exactly one thread."""

    __slots__ = ("name", "t0_ns", "wall_ns", "ivs")

    def __init__(self, name: str) -> None:
        self.name = name
        self.t0_ns = time.perf_counter_ns()
        self.wall_ns = 0
        self.ivs: List[tuple] = []   # (code, t0_ns, t1_ns)


def active() -> Optional[QueryTrace]:
    """The calling thread's in-flight trace, if any."""
    return getattr(_active, "trace", None)


def stamp_active(code: int, t0_ns: int, t1_ns: int) -> None:  # zt-dispatch-critical: one thread-local read + list append when armed; pure no-op otherwise
    tr = getattr(_active, "trace", None)
    if tr is None:
        return
    tr.ivs.append((code, t0_ns, t1_ns))


@contextmanager
def lock_label(label: str):
    """Attribute aggregator-lock holds on this thread to ``label`` when
    no query trace is active (the write path has no trace)."""
    prev = getattr(_label, "v", None)
    _label.v = label
    try:
        yield
    finally:
        _label.v = prev


def current_label() -> str:
    tr = getattr(_active, "trace", None)
    if tr is not None:
        return "query:" + tr.name
    return getattr(_label, "v", None) or "unattributed"


# -- the instrumented aggregator lock ------------------------------------


class InstrumentedRLock:
    """Re-entrant lock with a contention ledger.

    Drop-in for ``threading.RLock`` under ``with`` discipline. Counter
    writes that happen while holding the inner lock are serialized by
    it; the waiter depth/high-water pair is the only state mutated by
    threads that do NOT hold the lock, so it lives under ``_meta``.
    Histogram reads from the counters path may be torn by one in-flight
    increment — these are debug gauges, same contract as obs/device.py.
    """

    def __init__(self, name: str = "agg", recorder=None,
                 enabled: Optional[bool] = None) -> None:
        self.name = name
        self._inner = threading.RLock()
        self._tl = threading.local()
        self._meta = threading.Lock()
        self._recorder = recorder
        self._enabled = _default_enabled() if enabled is None else bool(enabled)
        self.waiters = 0
        self.waiters_high_water = 0
        self.acquisitions = 0
        self.contended = 0
        self.reentries = 0
        self.wait_sum_us = 0
        self.wait_max_us = 0
        self.hold_sum_us = 0
        self.hold_max_us = 0
        self._wait_hist = [0] * NUM_BUCKETS
        self._hold_hist = [0] * NUM_BUCKETS
        self._holders: Dict[str, List[int]] = {}  # label -> [count, holdSumUs]
        self._hold_t0 = 0
        self._holder_label = "unattributed"

    # -- configuration --------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, on: bool) -> None:
        self._enabled = bool(on)

    def reset_counters(self) -> None:
        """Zero the ledger (bench A/B helper); live depth is preserved."""
        with self._meta:
            self.waiters_high_water = self.waiters
        self.acquisitions = 0
        self.contended = 0
        self.reentries = 0
        self.wait_sum_us = 0
        self.wait_max_us = 0
        self.hold_sum_us = 0
        self.hold_max_us = 0
        self._wait_hist = [0] * NUM_BUCKETS
        self._hold_hist = [0] * NUM_BUCKETS
        self._holders = {}

    # -- lock protocol ---------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        depth = getattr(self._tl, "depth", 0)
        if depth:
            got = self._inner.acquire(blocking, timeout)
            if got:
                self._tl.depth = depth + 1
                self.reentries += 1  # holder-thread write: serialized
            return got
        if not self._enabled or not blocking or timeout != -1:
            got = self._inner.acquire(blocking, timeout)
            if got:
                self._tl.depth = 1
                self.acquisitions += 1
                self._hold_t0 = 0  # unmeasured acquire: skip hold math
            return got
        t0 = time.perf_counter_ns()
        if self._inner.acquire(blocking=False):
            wait_ns = 0
        else:
            with self._meta:
                self.waiters += 1
                if self.waiters > self.waiters_high_water:
                    self.waiters_high_water = self.waiters
            self._inner.acquire()
            with self._meta:
                self.waiters -= 1
            wait_ns = time.perf_counter_ns() - t0
            self.contended += 1
        # Holding from here on: counter writes serialized by the lock.
        self._tl.depth = 1
        self.acquisitions += 1
        wait_us = wait_ns // 1000
        self._wait_hist[_bucket(wait_us)] += 1
        self.wait_sum_us += wait_us
        if wait_us > self.wait_max_us:
            self.wait_max_us = wait_us
        self._hold_t0 = time.perf_counter_ns()
        self._holder_label = current_label()
        if wait_ns:
            stamp_active(QSEG_LOCK_WAIT, t0, t0 + wait_ns)
        rec = self._recorder if self._recorder is not None else _obs.RECORDER
        rec.record_relayed("query_lock_wait", wait_ns / 1e9)
        return True

    def release(self) -> None:
        depth = getattr(self._tl, "depth", 0)
        if depth > 1:
            self._tl.depth = depth - 1
            self._inner.release()
            return
        if self._enabled and self._hold_t0:
            hold_us = (time.perf_counter_ns() - self._hold_t0) // 1000
            self._hold_hist[_bucket(hold_us)] += 1
            self.hold_sum_us += hold_us
            if hold_us > self.hold_max_us:
                self.hold_max_us = hold_us
            ent = self._holders.get(self._holder_label)
            if ent is None:
                ent = self._holders[self._holder_label] = [0, 0]
            ent[0] += 1
            ent[1] += hold_us
        self._hold_t0 = 0
        self._tl.depth = 0
        self._inner.release()

    def would_block(self) -> bool:
        """Non-blocking contention probe: True when ANOTHER thread
        holds the lock right now (a read here would queue). Touches
        neither the ledger (``contended`` is its counter) nor the
        re-entrancy depth — a probe is not an acquisition. The
        mirror's serve arbitration uses this: a version-stale epoch
        may serve a default request only while the fresh path would
        actually block."""
        if getattr(self._tl, "depth", 0):
            return False
        if self._inner.acquire(blocking=False):
            self._inner.release()
            return False
        return True

    def relabel(self, label: str) -> None:
        """Override the holder attribution for the CURRENT outermost
        hold; no-op when called from a nested (re-entrant) hold so an
        enclosing query keeps the attribution for work it caused."""
        if getattr(self._tl, "depth", 0) == 1:
            self._holder_label = label

    def __enter__(self) -> "InstrumentedRLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # -- ledger reads ----------------------------------------------------

    def counters(self) -> Dict:
        wait_hist = list(self._wait_hist)
        hold_hist = list(self._hold_hist)
        holders = {
            k: {"count": v[0], "holdSumUs": v[1]}
            for k, v in list(self._holders.items())
        }
        return {
            "queryLockAcquisitions": self.acquisitions,
            "queryLockContended": self.contended,
            "queryLockReentries": self.reentries,
            "queryLockWaiters": self.waiters,
            "queryLockWaitersHighWater": self.waiters_high_water,
            "queryLockWaitSumUs": self.wait_sum_us,
            "queryLockWaitMaxUs": self.wait_max_us,
            "queryLockWaitP50Us": _hist_quantile_us(wait_hist, 0.50),
            "queryLockWaitP99Us": _hist_quantile_us(wait_hist, 0.99),
            "queryLockHoldSumUs": self.hold_sum_us,
            "queryLockHoldMaxUs": self.hold_max_us,
            "queryLockHoldP50Us": _hist_quantile_us(hold_hist, 0.50),
            "queryLockHoldP99Us": _hist_quantile_us(hold_hist, 0.99),
            # nested table: skipped by the flat gauge loops, consumed by
            # the /prometheus zipkin_tpu_query_lock_* renderer
            "queryLock": {
                "waitHist": wait_hist,
                "waitSumUs": self.wait_sum_us,
                "holdHist": hold_hist,
                "holdSumUs": self.hold_sum_us,
                "holders": holders,
            },
        }

    def status(self) -> Dict:
        body = {k: v for k, v in self.counters().items() if k != "queryLock"}
        body["name"] = self.name
        body["enabled"] = self._enabled
        body["holders"] = self.counters()["queryLock"]["holders"]
        return body


# -- the stitcher --------------------------------------------------------


class QueryObservatory:
    """Owns the completed-trace queue and the fold aggregates for one
    store. ``begin``/``finish`` bracket a query on its serving thread;
    ``on_tick`` (registered with the windows ticker, before the SLO
    watchdog so alerts lag at most one tick) folds what completed."""

    def __init__(self, recorder=None,
                 enabled: Optional[bool] = None) -> None:
        self.enabled = _default_enabled() if enabled is None else bool(enabled)
        self._recorder = recorder
        self._lock = threading.Lock()
        self._done: deque = deque(maxlen=8192)   # GIL-atomic appends
        self.emitter = None          # SelfSpanEmitter, wired by the server
        self.lock_provider: Optional[Callable] = None  # -> InstrumentedRLock
        self.queries = 0
        self.wall_sum_us = 0
        self.seg_count = [0] * N_QSEGS
        self.seg_sum_us = [0] * N_QSEGS
        self.seg_max_us = [0] * N_QSEGS
        self._walls: deque = deque(maxlen=16384)   # µs
        self._cons: deque = deque(maxlen=4096)
        self._slowest: Optional[Dict] = None

    # -- trace lifecycle (serving threads) -------------------------------

    def begin(self, name: str) -> Optional[QueryTrace]:
        """Arm a trace for this thread; None when disabled or when an
        enclosing query already owns the thread (nested reads fold into
        the outer timeline)."""
        if not self.enabled:
            return None
        if getattr(_active, "trace", None) is not None:
            return None
        tr = QueryTrace(name)
        _active.trace = tr
        return tr

    def finish(self, tr: Optional[QueryTrace]) -> None:
        if tr is None:
            return
        if getattr(_active, "trace", None) is tr:
            _active.trace = None
        tr.wall_ns = max(1, time.perf_counter_ns() - tr.t0_ns)
        self._done.append(tr)

    # -- stitching (ticker thread) ---------------------------------------

    def on_tick(self, _windows=None) -> None:
        self.stitch()

    def stitch(self) -> int:
        with self._lock:
            return self._stitch_locked()

    def _stitch_locked(self) -> int:  # zt-lint: disable=ZT04 — sole caller stitch() holds self._lock; the drain+fold must be one critical section
        rec = self._recorder if self._recorder is not None else _obs.RECORDER
        folded = 0
        slowest = None
        while True:
            try:
                tr = self._done.popleft()
            except IndexError:
                break
            f = self._fold(tr)
            folded += 1
            self.queries += 1
            for c, d_ns in enumerate(f["durs_ns"]):
                if not d_ns:
                    continue
                us = d_ns // 1000
                self.seg_count[c] += 1
                self.seg_sum_us[c] += us
                if us > self.seg_max_us[c]:
                    self.seg_max_us[c] = us
            wall_us = f["wall_ns"] // 1000
            self.wall_sum_us += wall_us
            self._walls.append(wall_us)
            self._cons.append(f["conservation"])
            rec.record_relayed("query_wall", f["wall_ns"] / 1e9)
            if slowest is None or f["wall_ns"] > slowest["wall_ns"]:
                slowest = f
        if slowest is not None:
            self._slowest = slowest
            if self.emitter is not None:
                try:
                    self.emitter.emit_spans(self._spans_for(slowest))
                except Exception:
                    pass
        return folded

    def _fold(self, tr: QueryTrace) -> Dict:
        wall = tr.wall_ns
        t0, t_end = tr.t0_ns, tr.t0_ns + wall
        durs = [0] * N_QSEGS
        clipped = []
        for code, a, b in tr.ivs:
            a = max(a, t0)
            b = min(b, t_end)
            if b > a:
                clipped.append((a, b, code))
                durs[code] += b - a
        clipped.sort()
        cur = t0
        for a, b, _code in clipped:
            if a > cur:
                durs[QSEG_OTHER] += a - cur
            if b > cur:
                cur = b
        if t_end > cur:
            durs[QSEG_OTHER] += t_end - cur
        return {
            "name": tr.name,
            "t0_ns": t0,
            "wall_ns": wall,
            "durs_ns": durs,
            "ivs": clipped,
            "conservation": sum(durs) / wall,
        }

    def _spans_for(self, f: Dict):
        from zipkin_tpu.model import Endpoint, Span
        from zipkin_tpu.obs.selfspans import SERVICE_NAME, _new_id

        bridge_ns = time.time_ns() - time.perf_counter_ns()
        ep = Endpoint.create(service_name=SERVICE_NAME, ip="127.0.0.1")
        trace_id = _new_id()
        root_id = _new_id()
        spans = [Span.create(
            trace_id=trace_id,
            id=root_id,
            name="query_" + f["name"],
            timestamp=max(1, (f["t0_ns"] + bridge_ns) // 1000),
            duration=max(1, f["wall_ns"] // 1000),
            local_endpoint=ep,
            tags={
                "obs.querytrace.kind": f["name"],
                "obs.querytrace.conservation": "%.3f" % f["conservation"],
                "obs.querytrace.wall_us": str(f["wall_ns"] // 1000),
            },
        )]
        for a, b, code in f["ivs"]:
            spans.append(Span.create(
                trace_id=trace_id,
                id=_new_id(),
                parent_id=root_id,
                name=QSEG_NAMES[code],
                timestamp=max(1, (a + bridge_ns) // 1000),
                duration=max(1, (b - a) // 1000),
                local_endpoint=ep,
                tags={"obs.querytrace.segkind": QSEG_KIND[code]},
            ))
        return spans

    # -- reads -----------------------------------------------------------

    def reset(self) -> None:
        """Drop aggregates and pending traces; zero the lock ledger too
        (bench legs and tests want a clean baseline)."""
        with self._lock:
            self._done.clear()
            self.queries = 0
            self.wall_sum_us = 0
            self.seg_count = [0] * N_QSEGS
            self.seg_sum_us = [0] * N_QSEGS
            self.seg_max_us = [0] * N_QSEGS
            self._walls.clear()
            self._cons.clear()
            self._slowest = None
        lock = self.lock_provider() if self.lock_provider else None
        if lock is not None and hasattr(lock, "reset_counters"):
            lock.reset_counters()

    def counters(self) -> Dict:
        with self._lock:
            walls = sorted(self._walls)
            cons = sorted(self._cons)
            segs = {}
            for c in range(N_QSEGS):
                if not self.seg_count[c]:
                    continue
                segs[QSEG_NAMES[c]] = {
                    "kind": QSEG_KIND[c],
                    "count": self.seg_count[c],
                    "sumUs": self.seg_sum_us[c],
                    "maxUs": self.seg_max_us[c],
                }
            out = {
                "queryTraces": self.queries,
                "queryWallSumUs": self.wall_sum_us,
                "queryWallP50Us": _pctl(walls, 0.50),
                "queryWallP99Us": _pctl(walls, 0.99),
                "queryWallMaxUs": walls[-1] if walls else 0,
                "queryConservationP50Milli": int(
                    _pctl(cons, 0.50) * 1000) if cons else 0,
                "querySegments": segs,
            }
        lock = self.lock_provider() if self.lock_provider else None
        if lock is not None and hasattr(lock, "counters"):
            out.update(lock.counters())
        return out

    def waterfall(self) -> Dict:
        """Full dict for the ``/statusz`` queries section."""
        self.stitch()
        with self._lock:
            walls = sorted(self._walls)
            cons = sorted(self._cons)
            wait_us = sum(
                self.seg_sum_us[c] for c in range(N_QSEGS) if c in _QWAIT)
            service_us = sum(
                self.seg_sum_us[c] for c in range(N_QSEGS)
                if c not in _QWAIT)
            body = {
                "enabled": self.enabled,
                "queries": self.queries,
                "wall": {
                    "count": len(walls),
                    "p50Us": _pctl(walls, 0.50),
                    "p99Us": _pctl(walls, 0.99),
                    "maxUs": walls[-1] if walls else 0,
                },
                "conservation": {
                    "p50": round(_pctl(cons, 0.50), 4) if cons else 0.0,
                    "min": round(cons[0], 4) if cons else 0.0,
                    "max": round(cons[-1], 4) if cons else 0.0,
                },
                "waitVsService": {
                    "waitUs": wait_us,
                    "serviceUs": service_us,
                    "waitFraction": round(
                        wait_us / max(1, wait_us + service_us), 4),
                },
                "segments": [
                    {
                        "name": QSEG_NAMES[c],
                        "kind": QSEG_KIND[c],
                        "count": self.seg_count[c],
                        "sumUs": self.seg_sum_us[c],
                        "maxUs": self.seg_max_us[c],
                        "meanUs": round(
                            self.seg_sum_us[c] / self.seg_count[c], 1),
                    }
                    for c in range(N_QSEGS) if self.seg_count[c]
                ],
            }
            slow = self._slowest
            if slow is not None:
                body["slowest"] = {
                    "name": slow["name"],
                    "wallUs": slow["wall_ns"] // 1000,
                    "conservation": round(slow["conservation"], 4),
                    "segments": {
                        QSEG_NAMES[c]: d // 1000
                        for c, d in enumerate(slow["durs_ns"]) if d
                    },
                }
        lock = self.lock_provider() if self.lock_provider else None
        if lock is not None and hasattr(lock, "status"):
            body["lock"] = lock.status()
        return body
