"""Lock-free stage-latency recorder: the pipeline flight recorder core.

Concurrency design (the "Fast Concurrent Data Sketches" split): each
recording thread owns a private ``_LocalHist`` — writers never contend,
never take a lock, never wait. A per-local even/odd ``gen`` counter is
the seqlock: the writer bumps it to odd, mutates its three arrays
(bucket counts, per-stage µs sums, per-stage maxes), and bumps it back
to even. ``snapshot()`` is the compact query side (the SF-sketch-style
export): it copies each local under a gen-stable retry loop — odd or
changed gen means the copy may be torn across the three arrays, so it
re-reads — then merges everything into one immutable ``Snapshot``.
Under CPython the GIL makes each individual list op atomic; the gen
stamp is what makes the *cross-array* view consistent.

Latency buckets are log2 in µs: bucket 0 holds 0 µs, bucket ``b`` holds
``[2^(b-1), 2^b)`` µs, the top bucket is clipped (≈ ≥9 min). Exact
inclusive upper bound of bucket ``b`` is ``(1 << b) - 1`` µs, which is
what the Prometheus ``le`` labels and quantile reads report.

The only work on the record hot path beyond the histogram update is a
single budget comparison; crossing the budget takes the (rare) slow
path: an event dict appended to a bounded ring, plus an optional hook
(installed by ``selfspans.SelfSpanEmitter``) that runs on the recording
thread so it can read request-scoped context vars.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from zipkin_tpu.obs.stages import (
    DEFAULT_BUDGETS_US,
    NUM_STAGES,
    STAGE_INDEX,
    STAGES,
)

NUM_BUCKETS = 31

# A torn read lasts a few bytecodes; retries beyond this mean a writer
# died mid-update (impossible without a killed thread) — take the read.
_TORN_RETRIES = 1000


def bucket_index(dur_s: float) -> int:
    """Bucket for a duration in seconds (µs resolution, rounded)."""
    us = int(dur_s * 1_000_000 + 0.5)
    if us <= 0:
        return 0
    b = us.bit_length()
    return b if b < NUM_BUCKETS else NUM_BUCKETS - 1


def bucket_le_us(b: int) -> int:
    """Exact inclusive upper bound of bucket ``b`` in µs.

    The top bucket is clipped and has no finite bound; callers export
    it as ``+Inf`` (Prometheus) or fall back to the observed max.
    """
    return (1 << b) - 1


class _LocalHist:
    """One writer thread's private histogram block (seqlock-stamped)."""

    __slots__ = ("gen", "counts", "sums", "maxes")

    def __init__(self) -> None:
        self.gen = 0
        self.counts = [0] * (NUM_STAGES * NUM_BUCKETS)
        self.sums = [0] * NUM_STAGES
        self.maxes = [0] * NUM_STAGES


class StageStat:
    """Merged per-stage view inside a :class:`Snapshot`."""

    __slots__ = ("stage", "count", "sum_us", "max_us", "buckets")

    def __init__(self, stage: str, count: int, sum_us: int, max_us: int,
                 buckets: List[int]) -> None:
        self.stage = stage
        self.count = count
        self.sum_us = sum_us
        self.max_us = max_us
        self.buckets = buckets

    def quantile_us(self, q: float) -> int:
        """Upper-bound estimate of the q-quantile in µs.

        Log2-bucket resolution: the true value lies within 2x below the
        returned bound. The top (clipped) bucket and any bucket whose
        bound exceeds the observed max report the max instead.
        """
        if self.count <= 0:
            return 0
        target = q * self.count
        cum = 0
        for b, c in enumerate(self.buckets):
            cum += c
            if c and cum >= target:
                if b >= NUM_BUCKETS - 1:
                    return self.max_us
                return min(bucket_le_us(b), self.max_us)
        return self.max_us

    @property
    def p50_us(self) -> int:
        return self.quantile_us(0.50)

    @property
    def p99_us(self) -> int:
        return self.quantile_us(0.99)


class Snapshot:
    """Immutable merge of every writer's histograms at one generation."""

    __slots__ = ("counts", "sums", "maxes", "generation", "locals_seen")

    def __init__(self, counts: List[int], sums: List[int], maxes: List[int],
                 generation: int, locals_seen: int) -> None:
        self.counts = counts
        self.sums = sums
        self.maxes = maxes
        self.generation = generation
        self.locals_seen = locals_seen

    def stage(self, name: str) -> StageStat:
        idx = STAGE_INDEX[name]
        buckets = self.counts[idx * NUM_BUCKETS:(idx + 1) * NUM_BUCKETS]
        return StageStat(name, sum(buckets), self.sums[idx],
                         self.maxes[idx], buckets)

    def stages(self) -> List[StageStat]:
        return [self.stage(name) for name in STAGES]

    def nonzero(self) -> List[StageStat]:
        return [s for s in self.stages() if s.count]

    @property
    def total_count(self) -> int:
        return sum(self.counts)


class StageRecorder:
    """Process-wide flight recorder; one instance lives at ``obs.RECORDER``."""

    def __init__(self, enabled: bool = True, slow_ring_size: int = 64) -> None:
        self._enabled = bool(enabled)
        self._tl = threading.local()
        self._reg_lock = threading.Lock()  # registration only — never on record()
        self._locals: List[_LocalHist] = []
        self._budget_scale = 1.0
        self._budgets_us: List[float] = [
            float(DEFAULT_BUDGETS_US[s]) for s in STAGES
        ]
        self._slow_ring: deque = deque(maxlen=slow_ring_size)
        self._slow_hook: Optional[Callable[[Dict], None]] = None

    # -- hot path ------------------------------------------------------

    def record(self, stage: str, dur_s: float) -> None:
        """Record one observation of ``stage`` taking ``dur_s`` seconds.

        Wait-free for the writer: no locks, no allocation beyond the
        first call on a thread, one budget compare at the end.
        """
        if not self._enabled:
            return
        idx = STAGE_INDEX[stage]
        us = int(dur_s * 1_000_000 + 0.5)
        if us < 0:
            us = 0
        b = us.bit_length()
        if b >= NUM_BUCKETS:
            b = NUM_BUCKETS - 1
        try:
            h = self._tl.hist
        except AttributeError:
            h = self._new_local()
        h.gen += 1  # odd: local mid-update
        h.counts[idx * NUM_BUCKETS + b] += 1
        h.sums[idx] += us
        if us > h.maxes[idx]:
            h.maxes[idx] = us
        h.gen += 1  # even: stable again
        if us > self._budgets_us[idx]:
            self._slow(stage, us, self._budgets_us[idx])

    def record_relayed(self, stage: str, dur_s: float) -> None:
        """Record a stage wall that was *measured on another thread or
        process* and relayed here (e.g. worker parse/pack/route timings
        riding MP batch messages). Histogram-only: no budget compare, no
        slow ring, no self-span hook — the recording thread's request
        context has nothing to do with where the time was spent, so a
        budget crossing must not emit a self-span B3-linked to it."""
        if not self._enabled:
            return
        idx = STAGE_INDEX[stage]
        us = int(dur_s * 1_000_000 + 0.5)
        if us < 0:
            us = 0
        b = us.bit_length()
        if b >= NUM_BUCKETS:
            b = NUM_BUCKETS - 1
        try:
            h = self._tl.hist
        except AttributeError:
            h = self._new_local()
        h.gen += 1  # odd: local mid-update
        h.counts[idx * NUM_BUCKETS + b] += 1
        h.sums[idx] += us
        if us > h.maxes[idx]:
            h.maxes[idx] = us
        h.gen += 1  # even: stable again

    def _new_local(self) -> _LocalHist:
        h = _LocalHist()
        with self._reg_lock:
            self._locals.append(h)
        self._tl.hist = h
        return h

    # -- slow path -----------------------------------------------------

    def _slow(self, stage: str, us: int, budget_us: float) -> None:
        event = {
            "stage": stage,
            "durUs": us,
            "budgetUs": int(budget_us),
            "tsUs": int(time.time() * 1_000_000),
            "thread": threading.current_thread().name,
        }
        hook = self._slow_hook
        if hook is not None:
            try:
                hook(event)  # may enrich the event with B3 ids
            except Exception:
                pass
        self._slow_ring.append(event)

    def slow_events(self) -> List[Dict]:
        """Recent over-budget events, oldest first (bounded ring)."""
        return list(self._slow_ring)

    def set_slow_hook(self, hook: Optional[Callable[[Dict], None]]) -> None:
        self._slow_hook = hook

    # -- configuration -------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, on: bool) -> None:
        self._enabled = bool(on)

    @property
    def budget_scale(self) -> float:
        return self._budget_scale

    def set_budget_scale(self, scale: float) -> None:
        self._budget_scale = float(scale)
        self._budgets_us = [
            DEFAULT_BUDGETS_US[s] * self._budget_scale for s in STAGES
        ]

    def budget_us(self, stage: str) -> float:
        return self._budgets_us[STAGE_INDEX[stage]]

    @property
    def locals_count(self) -> int:
        with self._reg_lock:
            return len(self._locals)

    # -- query side ----------------------------------------------------

    def snapshot(self) -> Snapshot:
        """Merge every writer's local into one torn-read-free view."""
        with self._reg_lock:
            locals_ = list(self._locals)
        counts = [0] * (NUM_STAGES * NUM_BUCKETS)
        sums = [0] * NUM_STAGES
        maxes = [0] * NUM_STAGES
        generation = 0
        for h in locals_:
            c = h.counts
            s = h.sums
            m = h.maxes
            g1 = -1
            for _ in range(_TORN_RETRIES):
                g1 = h.gen
                if g1 & 1:
                    continue
                c = h.counts[:]
                s = h.sums[:]
                m = h.maxes[:]
                if h.gen == g1:
                    break
            generation += max(g1, 0)
            for i in range(NUM_STAGES * NUM_BUCKETS):
                counts[i] += c[i]
            for i in range(NUM_STAGES):
                sums[i] += s[i]
                if m[i] > maxes[i]:
                    maxes[i] = m[i]
        return Snapshot(counts, sums, maxes, generation, len(locals_))

    def measure_overhead(self, n: int = 2000) -> float:
        """ns per record(), measured against a scratch recorder so the
        published histograms are not polluted by the self-measurement."""
        scratch = StageRecorder(enabled=True, slow_ring_size=1)
        scratch.set_budget_scale(float("inf"))
        rec = scratch.record
        t0 = time.perf_counter_ns()
        for _ in range(n):
            rec("parse", 9.9e-07)
        dt = time.perf_counter_ns() - t0
        return dt / max(1, n)

    def reset(self) -> None:
        """Zero all histograms and the slow ring. Test helper — callers
        must be quiesced (no concurrent writers)."""
        with self._reg_lock:
            locals_ = list(self._locals)
        for h in locals_:
            h.gen += 1
            h.counts = [0] * (NUM_STAGES * NUM_BUCKETS)
            h.sums = [0] * NUM_STAGES
            h.maxes = [0] * NUM_STAGES
            h.gen += 1
        self._slow_ring.clear()
