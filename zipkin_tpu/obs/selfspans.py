"""Slow-dispatch self-spans: the tracer dogfooding itself.

When a pipeline stage blows its budget, the recorder's slow hook hands
the event to a :class:`SelfSpanEmitter`, which publishes it as an
internal span for service ``zipkin-tpu-pipeline`` through the ordinary
collector path — so a slow fresh read is literally queryable as a
trace in the server's own UI.

B3 linkage: the self-tracing middleware sets :data:`CURRENT_B3` to the
(trace id, span id) of the enclosing HTTP self-span. Context vars
propagate through ``asyncio.to_thread`` (it copies the context), so a
storage stage that stalls while serving a request emits a span parented
under that request's own trace. Stages with no enclosing request
(sampler ticks, snapshot loops, the MP dispatcher) become roots.

The hook runs on the recording thread and only appends to a bounded
deque (GIL-atomic) behind a per-stage rate limit; a daemon drain thread
builds the spans and feeds the collector. The drain thread marks itself
suppressed while accepting so its own over-budget stages cannot re-emit
— the feedback loop is cut at the hook.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextvars import ContextVar
from typing import Dict, Optional, Tuple

from zipkin_tpu.model import Endpoint, Span

# (trace_id, span_id) of the enclosing HTTP self-span, if any.
CURRENT_B3: ContextVar[Optional[Tuple[str, str]]] = ContextVar(
    "zipkin_tpu_obs_b3", default=None
)

SERVICE_NAME = "zipkin-tpu-pipeline"


def _new_id() -> str:
    return "%016x" % int.from_bytes(os.urandom(8), "big")


class SelfSpanEmitter:
    """Drains over-budget stage events into collector-accepted spans."""

    def __init__(self, collector, budget_scale: float = 1.0,
                 min_interval_s: float = 1.0, queue_size: int = 256) -> None:
        self._collector = collector
        self.budget_scale = float(budget_scale)
        self.min_interval_s = float(min_interval_s)
        self._queue: deque = deque(maxlen=queue_size)
        # pre-built spans from other planes (critpath slow-chunk
        # timelines): already Span objects, just need the suppressed
        # collector hand-off the drain thread provides
        self._prebuilt: deque = deque(maxlen=queue_size)
        self._last_emit: Dict[str, float] = {}
        self._suppress = threading.local()
        self._endpoint = Endpoint.create(service_name=SERVICE_NAME,
                                         ip="127.0.0.1")
        self._recorder = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.emitted = 0
        # brownout gate (runtime/overload.py, ISSUE 13): a callable
        # returning True when B1+ is shedding expensive observability.
        # Gated events are counted and DROPPED — the slow ring and
        # /statusz keep recording (they are cheap); only the span
        # emission (a collector write competing with real traffic for
        # the device) goes overboard.
        self.gate = None
        self.shed = 0

    # -- wiring --------------------------------------------------------

    def install(self, recorder) -> None:
        """Arm ``recorder`` with scaled budgets and this emitter's hook."""
        self._recorder = recorder
        recorder.set_budget_scale(self.budget_scale)
        recorder.set_slow_hook(self._on_slow)
        self._thread = threading.Thread(
            target=self._drain_loop, name="obs-selfspans", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        if self._recorder is not None:
            self._recorder.set_slow_hook(None)
            self._recorder.set_budget_scale(1.0)
            self._recorder = None
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    # -- recording-thread side (the recorder's slow hook) --------------

    def _on_slow(self, event: Dict) -> None:
        ctx = CURRENT_B3.get()
        if ctx is not None:
            # Enrich in place: the recorder's ring entry gains the B3
            # ids too, so /statusz shows which trace hit the stall.
            event["traceId"], event["parentId"] = ctx
        if getattr(self._suppress, "on", False):
            return
        gate = self.gate
        if gate is not None and gate():
            self.shed += 1
            return
        now = time.monotonic()
        stage = event["stage"]
        last = self._last_emit.get(stage, 0.0)
        if now - last < self.min_interval_s:
            return
        self._last_emit[stage] = now
        self._queue.append(dict(event))

    def emit_spans(self, spans) -> None:
        """Queue already-built self-spans (e.g. a critpath timeline).

        Bounded append only — safe from any thread; the drain thread
        publishes them under the same suppression guard as slow-stage
        events, so the hand-off cannot re-trigger itself. Subject to
        the same brownout gate as slow-stage events: under B1+ the
        slowest-chunk timelines are shed, not queued.
        """
        gate = self.gate
        if gate is not None and gate():
            self.shed += len(spans)
            return
        for s in spans:
            self._prebuilt.append(s)

    # -- drain-thread side ---------------------------------------------

    def _drain_loop(self) -> None:
        while not self._stop.wait(0.05):
            self.flush()
        self.flush()

    def flush(self) -> int:
        """Publish every queued event now; returns spans emitted."""
        spans = []
        while True:
            try:
                ev = self._queue.popleft()
            except IndexError:
                break
            spans.append(self._span_for(ev))
        while True:
            try:
                spans.append(self._prebuilt.popleft())
            except IndexError:
                break
        if not spans:
            return 0
        self._suppress.on = True
        try:
            self._collector.accept(spans)
        except Exception:
            return 0
        finally:
            self._suppress.on = False
        self.emitted += len(spans)
        return len(spans)

    def _span_for(self, ev: Dict) -> Span:
        dur_us = max(1, int(ev["durUs"]))
        end_us = int(ev["tsUs"])
        return Span.create(
            trace_id=ev.get("traceId") or _new_id(),
            id=_new_id(),
            parent_id=ev.get("parentId"),
            name=ev["stage"],
            timestamp=max(1, end_us - dur_us),
            duration=dur_us,
            local_endpoint=self._endpoint,
            tags={
                "obs.stage": ev["stage"],
                "obs.budget_us": str(ev["budgetUs"]),
                "obs.thread": str(ev.get("thread", "")),
            },
        )
