"""Bounded-memory host shadow of the device sketch plane.

The device plane answers every query off approximate structures
(t-digest percentiles, HLL cardinalities, compacted link matrices,
sampled retention) and nothing in the running system measured whether
those answers were still *correct*. This module is the ground-truth
half of the accuracy observatory: a small host-side shadow fed from
the post-parse ingest path — tapped in ``collector/core.py`` (object
path), ``tpu/store.py`` (sync fast path) and the MP fan-out dispatcher
in ``tpu/mp_ingest.py`` — that keeps EXACT statistics over bounded
sub-streams:

- **Per-service duration reservoirs** (vectorized Algorithm R,
  ``reservoir_k`` values per service): exact durations whose empirical
  quantiles anchor the digest relative-error estimators.
- **Hash-sampled distinct sub-stream** (adaptive / KMV-style sketch,
  ``distinct_k`` trace ids): every trace id whose selection hash falls
  under an adaptive threshold θ is kept *exactly*; the distinct-count
  estimate ``|kept| * 2^32 / θ`` is unbiased with relative standard
  error ≈ 1.2/sqrt(|kept|) — the HLL error oracle.
- **Exact link edges on hash-sampled traces** (``link_rate`` of
  traces, trace-affine so sampled traces are COMPLETE): the shadow
  retains the raw span lanes of each sampled trace and the accuracy
  rollup replays them through the host dependency-linker oracle
  (``internal/dependency_linker.py`` — the same semantics the device
  linker is parity-tested against), giving the recall denominator for
  the device's compacted dependency matrices.
- **Retention tallies**: the shadow re-runs the reference verdict
  (:func:`zipkin_tpu.sampling.reference.host_verdict`) over everything
  it drains and keeps its OWN cumulative seen/kept counts — the
  controller consumes ``HostSampler.take_tallies()`` destructively, so
  bias against the live retention counters needs an independent ledger.

Concurrency / hot-path contract: the three ingest taps only call
``offer_*``, which is an O(1) bounded-deque append (plus a drop
counter) — no parsing, hashing or locking happens on the dispatch
path. All real work runs in :meth:`HostShadow.drain`, called from the
accuracy rollup (``obs/accuracy.py``) on the telemetry ticker thread.
Overflowing the pending queue drops the OLDEST batch and counts it;
the accuracy plane gates its estimators on the observed coverage ratio
so a lossy shadow degrades to "no signal", never to a false alert.

Like ``windows``/``slo``/``device``, this module is imported lazily by
the server — ``import zipkin_tpu.obs`` alone never pays for it. Lint
rule ZT08 rejects any shadow hook reachable from jit/shard_map-traced
code: the shadow is host-side ground truth and must never be traced.
"""

from __future__ import annotations

import math
import threading
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from zipkin_tpu.tpu.columnar import SpanColumns, _hash2_np, _mix32

# Selection salts: distinct from sampling.VERDICT_SALT so the shadow's
# sub-streams are independent of the retention verdicts they audit.
_DISTINCT_SALT = 0x5AD0_5EED
_LINK_SALT = 0x11C4_E11E

_U32_SPACE = float(1 << 32)


def rank_interval(q: float, k: int, z: float = 3.0) -> Tuple[float, float]:
    """z-sigma confidence interval on the RANK of the reservoir's
    q-quantile: a k-sample empirical quantile's rank error is binomial,
    stderr sqrt(q(1-q)/k). The accuracy plane turns this into a VALUE
    interval by evaluating the reservoir at both rank endpoints —
    distribution-free, so the stated bound adapts to the data's local
    density instead of assuming a shape."""
    half = z * math.sqrt(max(q * (1.0 - q), 0.0) / max(k, 1))
    return max(0.0, q - half), min(1.0, q + half)


class _Reservoir:
    """Algorithm R over one service's durations, vectorized per batch.

    Element ``t`` (0-based stream index) replaces a uniformly chosen
    slot ``j in [0, t]`` iff ``j < k`` — numpy fancy assignment applies
    duplicates in order, which reproduces the sequential algorithm
    exactly, so the buffer is a uniform k-sample of the whole stream.
    """

    __slots__ = ("k", "buf", "seen", "_rng")

    def __init__(self, k: int, rng: np.random.Generator) -> None:
        self.k = int(k)
        self.buf = np.empty(self.k, np.float64)
        self.seen = 0
        self._rng = rng

    def add(self, vals: np.ndarray) -> None:
        m = len(vals)
        if not m:
            return
        n0 = self.seen
        fill = min(max(self.k - n0, 0), m)
        if fill:
            self.buf[n0:n0 + fill] = vals[:fill]
        if m > fill:
            t = n0 + np.arange(fill, m, dtype=np.int64)
            j = self._rng.integers(0, t + 1)
            sel = j < self.k
            self.buf[j[sel]] = vals[fill:][sel]
        self.seen = n0 + m

    def values(self) -> np.ndarray:
        return self.buf[: min(self.seen, self.k)]

    def quantile(self, q: float) -> float:
        vals = self.values()
        if not len(vals):
            return 0.0
        return float(np.quantile(vals, q))

    def quantile_interval(self, q: float, z: float = 3.0) -> Tuple[float, float]:
        """(lo, hi) VALUE interval for the q-quantile at z sigmas of
        rank noise — empty reservoirs return (0, 0)."""
        vals = self.values()
        if not len(vals):
            return 0.0, 0.0
        q_lo, q_hi = rank_interval(q, len(vals), z)
        pair = np.quantile(vals, [q_lo, q_hi])
        return float(pair[0]), float(pair[1])


class _DistinctSketch:
    """Adaptive hash-sampled distinct counter (KMV / Wegman sampling).

    Keeps EVERY trace id whose selection hash lands below θ; when the
    kept set outgrows ``k``, θ halves and the set is re-filtered — an
    exact distinct count over a uniform 1-in-(2^32/θ) sub-stream. The
    estimate ``|kept| * 2^32/θ`` is unbiased; relative standard error
    ≈ 1.2/sqrt(|kept|) (Flajolet's adaptive-sampling analysis).
    """

    __slots__ = ("k", "ids", "theta")

    def __init__(self, k: int) -> None:
        self.k = int(k)
        self.ids = np.empty(0, np.uint64)
        self.theta = 1 << 32  # full stream until first saturation

    @staticmethod
    def _sel_hash(ids: np.ndarray) -> np.ndarray:
        tl0 = (ids & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        tl1 = (ids >> np.uint64(32)).astype(np.uint32)
        return _mix32(_hash2_np(tl0, tl1) ^ np.uint32(_DISTINCT_SALT))

    def add(self, ids: np.ndarray) -> None:
        if not len(ids):
            return
        ids = ids.astype(np.uint64)
        cand = ids[self._sel_hash(ids).astype(np.uint64) < np.uint64(self.theta)]
        if len(cand):
            self.ids = np.union1d(self.ids, cand)
        while len(self.ids) > self.k:
            self.theta //= 2
            keep = self._sel_hash(self.ids).astype(np.uint64) < np.uint64(self.theta)
            self.ids = self.ids[keep]

    def estimate(self) -> float:
        return len(self.ids) * (_U32_SPACE / self.theta)

    def rel_bound(self, z: float = 3.0) -> float:
        """z-sigma relative error bound of the estimate itself: zero
        while the sketch is still exact (θ never halved)."""
        if self.theta >= (1 << 32):
            return 0.0
        return z * 1.2 / math.sqrt(max(len(self.ids), 1))


class HostShadow:
    """The bounded-memory ground-truth shadow (one per storage)."""

    def __init__(
        self,
        *,
        reservoir_k: int = 512,
        distinct_k: int = 4096,
        link_rate: float = 0.125,
        pending_max: int = 512,
        max_services: int = 1 << 16,
        max_link_traces: int = 256,
        max_link_spans: int = 512,
        seed: int = 0xACC0,
        sampler_ref: Optional[Callable[[], object]] = None,
        svc_resolver: Optional[Callable[[str], Optional[int]]] = None,
        bucket_minutes: int = 0,
        window_slots: int = 8,
    ) -> None:
        self.reservoir_k = int(reservoir_k)
        self.distinct_k = int(distinct_k)
        self.link_rate = float(link_rate)
        self._link_theta = np.uint32(
            min(int(self.link_rate * _U32_SPACE), (1 << 32) - 1)
        )
        self.pending_max = int(pending_max)
        self.max_services = int(max_services)
        self.max_link_traces = int(max_link_traces)
        self.max_link_spans = int(max_link_spans)
        self._seed = int(seed)
        # sampler_ref returns the CURRENT HostSampler (or None): the
        # aggregator can be swapped wholesale (clear/restore), so the
        # shadow must not pin one instance.
        self._sampler_ref = sampler_ref or (lambda: None)
        self._svc_resolver = svc_resolver or (lambda name: None)
        # windowed ground truth (ISSUE 15): when bucket_minutes > 0 the
        # shadow also keeps PER-TIME-BUCKET exact sub-streams — a global
        # duration reservoir and a KMV distinct sketch per epoch, a ring
        # of the most recent window_slots epochs — so the accuracy plane
        # can audit the time tier's sealed segments the same way the
        # cumulative estimators audit the all-time sketches.
        self.bucket_minutes = int(bucket_minutes)
        self.window_slots = int(window_slots)
        self._pending: deque = deque()
        self._dropped_batches = 0
        self._offered_batches = 0
        self._lock = threading.Lock()
        self._reset_locked()

    def _reset_locked(self) -> None:
        self._rng = np.random.default_rng(self._seed)
        self._reservoirs: Dict[int, _Reservoir] = {}
        self._distinct = _DistinctSketch(self.distinct_k)
        # sampled-trace span lanes: trace id64 -> list of
        # (s0, s1, p0, p1, shared, kind, svc, rsvc, err) tuples; the
        # accuracy rollup replays these through the host linker oracle
        self._link_traces: Dict[int, List[tuple]] = {}
        self._seen_by_svc: Dict[int, int] = {}
        self._total_seen = 0
        self._ret_seen = 0
        self._ret_kept = 0
        # per-epoch windowed sub-streams, oldest-first insertion order
        self._win_res: "OrderedDict[int, _Reservoir]" = OrderedDict()
        self._win_distinct: "OrderedDict[int, _DistinctSketch]" = OrderedDict()

    def reset(self) -> None:
        """Start a fresh shadow window (e.g. after the operator rotates
        retention / clears device state): drop every sub-stream AND the
        pending queue so the next rollup compares like with like."""
        with self._lock:
            self._pending.clear()
            self._reset_locked()

    # -- taps (O(1), called from the ingest paths) ---------------------

    def offer_cols(self, cols: SpanColumns) -> None:
        """Tap for the sync fast path: one packed columnar batch."""
        self._offer(("cols", cols))

    def offer_fused(self, fused: np.ndarray) -> None:
        """Tap for the MP dispatcher: one routed wire image (the
        dispatcher's own copy — safe to hold a reference)."""
        self._offer(("fused", fused))

    def offer_spans(self, spans) -> None:
        """Tap for the object path: already-decoded Span objects."""
        self._offer(("spans", list(spans)))

    def _offer(self, item) -> None:
        # append is atomic under the GIL; the drop check races only
        # against other offers, so the counter is approximate by at
        # most the number of concurrently offering threads.
        if len(self._pending) >= self.pending_max:
            try:
                self._pending.popleft()
            except IndexError:
                pass
            self._dropped_batches += 1
        self._offered_batches += 1
        self._pending.append(item)

    # -- drain (rollup cadence, off the dispatch path) -----------------

    def drain(self) -> int:
        """Fold every pending batch into the shadow; returns batches
        processed. Runs on the accuracy-rollup thread."""
        n = 0
        with self._lock:
            while True:
                try:
                    kind, payload = self._pending.popleft()
                except IndexError:
                    break
                if kind == "cols":
                    self._fold_cols(payload)
                elif kind == "fused":
                    self._fold_fused(payload)
                else:
                    self._fold_spans(payload)
                n += 1
        return n

    def _fold_cols(self, cols: SpanColumns) -> None:
        self._fold_lanes(
            cols.trace_h, cols.tl0, cols.tl1, cols.svc, cols.rsvc,
            cols.key, cols.dur, cols.has_dur, cols.err, cols.valid,
            cols.s0, cols.s1, cols.p0, cols.p1, cols.shared, cols.kind,
            ts=cols.ts_min,
        )

    def _fold_fused(self, fused: np.ndarray) -> None:
        f = np.asarray(fused)
        sr = f[..., 9, :].reshape(-1)
        kf = f[..., 10, :].reshape(-1)
        self._fold_lanes(
            f[..., 0, :].reshape(-1),
            f[..., 1, :].reshape(-1),
            f[..., 2, :].reshape(-1),
            (sr >> np.uint32(16)).astype(np.int64),
            (sr & np.uint32(0xFFFF)).astype(np.int64),
            (kf >> np.uint32(8)).astype(np.int64),
            f[..., 7, :].reshape(-1),
            (kf & np.uint32(8)) != 0,
            (kf & np.uint32(4)) != 0,
            (kf & np.uint32(1)) != 0,
            f[..., 3, :].reshape(-1),
            f[..., 4, :].reshape(-1),
            f[..., 5, :].reshape(-1),
            f[..., 6, :].reshape(-1),
            (kf & np.uint32(2)) != 0,
            ((kf >> np.uint32(4)) & np.uint32(0xF)).astype(np.int64),
            ts=f[..., 8, :].reshape(-1),
        )

    def _fold_spans(self, spans: List) -> None:
        """Object-path batches arrive as Span objects: rebuild the lanes
        the vectorized fold needs. The object path is the low-volume
        compatibility path, so a per-span Python pass here (on the
        rollup thread) is within budget. Spans whose service has not
        been interned yet are skipped — the device has not attributed
        them to a slot either. Retention verdicts are NOT re-run for
        this path (the (service, name) key id is not resolvable without
        interning, which a read-side plane must never do)."""
        from zipkin_tpu.internal.hex import normalize_trace_id
        from zipkin_tpu.tpu.columnar import KIND_TO_ID

        n = len(spans)
        if not n:
            return
        tl0 = np.zeros(n, np.uint32)
        tl1 = np.zeros(n, np.uint32)
        th0 = np.zeros(n, np.uint32)
        th1 = np.zeros(n, np.uint32)
        s0 = np.zeros(n, np.uint32)
        s1 = np.zeros(n, np.uint32)
        p0 = np.zeros(n, np.uint32)
        p1 = np.zeros(n, np.uint32)
        shared = np.zeros(n, bool)
        kind = np.zeros(n, np.int64)
        svc = np.zeros(n, np.int64)
        rsvc = np.zeros(n, np.int64)
        dur = np.zeros(n, np.uint32)
        has_dur = np.zeros(n, bool)
        err = np.zeros(n, bool)
        valid = np.zeros(n, bool)
        ts = np.zeros(n, np.uint32)
        for i, s in enumerate(spans):
            sid = self._svc_resolver(s.local_service_name) if s.local_service_name else None
            if not sid:
                continue
            try:
                full = int(normalize_trace_id(s.trace_id), 16)
                sid64 = int(s.id, 16)
                pid64 = int(s.parent_id, 16) if s.parent_id else 0
            except (TypeError, ValueError):
                continue
            lo64, hi64 = full & ((1 << 64) - 1), full >> 64
            tl0[i], tl1[i] = lo64 & 0xFFFFFFFF, lo64 >> 32
            th0[i], th1[i] = hi64 & 0xFFFFFFFF, hi64 >> 32
            s0[i], s1[i] = sid64 & 0xFFFFFFFF, sid64 >> 32
            p0[i], p1[i] = pid64 & 0xFFFFFFFF, pid64 >> 32
            shared[i] = bool(s.shared)
            kind[i] = KIND_TO_ID.get(s.kind, 0)
            svc[i] = sid
            rid = self._svc_resolver(s.remote_service_name) if s.remote_service_name else None
            rsvc[i] = rid or 0
            d = s.duration or 0
            dur[i] = min(int(d), 0xFFFFFFFF)
            has_dur[i] = d > 0
            err[i] = "error" in (s.tags or {})
            ts[i] = min(int(s.timestamp or 0) // 60_000_000, 0xFFFFFFFF)
            valid[i] = True
        trace_h = _hash2_np(_hash2_np(tl0, tl1), _hash2_np(th0, th1))
        self._fold_lanes(
            trace_h, tl0, tl1, svc, rsvc, None, dur, has_dur, err, valid,
            s0, s1, p0, p1, shared, kind, ts=ts,
        )

    def _fold_lanes(
        self, trace_h, tl0, tl1, svc, rsvc, key, dur, has_dur, err, valid,
        s0, s1, p0, p1, shared, kind, ts=None,
    ) -> None:
        v = np.asarray(valid, bool)
        if not v.any():
            return
        trace_h = np.asarray(trace_h, np.uint32)[v]
        tl0 = np.asarray(tl0)[v]
        tl1 = np.asarray(tl1)[v]
        svc = np.asarray(svc, np.int64)[v]
        rsvc = np.asarray(rsvc, np.int64)[v]
        dur = np.asarray(dur, np.uint32)[v]
        has_dur = np.asarray(has_dur, bool)[v]
        err = np.asarray(err, bool)[v]
        s0 = np.asarray(s0, np.uint32)[v]
        s1 = np.asarray(s1, np.uint32)[v]
        p0 = np.asarray(p0, np.uint32)[v]
        p1 = np.asarray(p1, np.uint32)[v]
        shared = np.asarray(shared, bool)[v]
        kind = np.asarray(kind, np.int64)[v]
        svc = np.clip(svc, 0, self.max_services - 1)
        rsvc = np.clip(rsvc, 0, self.max_services - 1)
        self._total_seen += len(svc)
        # per-service seen tallies + duration reservoirs
        uniq, counts = np.unique(svc, return_counts=True)
        for s, c in zip(uniq.tolist(), counts.tolist()):
            self._seen_by_svc[s] = self._seen_by_svc.get(s, 0) + c
        hd = has_dur
        if hd.any():
            dsvc = svc[hd]
            ddur = dur[hd].astype(np.float64)
            for s in np.unique(dsvc).tolist():
                res = self._reservoirs.get(s)
                if res is None:
                    res = self._reservoirs[s] = _Reservoir(
                        self.reservoir_k, self._rng
                    )
                res.add(ddur[dsvc == s])
        # distinct sub-stream (trace identity = low-64 id lanes)
        ids = (tl1.astype(np.uint64) << np.uint64(32)) | tl0.astype(np.uint64)
        self._distinct.add(np.unique(ids))
        # per-time-bucket windowed sub-streams (ISSUE 15): the exact
        # mirrors of the device's tb_* current-bucket sketches, keyed by
        # the SAME epoch = ts_min // bucket_minutes the ingest step uses
        if self.bucket_minutes > 0 and ts is not None:
            eps = (
                np.asarray(ts, np.int64)[v] // self.bucket_minutes
            )
            for e in np.unique(eps).tolist():
                in_e = eps == e
                res = self._win_res.get(e)
                if res is None:
                    # only track epochs newer than anything evicted —
                    # a late straggler for a dropped epoch must not
                    # resurrect it with a near-empty (biased) reservoir
                    if (
                        len(self._win_res) >= self.window_slots
                        and e < next(iter(self._win_res))
                    ):
                        continue
                    res = self._win_res[e] = _Reservoir(
                        self.reservoir_k, self._rng
                    )
                    self._win_distinct[e] = _DistinctSketch(self.distinct_k)
                sel_d = in_e & hd
                if sel_d.any():
                    res.add(dur[sel_d].astype(np.float64))
                self._win_distinct[e].add(np.unique(ids[in_e]))
            while len(self._win_res) > self.window_slots:
                old, _ = self._win_res.popitem(last=False)
                self._win_distinct.pop(old, None)
            # keep insertion order == epoch order for the eviction rule
            if len(self._win_res) > 1:
                order = sorted(self._win_res)
                if list(self._win_res) != order:
                    self._win_res = OrderedDict(
                        (e, self._win_res[e]) for e in order
                    )
                    self._win_distinct = OrderedDict(
                        (e, self._win_distinct[e])
                        for e in order if e in self._win_distinct
                    )
        # sampled-trace span lanes for the host linker oracle: trace-
        # affine selection (pure function of the trace hash) keeps every
        # span of a sampled trace across batches and ingest paths
        sel = _mix32(trace_h ^ np.uint32(_LINK_SALT)) < self._link_theta
        for i in np.nonzero(sel)[0].tolist():
            tid = int(ids[i])
            rec = self._link_traces.get(tid)
            if rec is None:
                if len(self._link_traces) >= self.max_link_traces:
                    continue
                rec = self._link_traces[tid] = []
            if len(rec) < self.max_link_spans:
                rec.append((
                    int(s0[i]), int(s1[i]), int(p0[i]), int(p1[i]),
                    bool(shared[i]), int(kind[i]), int(svc[i]),
                    int(rsvc[i]), bool(err[i]),
                ))
        # retention verdicts vs the sampler's published tables
        if key is not None:
            sampler = self._sampler_ref()
            if sampler is not None:
                from zipkin_tpu.sampling.reference import host_verdict

                key = np.clip(np.asarray(key, np.int64)[v], 0, None)
                keep = host_verdict(
                    trace_h, svc, rsvc, key, dur, hd, err,
                    np.ones(len(svc), bool),
                    sampler.rate, sampler.tail, sampler.link,
                    sampler.rare_min,
                )
                self._ret_seen += len(svc)
                self._ret_kept += int(keep.sum())

    # -- query side (accuracy rollup + statusz) ------------------------

    def services(self) -> List[int]:
        with self._lock:
            return sorted(self._reservoirs)

    def reservoir(self, svc_id: int) -> Optional[_Reservoir]:
        with self._lock:
            return self._reservoirs.get(svc_id)

    def distinct_estimate(self) -> float:
        with self._lock:
            return self._distinct.estimate()

    def distinct_bound(self, z: float = 3.0) -> float:
        with self._lock:
            return self._distinct.rel_bound(z)

    def link_traces(self) -> Dict[int, List[tuple]]:
        """Snapshot of the sampled traces' span lanes: trace id64 ->
        [(s0, s1, p0, p1, shared, kind, svc, rsvc, err), ...]."""
        with self._lock:
            return {tid: list(rec) for tid, rec in self._link_traces.items()}

    def retention(self) -> Tuple[int, int]:
        """(seen, kept) cumulative shadow verdict tallies."""
        with self._lock:
            return self._ret_seen, self._ret_kept

    def window_epochs(self) -> List[int]:
        """Epochs (ts_min // bucket_minutes) the windowed shadow holds,
        ascending — empty when the windowed shadow is off."""
        with self._lock:
            return sorted(self._win_res)

    def window_reservoir(self, epoch: int) -> Optional[_Reservoir]:
        with self._lock:
            return self._win_res.get(epoch)

    def window_distinct(self, epoch: int) -> Optional[_DistinctSketch]:
        with self._lock:
            return self._win_distinct.get(epoch)

    def seen_by_service(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._seen_by_svc)

    @property
    def total_seen(self) -> int:
        return self._total_seen

    @property
    def dropped_batches(self) -> int:
        return self._dropped_batches

    def counters(self) -> Dict[str, float]:
        with self._lock:
            return {
                "shadowSpans": self._total_seen,
                "shadowServices": len(self._reservoirs),
                "shadowDistinctKept": len(self._distinct.ids),
                "shadowDistinctTheta": self._distinct.theta / _U32_SPACE,
                "shadowLinkTraces": len(self._link_traces),
                "shadowWindowEpochs": len(self._win_res),
                "shadowPending": len(self._pending),
                "shadowOfferedBatches": self._offered_batches,
                "shadowDroppedBatches": self._dropped_batches,
            }
