"""SLO burn-rate watchdog over the windowed telemetry plane.

Declarative specs, Google-SRE-style multi-window evaluation: each
:class:`SloSpec` names an error-budget objective and two lookbacks; the
watchdog computes the **burn rate** (observed bad fraction divided by
the budgeted bad fraction ``1 - objective``) over both windows and
trips only when *both* burn — the short window gives fast reaction, the
long window filters blips. A tripped alert holds until both windows
recover (hysteresis for free: the long window keeps burning until the
bad events age out of it).

Spec grammar (three kinds):

- ``latency``: ``stage`` + ``threshold_us`` against the windowed stage
  histogram. An observation counts *bad* when its bucket's inclusive
  upper bound exceeds the threshold — the same upper-bound convention
  the quantile reads use, so "p99 < 50 ms" is expressed as objective
  0.99 with threshold_us 50_000.
- ``ratio``: ``bad`` counter delta over either ``total`` (exact
  denominator) or ``bad + good`` (when no total counter exists).
- ``gauge``: instantaneous counter value against ``limit``; burn is
  ``value / limit`` on both windows and the alert threshold is 1.0
  (a gauge is not rate-like, so the burn multiplier does not apply).

Windows with no events do not burn: an idle system is in SLO.
Evaluation is driven by the telemetry ticker (the watchdog subscribes
to ``on_tick``) so trips land within one tick of the burn being
visible; read paths may also call :meth:`SloWatchdog.evaluate`.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Sequence

from zipkin_tpu.obs.recorder import bucket_le_us
from zipkin_tpu.obs.windows import WindowedTelemetry, WindowStats


@dataclasses.dataclass(frozen=True)
class SloSpec:
    name: str
    kind: str                  # "latency" | "ratio" | "gauge"
    short_s: float = 60.0
    long_s: float = 300.0
    burn_threshold: float = 2.0
    objective: float = 0.99    # good-fraction target (latency/ratio)
    # latency
    stage: str = ""
    threshold_us: int = 0
    # ratio
    bad: str = ""
    good: str = ""
    total: str = ""
    # gauge
    gauge: str = ""
    limit: float = 0.0

    def __post_init__(self) -> None:
        if self.kind not in ("latency", "ratio", "gauge"):
            raise ValueError(f"unknown SLO kind: {self.kind!r}")
        if self.kind == "latency" and not self.stage:
            raise ValueError(f"{self.name}: latency SLO needs a stage")
        if self.kind == "ratio" and not (self.bad and (self.good
                                                       or self.total)):
            raise ValueError(f"{self.name}: ratio SLO needs bad+good/total")
        if self.kind == "gauge" and not (self.gauge and self.limit > 0):
            raise ValueError(f"{self.name}: gauge SLO needs gauge+limit")


def default_specs(short_s: float = 60.0, long_s: float = 300.0,
                  burn_threshold: float = 2.0) -> List[SloSpec]:
    """The four production SLOs from the north star, snapshot age, plus
    the accuracy-drift gauges published by the accuracy observatory
    (obs/accuracy.py) and the PR 2 HLL operating-envelope breach ratio.

    The accuracy gauges default to 0.0 (and are coverage-gated to 0.0
    when the shadow is lossy), so these specs are inert until a rollup
    actually measures drift — an idle or shadowless deployment stays in
    SLO. The specs watch the DRIFT gauges — relative error in excess
    of the noise the accuracy plane's own ground truth carries (see
    obs/accuracy.py) — not the raw relative errors: a heavy-tailed
    stream makes the raw p99 comparison noisy even when the digest is
    healthy, while an undersized digest shows up as drift the noise
    bound cannot explain. Limits mirror the sketches' design envelopes
    with headroom: t-digest C=64 claims ~0.5% p99 error, HLL p=14
    claims ~0.8% — a sustained 20% / 15% of UNEXPLAINED relative error
    means the structure is mis-sized or broken, not noisy."""
    kw = dict(short_s=short_s, long_s=long_s, burn_threshold=burn_threshold)
    return [
        SloSpec("ingest_wire_to_ack", "ratio", objective=0.999,
                bad="collectorMessagesDropped", total="collectorMessages",
                **kw),
        SloSpec("query_fresh_p99", "latency", objective=0.99,
                stage="query_fresh", threshold_us=50_000, **kw),
        SloSpec("durability_wal_fsync", "latency", objective=0.99,
                stage="wal_fsync", threshold_us=100_000, **kw),
        SloSpec("backpressure_429", "ratio", objective=0.99,
                bad="mpRejected", good="mpAccepted", **kw),
        SloSpec("snapshot_age", "gauge", gauge="snapshotAgeS",
                limit=1800.0, **kw),
        # Disk-exhaustion degraded mode (ISSUE 13): storage flips this
        # 0/1 gauge the instant a durable tier (WAL append or snapshot
        # commit) enters ENOSPC-degraded mode — acked spans are not
        # crash-safe until a snapshot re-covers the gap, which is a
        # page, not a dashboard curiosity. A 0/1 gauge against limit
        # 1.0 makes the trip immediate and the clear exact.
        SloSpec("durability_at_risk", "gauge", gauge="durabilityAtRisk",
                limit=1.0, **kw),
        SloSpec("digest_p99_relerr", "gauge",
                gauge="accuracyDigestP99Drift", limit=0.20, **kw),
        SloSpec("hll_relerr", "gauge",
                gauge="accuracyHllDrift", limit=0.15, **kw),
        # Windowed accuracy (ISSUE 15): the same drift-over-noise
        # semantics evaluated against the time tier's newest sealed
        # bucket — per-bucket digest p99 vs the bucket's exact shadow
        # reservoir, per-bucket HLL vs its KMV sketch. Same limits as
        # the cumulative pair: a sealed segment is the SAME sketch
        # structure, so sustained unexplained error past them means the
        # seal/merge path (not sampling noise) is corrupting windows.
        SloSpec("windowed_digest_p99_relerr", "gauge",
                gauge="accuracyWindowedDigestP99Drift", limit=0.20, **kw),
        SloSpec("windowed_hll_relerr", "gauge",
                gauge="accuracyWindowedHllDrift", limit=0.15, **kw),
        SloSpec("hll_envelope", "ratio", objective=0.99,
                bad="hllEnvelopeExceeded", total="hostTransfers", **kw),
        # Critical-path tracer (obs/critpath.py): wire-to-durable is the
        # END of the ingest story — boundary read through wal fsync — a
        # strictly longer interval than wire-to-ack's 202-on-enqueue.
        # 5 s covers the dispatcher's coalescing window plus a device
        # feed with headroom; sustained excess means the fan-out tier is
        # backed up, not merely busy.
        SloSpec("ingest_wire_to_durable", "latency", objective=0.99,
                stage="wire_to_durable", threshold_us=5_000_000, **kw),
        # Little's-law queue saturation gauge from the stitcher: lambda
        # x mean(queue-wait + slot-wait) over total queue capacity.
        # Zeroed on idle ticks, so a stale reading cannot hold an alert.
        SloSpec("ingest_queue_saturation", "gauge",
                gauge="critpathQueueSaturation", limit=0.9, **kw),
        # Query-plane observatory (obs/querytrace.py, ISSUE 12): the
        # instrumented aggregator lock relays every outermost wait into
        # query_lock_wait — sustained waits past 10 ms mean readers are
        # queueing on the lock again, i.e. traffic is bypassing the
        # epoch-published read mirror (tpu/mirror.py) that took the read
        # path off the lock (per-request staleness_ms=0 floods, or
        # TPU_READ_MIRROR=false). query_wall is the stitched whole-query
        # critical path, so this spec IS the "p99 < 50 ms under
        # concurrent readers" target measured from inside the pipeline
        # rather than from a benchmark harness.
        SloSpec("query_lock_wait", "latency", objective=0.99,
                stage="query_lock_wait", threshold_us=10_000, **kw),
        SloSpec("query_p99_concurrent", "latency", objective=0.99,
                stage="query_wall", threshold_us=50_000, **kw),
        # Epoch-published read mirror (tpu/mirror.py, ISSUE 14): the
        # staleness contract is the price of lock-free serving — mirror
        # answers may lag the live aggregator by up to the publish
        # cadence. mirrorServeAgeMs is the age-at-serve gauge (worst
        # serve in flight resets per read); the limit mirrors the
        # TPU_MIRROR_MAX_STALE_MS default, so a trip means the publisher
        # stopped cutting epochs (ticker dead, publish erroring) while
        # reads kept serving ever-older data — page before dashboards
        # quietly freeze in time.
        SloSpec("query_mirror_staleness", "gauge",
                gauge="mirrorServeAgeMs", limit=5000.0, **kw),
        # Scale-out reader processes (serving/, ISSUE 19): the same
        # staleness contract one process boundary further out —
        # readerServeAgeMs is the worst live reader's age-at-serve,
        # relayed through the segment heartbeat stripes into
        # ingest_counters. Inert at 0.0 with no readers attached; a
        # trip with readers attached means the segment publisher
        # stopped landing epochs (sink erroring, payload overflowing)
        # while reader processes kept serving the last one.
        SloSpec("reader_staleness", "gauge",
                gauge="readerServeAgeMs", limit=5000.0, **kw),
    ]


def tenant_specs(tenant: str, short_s: float = 60.0, long_s: float = 300.0,
                 burn_threshold: float = 2.0,
                 objective: float = 0.99) -> List[SloSpec]:
    """Tenant-scoped SLOs (ISSUE 18): shed ratio over ONE tenant's own
    offered/shed counters (published per-tenant by the admission table
    via the overload controller's counter export), so tenant A's error
    budget cannot be consumed by tenant B's flood — the SLO twin of the
    isolation property itself. Instantiated per TPU_TENANT_SLO entry
    using the same PR 9 grammar as :func:`default_specs`; counter name
    suffixes use the tenant's prometheus-safe slug."""
    from zipkin_tpu.runtime.tenant import tenant_slug

    slug = tenant_slug(tenant)
    kw = dict(short_s=short_s, long_s=long_s, burn_threshold=burn_threshold)
    return [
        SloSpec(f"tenant_{slug}_shed_ratio", "ratio", objective=objective,
                bad=f"tenantShed_{slug}", total=f"tenantOffered_{slug}",
                **kw),
    ]


class SloWatchdog:
    """Evaluates specs against a :class:`WindowedTelemetry` plane."""

    def __init__(self, windows: WindowedTelemetry,
                 specs: Optional[Sequence[SloSpec]] = None,
                 subscribe: bool = True) -> None:
        self._win = windows
        self.specs: List[SloSpec] = list(specs if specs is not None
                                         else default_specs())
        self._lock = threading.Lock()
        self._alerts: Dict[str, bool] = {s.name: False for s in self.specs}
        self._verdicts: List[Dict] = []
        self.trips = 0
        self.clears = 0
        # on_trip(name, verdict) hooks fire once per alert transition
        # into the tripped state — incident capture registers here.
        self.on_trip: List = []
        if subscribe:
            windows.on_tick(lambda _w: self.evaluate())

    def add_spec(self, spec: SloSpec) -> None:
        """Register one more spec after construction (tenant-scoped
        instances, ISSUE 18). Idempotent by name — re-adding an
        existing spec is a no-op, so wiring code can be re-entered."""
        with self._lock:
            if any(s.name == spec.name for s in self.specs):
                return
            self.specs.append(spec)
            self._alerts.setdefault(spec.name, False)

    # -- burn math -----------------------------------------------------

    @staticmethod
    def _bad_fraction_latency(spec: SloSpec, w: WindowStats) -> tuple:
        stat = w.stage(spec.stage)
        if stat.count <= 0:
            return 0.0, 0
        bad = sum(c for b, c in enumerate(stat.buckets)
                  if c and bucket_le_us(b) > spec.threshold_us)
        return bad / stat.count, stat.count

    @staticmethod
    def _bad_fraction_ratio(spec: SloSpec, w: WindowStats) -> tuple:
        deltas = w.counter_deltas
        bad = max(0.0, deltas.get(spec.bad, 0.0))
        if spec.total:
            total = max(0.0, deltas.get(spec.total, 0.0))
        else:
            total = bad + max(0.0, deltas.get(spec.good, 0.0))
        if total <= 0:
            return 0.0, 0
        return min(1.0, bad / total), int(total)

    def _burn(self, spec: SloSpec, w: WindowStats) -> Dict:
        if spec.kind == "gauge":
            value = self._win.current_counters().get(spec.gauge, 0.0)
            return {"burn": value / spec.limit, "events": 1,
                    "value": value}
        if spec.kind == "latency":
            frac, events = self._bad_fraction_latency(spec, w)
        else:
            frac, events = self._bad_fraction_ratio(spec, w)
        budget = max(1e-9, 1.0 - spec.objective)
        return {"burn": frac / budget, "events": events,
                "badFraction": round(frac, 6)}

    # -- evaluation ----------------------------------------------------

    def evaluate(self) -> List[Dict]:
        """Evaluate every spec; returns (and caches) the verdict list."""
        verdicts: List[Dict] = []
        tripped: List[int] = []  # verdict indexes that transitioned
        with self._lock:
            for spec in self.specs:
                short = self._burn(spec, self._win.window(spec.short_s))
                long_ = self._burn(spec, self._win.window(spec.long_s))
                thr = 1.0 if spec.kind == "gauge" else spec.burn_threshold
                burning = short["burn"] >= thr and long_["burn"] >= thr
                calm = short["burn"] < thr and long_["burn"] < thr
                was = self._alerts[spec.name]
                now = burning or (was and not calm)
                if now and not was:
                    self.trips += 1
                    tripped.append(len(verdicts))
                elif was and not now:
                    self.clears += 1
                self._alerts[spec.name] = now
                verdicts.append({
                    "name": spec.name,
                    "kind": spec.kind,
                    "alert": now,
                    "burnThreshold": thr,
                    "objective": spec.objective,
                    "windows": {
                        f"{int(spec.short_s)}s": {
                            **short, "burn": round(short["burn"], 4)},
                        f"{int(spec.long_s)}s": {
                            **long_, "burn": round(long_["burn"], 4)},
                    },
                })
            self._verdicts = verdicts
        # Hooks run outside the lock: capture sources read back into the
        # watchdog (status()) and must not deadlock.
        for i in tripped:
            v = verdicts[i]
            for cb in list(self.on_trip):
                try:
                    cb(v["name"], v)
                except Exception:
                    pass
        return verdicts

    def verdicts(self) -> List[Dict]:
        """Latest cached verdicts (evaluates once if never run)."""
        with self._lock:
            cached = list(self._verdicts)
        if cached:
            return cached
        return self.evaluate()

    def alerts(self) -> Dict[str, bool]:
        with self._lock:
            return dict(self._alerts)

    @property
    def alerting(self) -> bool:
        with self._lock:
            return any(self._alerts.values())

    def status(self) -> Dict:
        """Full dict for the ``/statusz`` slo section."""
        return {
            "specs": self.verdicts(),
            "alerting": self.alerting,
            "trips": self.trips,
            "clears": self.clears,
        }
