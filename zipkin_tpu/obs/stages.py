"""Fixed stage taxonomy for the pipeline flight recorder.

Every ``obs.record(stage, dur_s)`` call site must name one of the
stages below with a string literal (statically enforced by lint rule
ZT08). The taxonomy is deliberately closed: a fixed, ordered tuple
lets the recorder preallocate flat per-thread arrays indexed by stage,
and dashboards can rely on the label set being stable across builds.

To add a stage: append the name here, give it a budget in
``DEFAULT_BUDGETS_US``, and instrument the host-side call site —
never inside jit'd/shard_map'd code (ZT08 rejects that too). See
ARCHITECTURE.md "Pipeline observability".

Budgets are the slow-span thresholds in µs: an observation exceeding
its stage budget lands in the recorder's slow-event ring and, when the
self-span emitter is installed (``TPU_OBS_SELFSPANS=1``), is published
as an internal span for service ``zipkin-tpu-pipeline``. Defaults are
intentionally generous — they flag genuine stalls, not CPU-backend jit
compiles in tests; scale them with ``TPU_OBS_BUDGET_SCALE``.
"""

STAGES = (
    "http_boundary",     # request body read → collector hand-off (server side)
    "grpc_boundary",     # gRPC Report: request bytes → collector hand-off
    "parse",             # wire bytes → columnar/object spans (C parser or codec)
    "pack",              # parsed spans → packed device wire image
    "route",             # shard routing of a fused batch
    "device_dispatch",   # enqueue wall of the jit'd ingest step (async dispatch)
    "rollup",            # fused rollup dispatch wall (pre-eviction linking)
    "ctx_advance",       # incremental link-context advance at query time
    "wal_append",        # WAL record write incl. buffer flush
    "wal_fsync",         # the fsync portion of a WAL append
    "snapshot",          # device-state snapshot save + WAL truncate
    "sampler_tick",      # RateController control-loop tick
    "archive_write",     # disk archive / fast-sample append
    "query_fresh",       # read-path cache miss: full device read program
    "query_cached",      # read-path cache hit under the version check
    "readpack_transfer",  # the single packed device→host pull per query
    "mp_record",         # MP dispatcher: shm copy + remap + device feed
    "mp_shm_copy",       # mp_record substage: shm slot → host array copy
    "mp_vocab_replay",   # mp_record substage: worker vocab journal replay
    "mp_lut_remap",      # mp_record substage: worker-local → global LUT remap
    "mp_device_feed",    # mp_record substage: fused batch → device ingest feed
    "coalesce",          # multi-chunk concat+remap gather into one bucketed image
    "accuracy_rollup",   # shadow drain + device reads + error estimators
    "wire_to_durable",   # stitched critical path: wire receipt → WAL-durable ack
    "query_lock_wait",   # outermost wait on the aggregator lock (per acquire)
    "query_wall",        # stitched query critical path: request begin → result
    "query_mirror",      # lock-free serve from the epoch-published read mirror
    "mirror_publish",    # one mirror publish: lock once, packed reads, swap
    "reader_serve",      # reader-process serve from the shm mirror segment
)

NUM_STAGES = len(STAGES)
STAGE_INDEX = {name: i for i, name in enumerate(STAGES)}

# Slow-span budgets, µs, scaled by TPU_OBS_BUDGET_SCALE at install time.
DEFAULT_BUDGETS_US = {
    "http_boundary": 500_000,
    "grpc_boundary": 500_000,
    "parse": 250_000,
    "pack": 250_000,
    "route": 250_000,
    "device_dispatch": 250_000,
    "rollup": 1_000_000,
    "ctx_advance": 500_000,
    "wal_append": 100_000,
    "wal_fsync": 100_000,
    "snapshot": 5_000_000,
    "sampler_tick": 100_000,
    "archive_write": 250_000,
    "query_fresh": 150_000,
    "query_cached": 50_000,
    "readpack_transfer": 100_000,
    "mp_record": 500_000,
    "mp_shm_copy": 250_000,
    "mp_vocab_replay": 250_000,
    "mp_lut_remap": 250_000,
    "mp_device_feed": 500_000,
    "coalesce": 250_000,
    "accuracy_rollup": 1_000_000,
    "wire_to_durable": 5_000_000,
    "query_lock_wait": 50_000,
    "query_wall": 150_000,
    "query_mirror": 10_000,
    "mirror_publish": 1_000_000,
    "reader_serve": 10_000,
}

assert set(DEFAULT_BUDGETS_US) == set(STAGES)
