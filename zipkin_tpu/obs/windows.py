"""Windowed telemetry plane: per-tick delta rings over the flight recorder.

The cumulative flight recorder (:mod:`zipkin_tpu.obs.recorder`) answers
"since boot"; this module answers "over the last 10s/1m/5m/1h". The
windowed-merge idiom from "Sketch Disaggregation Across Time and Space"
applies directly because the recorder's log2 buckets are mergeable: a
window quantile is a bucket-wise sum of per-tick *deltas* followed by
the same cumulative-walk ``StageStat`` read the cumulative plane uses.

Each ``tick()`` takes one seqlock-consistent ``recorder.snapshot()``
(never blocking writers — the query side of the "Fast Concurrent Data
Sketches" split), subtracts the previous snapshot, and pushes the delta
into a two-tier ring:

- a **fine ring** of ``slots`` one-tick deltas (default 64 × 1s), and
- a **coarse ring** of ``coarse_slots`` block deltas, each merging
  ``coarse_factor`` ticks (default 64 × 60s ≈ 65 min of coverage).

A window read merges the newest fine slots back to the last completed
coarse block boundary, then whole coarse blocks — so long lookbacks are
block-aligned and may cover up to ``coarse_factor - 1`` extra ticks;
``WindowStats.ticks`` reports the exact coverage. Because deltas are
exact differences of monotonic counters, the merge over any covered
tick range equals a from-scratch histogram of the same interval (the
oracle property the tests pin).

Counter *rates* (spans/s, 429/s, queries/s) fall out of the same rings:
each tick also samples a caller-supplied numeric counter dict, and a
rate is the difference of two samples divided by the covered wall.

Threading: ``tick()`` is expected from one caller at a time (the
server's 1 Hz ticker or ``tick_if_due()`` on a read path); the ring
lock makes concurrent window reads and ticks safe either way. A
``recorder.reset()`` shows up as a negative delta and clears the rings.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from zipkin_tpu.obs.recorder import (
    NUM_BUCKETS,
    Snapshot,
    StageStat,
    bucket_le_us,
)
from zipkin_tpu.obs.stages import NUM_STAGES, STAGES

_FLAT = NUM_STAGES * NUM_BUCKETS

CounterSource = Callable[[], Dict[str, float]]


def _numeric(counters: Dict) -> Dict[str, float]:
    """Keep only scalar values — sources may carry nested tables."""
    out = {}
    for k, v in counters.items():
        if isinstance(v, bool):
            out[k] = float(v)
        elif isinstance(v, (int, float)):
            out[k] = v
    return out


class WindowStats:
    """One merged window: per-stage histogram view plus counter deltas."""

    __slots__ = ("counts", "sums", "maxes", "ticks", "span_s",
                 "counter_deltas", "end_tick")

    def __init__(self, counts: List[int], sums: List[int], maxes: List[int],
                 ticks: int, span_s: float,
                 counter_deltas: Dict[str, float], end_tick: int) -> None:
        self.counts = counts
        self.sums = sums
        self.maxes = maxes
        self.ticks = ticks
        self.span_s = span_s
        self.counter_deltas = counter_deltas
        self.end_tick = end_tick

    def stage(self, name: str) -> StageStat:
        from zipkin_tpu.obs.stages import STAGE_INDEX

        idx = STAGE_INDEX[name]
        buckets = self.counts[idx * NUM_BUCKETS:(idx + 1) * NUM_BUCKETS]
        return StageStat(name, sum(buckets), self.sums[idx],
                         self.maxes[idx], buckets)

    def nonzero(self) -> List[StageStat]:
        return [s for s in (self.stage(n) for n in STAGES) if s.count]

    def rate(self, counter: str) -> float:
        """Events/second for one sampled counter over this window."""
        if self.span_s <= 0:
            return 0.0
        return self.counter_deltas.get(counter, 0.0) / self.span_s

    @property
    def total_count(self) -> int:
        return sum(self.counts)


class WindowedTelemetry:
    """Tiered delta rings over a :class:`StageRecorder` + counter source."""

    def __init__(self, recorder, counter_source: Optional[CounterSource] = None,
                 *, tick_s: float = 1.0, slots: int = 64,
                 coarse_slots: int = 64, coarse_factor: int = 60,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if slots < coarse_factor:
            raise ValueError("fine ring must cover one coarse block")
        self._rec = recorder
        self._source = counter_source
        self.tick_s = float(tick_s)
        self.slots = int(slots)
        self.coarse_slots = int(coarse_slots)
        self.coarse_factor = int(coarse_factor)
        self._clock = clock
        self._lock = threading.Lock()
        # serializes whole ticks (snapshot + push): concurrent tickers
        # (thread + lazy read-path catch-up) must not interleave their
        # snapshots or a stale one would produce a phantom negative delta
        self._tick_mutex = threading.Lock()
        self._enabled = True
        self._on_tick: List[Callable[["WindowedTelemetry"], None]] = []
        self._ticker: Optional[threading.Thread] = None
        self._ticker_stop = threading.Event()
        self.resets = 0
        self._init_rings()
        # Baseline at construction: observations recorded after this
        # point land in tick 1's delta; pre-existing totals stay in the
        # cumulative plane only.
        base = recorder.snapshot()
        self._base_counts = base.counts
        self._base_sums = base.sums
        self._base_maxes = base.maxes
        self._base_counters = self._sample_counters()
        # the epoch sample backs full-coverage counter deltas (tick -1);
        # _base_counters advances every tick, this only moves on ring reset
        self._epoch_counters = self._base_counters
        self._last_tick: Optional[float] = None

    # -- internals -----------------------------------------------------

    def _init_rings(self) -> None:
        self.ticks = 0  # completed ticks; fine slot i holds tick i % slots
        self._fine_counts: List[Optional[List[int]]] = [None] * self.slots
        self._fine_sums: List[Optional[List[int]]] = [None] * self.slots
        self._fine_counters: List[Optional[Dict[str, float]]] = \
            [None] * self.slots
        self._coarse_counts: List[Optional[List[int]]] = \
            [None] * self.coarse_slots
        self._coarse_sums: List[Optional[List[int]]] = [None] * self.coarse_slots
        self._coarse_counters: List[Optional[Dict[str, float]]] = \
            [None] * self.coarse_slots
        self._accum_counts = [0] * _FLAT
        self._accum_sums = [0] * NUM_STAGES
        self._accum_ticks = 0

    def _sample_counters(self) -> Dict[str, float]:
        if self._source is None:
            return {}
        try:
            return _numeric(self._source())
        except Exception:
            return {}

    # -- tick side -----------------------------------------------------

    def tick(self, now: Optional[float] = None) -> bool:
        """Capture one delta slot. Returns False when disabled or when a
        recorder reset forced a ring clear (the tick re-baselines)."""
        if not self._enabled:
            return False
        with self._tick_mutex:
            return self._tick_inner(now)

    def _tick_inner(self, now: Optional[float]) -> bool:
        if now is None:
            now = self._clock()
        snap = self._rec.snapshot()
        counters = self._sample_counters()
        with self._lock:
            ok = self._push_locked(snap, counters, now)
        if ok:
            for cb in list(self._on_tick):
                try:
                    cb(self)
                except Exception:
                    pass
        return ok

    # zt-lint: disable=ZT04 — every caller (_tick_inner, tick_if_due) holds self._lock
    def _push_locked(self, snap: Snapshot, counters: Dict[str, float],
                     now: float) -> bool:
        d_counts = [a - b for a, b in zip(snap.counts, self._base_counts)]
        d_sums = [a - b for a, b in zip(snap.sums, self._base_sums)]
        self._base_counts = snap.counts
        self._base_sums = snap.sums
        self._base_maxes = snap.maxes
        self._base_counters = counters
        self._last_tick = now
        if any(d < 0 for d in d_counts):
            # recorder.reset() happened mid-stream: history is
            # incomparable with the new baseline, start over
            self._init_rings()
            self._epoch_counters = counters
            self.resets += 1
            return False
        slot = self.ticks % self.slots
        self._fine_counts[slot] = d_counts
        self._fine_sums[slot] = d_sums
        self._fine_counters[slot] = counters
        for i in range(_FLAT):
            self._accum_counts[i] += d_counts[i]
        for i in range(NUM_STAGES):
            self._accum_sums[i] += d_sums[i]
        self._accum_ticks += 1
        self.ticks += 1
        if self._accum_ticks >= self.coarse_factor:
            block = (self.ticks // self.coarse_factor - 1) % self.coarse_slots
            self._coarse_counts[block] = self._accum_counts
            self._coarse_sums[block] = self._accum_sums
            self._coarse_counters[block] = counters
            self._accum_counts = [0] * _FLAT
            self._accum_sums = [0] * NUM_STAGES
            self._accum_ticks = 0
        return True

    def tick_if_due(self, now: Optional[float] = None) -> int:
        """Catch up on missed ticks (read-path driver when no ticker
        thread runs). Idle gaps produce empty slots — the snapshot is
        only taken for the newest tick, so a long-idle read costs one
        snapshot, not one per missed second."""
        if not self._enabled:
            return 0
        if now is None:
            now = self._clock()
        with self._tick_mutex:
            with self._lock:
                last = self._last_tick
            if last is None:
                return 1 if self._tick_inner(now) else 0
            due = int((now - last) / self.tick_s)
            if due <= 0:
                return 0
            if due > self.slots + self.coarse_factor:
                # gap longer than the fine ring: history aged out anyway
                with self._lock:
                    self._init_rings()
                    self._epoch_counters = self._base_counters
            else:
                with self._lock:
                    for i in range(due - 1):
                        self._push_locked(
                            Snapshot(self._base_counts, self._base_sums,
                                     self._base_maxes, 0, 0),
                            self._base_counters,
                            last + (i + 1) * self.tick_s,
                        )
            self._tick_inner(now)
            return due

    def on_tick(self, cb: Callable[["WindowedTelemetry"], None]) -> None:
        self._on_tick.append(cb)

    # -- ticker thread -------------------------------------------------

    def start_ticker(self) -> None:
        if self._ticker is not None:
            return
        self._ticker_stop.clear()

        def _loop() -> None:
            while not self._ticker_stop.wait(self.tick_s):
                try:
                    self.tick()
                except Exception:
                    pass

        t = threading.Thread(target=_loop, name="obs-windows-ticker",
                             daemon=True)
        self._ticker = t
        t.start()

    def stop_ticker(self) -> None:
        t = self._ticker
        if t is None:
            return
        self._ticker_stop.set()
        t.join(timeout=5.0)
        self._ticker = None

    @property
    def ticker_running(self) -> bool:
        return self._ticker is not None

    def set_enabled(self, on: bool) -> None:
        self._enabled = bool(on)

    @property
    def enabled(self) -> bool:
        return self._enabled

    # -- window side ---------------------------------------------------

    def window(self, lookback_s: float) -> WindowStats:
        """Merge the newest deltas covering ``lookback_s`` seconds.

        Exact at fine (one-tick) resolution inside the fine ring;
        block-aligned beyond it. Returns an empty window before the
        first tick."""
        want = max(1, int(round(lookback_s / self.tick_s)))
        with self._lock:
            return self._window_locked(want)

    def _window_locked(self, want: int) -> WindowStats:
        counts = [0] * _FLAT
        sums = [0] * NUM_STAGES
        t = self.ticks
        covered = 0
        if t > 0:
            avail_fine = min(t, self.slots)
            if want <= avail_fine:
                # exact: the fine ring holds every requested tick
                fine_lo = t - want
            else:
                # block-aligned: fine segment back to the last completed
                # coarse boundary (always inside the fine ring because
                # slots >= coarse_factor), whole coarse blocks beyond
                fine_lo = (t // self.coarse_factor) * self.coarse_factor
            for tick_i in range(fine_lo, t):
                dc = self._fine_counts[tick_i % self.slots]
                ds = self._fine_sums[tick_i % self.slots]
                if dc is None:
                    continue
                for i in range(_FLAT):
                    counts[i] += dc[i]
                for i in range(NUM_STAGES):
                    sums[i] += ds[i]
                covered += 1
            remaining = want - covered
            if remaining > 0 and want > avail_fine:
                n_blocks = (remaining + self.coarse_factor - 1) \
                    // self.coarse_factor
                avail_blocks = min(t // self.coarse_factor, self.coarse_slots)
                n_blocks = min(n_blocks, avail_blocks)
                newest_block = t // self.coarse_factor - 1
                for k in range(n_blocks):
                    block = (newest_block - k) % self.coarse_slots
                    bc = self._coarse_counts[block]
                    bs = self._coarse_sums[block]
                    if bc is None:
                        continue
                    for i in range(_FLAT):
                        counts[i] += bc[i]
                    for i in range(NUM_STAGES):
                        sums[i] += bs[i]
                    covered += self.coarse_factor
        maxes = self._window_maxes(counts)
        deltas = self._counter_deltas_locked(covered)
        return WindowStats(counts, sums, maxes, covered,
                           covered * self.tick_s, deltas, t)

    def _window_maxes(self, counts: List[int]) -> List[int]:
        """Per-window max is not delta-decomposable; bound it by the top
        nonzero bucket's upper edge, capped by the cumulative max."""
        maxes = [0] * NUM_STAGES
        for s in range(NUM_STAGES):
            base = s * NUM_BUCKETS
            for b in range(NUM_BUCKETS - 1, -1, -1):
                if counts[base + b]:
                    maxes[s] = min(bucket_le_us(b), self._base_maxes[s]) \
                        if self._base_maxes[s] else bucket_le_us(b)
                    break
        return maxes

    def _counter_deltas_locked(self, covered: int) -> Dict[str, float]:
        if covered <= 0 or self.ticks == 0:
            return {}
        newest = self._fine_counters[(self.ticks - 1) % self.slots]
        if newest is None:
            return {}
        old = self._counters_at_locked(self.ticks - 1 - covered)
        if old is None:
            return {}
        return {k: v - old.get(k, 0.0) for k, v in newest.items()}

    def _counters_at_locked(self, tick_i: int) -> Optional[Dict[str, float]]:
        """Cumulative counter sample at completed tick index ``tick_i``
        (-1 means the construction baseline). Window decomposition only
        asks at fine-ring indices or coarse block ends, so exact samples
        always exist while the data is retained."""
        if tick_i < 0:
            # the window covers every tick: delta against the epoch
            # (construction or last ring reset)
            return self._epoch_counters
        if tick_i >= self.ticks - self.slots:
            return self._fine_counters[tick_i % self.slots]
        if (tick_i + 1) % self.coarse_factor != 0:
            return None
        block = (tick_i + 1) // self.coarse_factor - 1
        if block < self.ticks // self.coarse_factor - self.coarse_slots:
            return None
        return self._coarse_counters[block % self.coarse_slots]

    def current_counters(self) -> Dict[str, float]:
        """Newest cumulative counter sample (gauge reads)."""
        with self._lock:
            if self.ticks:
                c = self._fine_counters[(self.ticks - 1) % self.slots]
            else:
                c = self._base_counters
        return dict(c or {})

    def rates(self, lookback_s: float) -> Dict[str, float]:
        """Counter rates (events/s) over the newest covered window."""
        w = self.window(lookback_s)
        if w.span_s <= 0:
            return {}
        return {k: v / w.span_s for k, v in w.counter_deltas.items()}

    # -- introspection -------------------------------------------------

    def status(self, lookbacks: Tuple[float, ...] = (10.0, 60.0, 300.0,
                                                     3600.0)) -> Dict:
        """Compact dict for the ``/statusz`` windows section."""
        body: Dict = {
            "tickS": self.tick_s,
            "ticks": self.ticks,
            "fineSlots": self.slots,
            "coarseSlots": self.coarse_slots,
            "coarseFactor": self.coarse_factor,
            "resets": self.resets,
            "tickerRunning": self.ticker_running,
            "lookbacks": {},
        }
        for lb in lookbacks:
            w = self.window(lb)
            stages = {
                s.stage: {
                    "count": s.count,
                    "p50Us": s.p50_us,
                    "p99Us": s.p99_us,
                    "maxUs": s.max_us,
                }
                for s in w.nonzero()
            }
            rates = {}
            if w.span_s > 0:
                for key in ("spans", "accepted", "mpAccepted", "mpRejected"):
                    if key in w.counter_deltas:
                        rates[key + "PerSec"] = round(
                            w.counter_deltas[key] / w.span_s, 3)
            body["lookbacks"][f"{int(lb)}s"] = {
                "coveredS": round(w.span_s, 3),
                "stages": stages,
                "rates": rates,
            }
        return body
