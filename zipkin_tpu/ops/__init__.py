"""Pure-JAX device ops: the TPU equivalents of the reference's hand-rolled
hot loops (``zipkin2/internal/WriteBuffer.java``-class code, SURVEY.md §2.7).

Everything here is a pure function over fixed-shape arrays, safe under
``jax.jit`` and ``shard_map``:

- :mod:`hashing` — 32-bit avalanche mixes for ids (HLL, hash joins).
- :mod:`segments` — sorted-segment reductions (the scatter-free idiom).
- :mod:`hll` — HyperLogLog registers with scatter-max updates.
- :mod:`histogram` — HDR-style log2 latency histograms (exactly mergeable
  by addition, hence ``psum``-friendly).
- :mod:`tdigest` — merging t-digest with sort-based compaction.
- :mod:`linker` — windowed dependency linking (parent join + ancestor
  climb by pointer doubling), mirroring
  ``zipkin2/internal/DependencyLinker.java``.
"""
