"""Incremental link context: the since-rollup delta formulation.

The from-scratch resolve in :mod:`zipkin_tpu.ops.linker` sorts the full
2n-lane join union on every fresh read (~29.6 ms of the 41.3 ms fresh
dependency read at ring 2^18, PROFILE_r05). But the rollup cadence
already bounds how much the ring can change between rollups: the host
triggers a rollup before writes since the last one exceed
``rollup_segment`` (R/2), so at any instant the ring differs from its
state at the last rollup by at most one delta segment. This module
exploits that bound:

- At each rollup the device ADVANCES a persistent ctx structure: the
  sorted union order, its run decomposition, and per-run first-wins
  candidates restricted to lanes that cannot die before the next
  advance ("safe" lanes). The advance merges the delta segment into the
  stored order with binary-searched ranks — no full-ring sort.
- A fresh read sorts ONLY the 2·rollup_segment delta union, binary
  searches the stored (immutable) keys to map delta runs onto stored
  runs, and resolves every candidate by a three-way age-partition
  priority select. No full-ring sort, no run-min ladder.

Why the partition select is EXACT (bit-identical to the oracle): ring
overwrites always hit the globally-oldest lanes, so with ``Δ =
rollup_segment`` the lanes at advance-age ``[0, Δ)`` ("doomed") are the
only ones that can die before the next advance, and the age order
doomed < safe < delta holds lane-for-lane. First-wins = min insertion
age, so the run's first candidate is: the oldest STILL-ALIVE doomed
candidate if any (recomputed at read over the Δ-lane doomed window),
else the stored safe candidate (immutable between advances), else the
first delta candidate (from the delta sort). No fallback path, no
approximation — parity is fuzzed in tests/test_incremental_ctx.py.

Everything here is width-Δ or width-log(n): the only full-width ops are
elementwise gathers/scatters and the ancestor chase (pointer doubling
is already convergence-bounded and cheap). ZT-lint rule ZT07 enforces
that no full-ring sort/scan creeps back into this read path.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from zipkin_tpu.ops import linker
from zipkin_tpu.ops.segments import segment_starts


class CtxStruct(NamedTuple):
    """Persistent device ctx over the 2n-lane join union (n ring lanes).

    All leaves live in :class:`zipkin_tpu.tpu.state.AggState` (``ctx_*``)
    and are advanced in :func:`advance` at rollup cadence. ``keys`` is a
    SNAPSHOT of the union sort keys at the last advance: lanes written
    since then ("delta" lanes) have stale rows here, but their stored
    entries are dead (masked by age) and run identity of the surviving
    entries never changes — which is what makes the stored arrays
    binary-searchable without maintenance.
    """

    order: jnp.ndarray     # i32 [2n] union index at each sorted position
    keys: jnp.ndarray      # u32 [4, 2n] sort-key snapshot per position
    rid_c: jnp.ndarray     # i32 [2n] coarse (trace, id) run id, 1-based
    rid_f: jnp.ndarray     # i32 [2n] fine (trace, id, svc) run id, 1-based
    inv: jnp.ndarray       # i32 [2n] sorted position of union entry u
    safe_sh: jnp.ndarray   # i32 [2n] run-broadcast first SAFE shared lane
    safe_ns: jnp.ndarray   # i32 [2n] ... first SAFE non-shared lane
    safe_fsh: jnp.ndarray  # i32 [2n] ... first SAFE shared lane, fine run
    pos: jnp.ndarray       # i32 [] ring cursor at the last advance
    delta: jnp.ndarray     # i32 [] lanes written since the last advance


def init_ctx(n: int) -> CtxStruct:
    """Ctx of an all-invalid ring: every union key is 0xFFFFFFFF, so the
    identity order is validly sorted and the whole union is one run with
    no candidates — exactly what an advance over the empty ring yields."""
    u = 2 * n
    return CtxStruct(
        order=jnp.arange(u, dtype=jnp.int32),
        keys=jnp.full((4, u), 0xFFFFFFFF, jnp.uint32),
        rid_c=jnp.ones((u,), jnp.int32),
        rid_f=jnp.ones((u,), jnp.int32),
        inv=jnp.arange(u, dtype=jnp.int32),
        safe_sh=jnp.full((u,), -1, jnp.int32),
        safe_ns=jnp.full((u,), -1, jnp.int32),
        safe_fsh=jnp.full((u,), -1, jnp.int32),
        pos=jnp.zeros((), jnp.int32),
        delta=jnp.zeros((), jnp.int32),
    )


def _lex_lt(a, b):
    """Elementwise lexicographic a < b over parallel key-lane lists."""
    lt = a[-1] < b[-1]
    for k in range(len(a) - 2, -1, -1):
        lt = (a[k] < b[k]) | ((a[k] == b[k]) & lt)
    return lt


def _lex_eq(a, b):
    eq = a[0] == b[0]
    for k in range(1, len(a)):
        eq = eq & (a[k] == b[k])
    return eq


def _lower_bound(tbl, q, strict=False):
    """Vectorized binary search: for each query key (parallel lanes in
    ``q``) the leftmost index i in [0, len] with tbl[i] >= q (or > q when
    ``strict``). ``tbl`` lanes must be lex-sorted. ceil(log2(len))+1
    fixed passes of 4-wide gathers — the price of mapping a delta run
    onto the stored run universe without touching the full ring."""
    size = int(tbl[0].shape[0])
    m = q[0].shape[0]
    lo = jnp.zeros((m,), jnp.int32)
    hi = jnp.full((m,), size, jnp.int32)

    def body(_, carry):
        lo, hi = carry
        mid = (lo + hi) >> 1
        mi = jnp.clip(mid, 0, size - 1)
        t = [lane[mi] for lane in tbl]
        if strict:
            go_right = ~_lex_lt(q, t)  # tbl[mid] <= q
        else:
            go_right = _lex_lt(t, q)  # tbl[mid] < q
        act = lo < hi
        lo = jnp.where(act & go_right, mid + 1, lo)
        hi = jnp.where(act & ~go_right, mid, hi)
        return lo, hi

    lo, _ = jax.lax.fori_loop(0, max(size.bit_length(), 1), body, (lo, hi))
    return lo


def _resolve_core(x: linker.LinkInput, cs: CtxStruct, seg: int):
    """Shared delta machinery: everything both the fresh read and the
    advance need. Returns the resolved tree plus the sorted-delta
    internals the advance's merge reuses."""
    n = x.valid.shape[0]
    u = 2 * n
    apos = cs.pos
    # host invariant (ShardedAggregator ingest cadence): at most one
    # rollup segment is ever written between advances
    delta = jnp.clip(cs.delta, 0, seg)
    lane_all = jnp.arange(n, dtype=jnp.int32)

    # ---- the delta segment: the ONLY sorted piece, width 2*seg --------
    j = jnp.arange(seg, dtype=jnp.int32)
    dlane = (apos + j) % n
    live_j = j < delta  # lanes actually written since the advance

    def g(col):
        return col[dlane]

    sub = linker.LinkInput(
        trace_h=g(x.trace_h), tl0=g(x.tl0), tl1=g(x.tl1),
        s0=g(x.s0), s1=g(x.s1), p0=g(x.p0), p1=g(x.p1),
        shared=g(x.shared), kind=g(x.kind), svc=g(x.svc),
        rsvc=g(x.rsvc), err=g(x.err), valid=g(x.valid) & live_j,
    )
    d_id, d_svc, d_hasp = linker.union_key_lanes(sub)
    duidx = jnp.arange(2 * seg, dtype=jnp.int32)
    # zt-lint: disable=ZT07 — sorts only the delta segment: 2·Δ union lanes (Δ = rollup_segment = R/2), half the oracle's 2·R full-ring union; the ring-wide order is maintained at rollup cadence by advance()
    sk0, sk1, sk2, sk3, suid = jax.lax.sort(
        tuple(d_id) + (d_svc, duidx), num_keys=4
    )
    dkeys = [sk0, sk1, sk2, sk3]
    sj = suid % seg             # delta-lane index of the sorted entry
    s_isq = suid >= seg         # query-half entry
    slane = dlane[sj]
    s_live = live_j[sj]         # entry belongs to a written delta lane
    s_sh = sub.shared[sj]
    s_tbl_valid = ~s_isq & sub.valid[sj]
    s_q_valid = s_isq & d_hasp[sj]
    s_entry_valid = s_tbl_valid | s_q_valid

    # delta-local run decomposition (contiguous in the delta sort)
    dcoarse = linker._run_starts(dkeys[:3])
    dfine = dcoarse | jnp.asarray(segment_starts(sk3))
    drid_c = jnp.cumsum(dcoarse.astype(jnp.int32))
    drid_f = jnp.cumsum(dfine.astype(jnp.int32))

    # ---- map delta runs onto stored runs (binary search, width 2*seg) -
    skeys = [cs.keys[0], cs.keys[1], cs.keys[2], cs.keys[3]]
    p3 = _lower_bound(skeys[:3], dkeys[:3])
    p4 = _lower_bound(skeys, dkeys)
    p3c = jnp.clip(p3, 0, u - 1)
    p4c = jnp.clip(p4, 0, u - 1)
    m3 = (p3 < u) & _lex_eq([a[p3c] for a in skeys[:3]], dkeys[:3])
    m4 = (p4 < u) & _lex_eq([a[p4c] for a in skeys], dkeys)
    rid_c_old = jnp.where(m3, cs.rid_c[p3c], 0)  # 0 = no stored run
    rid_f_old = jnp.where(m4, cs.rid_f[p4c], 0)

    # delta candidate tables over the EXTENDED run universe: stored run
    # ids [1, u] for matched keys, synthetic ids above u for brand-new
    # keys (so two delta runs of the same new key still share a slot)
    tsz = u + 2 * seg + 1
    rid_c_ext = jnp.where(m3, rid_c_old, u + drid_c)
    rid_f_ext = jnp.where(m4, rid_f_old, u + drid_f)
    bigj = jnp.int32(2 * seg)  # > any delta write index

    def dmin(guard, rid):
        return jnp.full((tsz,), bigj, jnp.int32).at[rid].min(
            jnp.where(guard, sj, bigj)
        )

    dl_sh = dmin(s_tbl_valid & s_sh, rid_c_ext)
    dl_ns = dmin(s_tbl_valid & ~s_sh, rid_c_ext)
    dl_fsh = dmin(s_tbl_valid & s_sh, rid_f_ext)

    # ---- doomed window: first STILL-ALIVE candidate per stored run ----
    # (width seg; slot 0 of each table is never scattered — stored run
    # ids are 1-based — so unmatched gathers read the empty sentinel)
    a = jnp.arange(seg, dtype=jnp.int32)
    alane = (apos + a) % n
    aalive = (a >= delta) & x.valid[alane]
    apos_tbl = cs.inv[alane]  # stored position of the lane's table entry
    arc = cs.rid_c[apos_tbl]
    arf = cs.rid_f[apos_tbl]
    ash = x.shared[alane]
    biga = jnp.int32(seg)  # > any doomed age

    def amin(guard, rid):
        return jnp.full((u + 1,), biga, jnp.int32).at[rid].min(
            jnp.where(guard, a, biga)
        )

    dm_sh = amin(aalive & ash, arc)
    dm_ns = amin(aalive & ~ash, arc)
    dm_fsh = amin(aalive & ash, arf)

    def pick(dmv, safe, dlv):
        # age-partition priority: alive doomed (oldest) > stored safe
        # (middle) > delta (newest); exactness argued in the module doc
        return jnp.where(
            dmv < biga, (apos + dmv) % n,
            jnp.where(
                safe >= 0, safe,
                jnp.where(dlv < bigj, (apos + dlv) % n, -1),
            ),
        )

    def prefer(c_sh, c_ns, c_fsh, is_table, qshf, svc_key):
        # SpanNode._choose_parent preference chain on candidate LANES —
        # the elementwise mirror of resolve_parents' sorted-space select
        prim_ok = c_ns >= 0
        prim_svc = x.svc[jnp.where(prim_ok, c_ns, 0)].astype(jnp.uint32)
        prim_match = prim_ok & (prim_svc == svc_key)
        byp = c_ns
        byp = jnp.where(c_sh >= 0, c_sh, byp)
        byp = jnp.where(prim_match, c_ns, byp)
        byp = jnp.where(c_fsh >= 0, c_fsh, byp)
        return jnp.where(is_table | qshf, c_ns, byp)

    # ---- surviving stored entries (full-width elementwise only) -------
    ou = cs.order
    o_lane = jnp.where(ou < n, ou, ou - n)
    o_isq = ou >= n
    o_age = (o_lane - apos) % n
    o_alive = o_age >= delta  # lanes at age < delta were overwritten
    o_csh = pick(dm_sh[cs.rid_c], cs.safe_sh, dl_sh[cs.rid_c])
    o_cns = pick(dm_ns[cs.rid_c], cs.safe_ns, dl_ns[cs.rid_c])
    o_cfsh = pick(dm_fsh[cs.rid_f], cs.safe_fsh, dl_fsh[cs.rid_f])
    o_qsh = o_isq & x.shared[o_lane] & x.valid[o_lane]
    o_comb = prefer(o_csh, o_cns, o_cfsh, ~o_isq, o_qsh, cs.keys[3])

    # ---- delta entries ------------------------------------------------
    d_csh = pick(dm_sh[rid_c_old], jnp.where(m3, cs.safe_sh[p3c], -1),
                 dl_sh[rid_c_ext])
    d_cns = pick(dm_ns[rid_c_old], jnp.where(m3, cs.safe_ns[p3c], -1),
                 dl_ns[rid_c_ext])
    d_cfsh = pick(dm_fsh[rid_f_old], jnp.where(m4, cs.safe_fsh[p4c], -1),
                  dl_fsh[rid_f_ext])
    d_qsh = s_isq & s_sh & sub.valid[sj]
    d_comb = prefer(d_csh, d_cns, d_cfsh, ~s_isq, d_qsh, sk3)

    # ---- un-scatter: stored entries first, delta overwrites its lanes -
    un = jnp.full((u,), -1, jnp.int32)
    un = un.at[ou].set(jnp.where(o_alive, o_comb, -1))
    d_union_idx = jnp.where(s_isq, n + slane, slane)
    un = un.at[jnp.where(s_live, d_union_idx, u)].set(
        jnp.where(s_entry_valid, d_comb, -1), mode="drop"
    )

    # ---- finish exactly as resolve_parents ----------------------------
    has_parent = ((x.p0 | x.p1) != 0) & x.valid
    sharedv = x.valid & x.shared
    j_shared = jnp.where(sharedv, un[:n], -1)
    q = jnp.where(has_parent, un[n:], -1)
    parent = jnp.where(sharedv, jnp.where(j_shared >= 0, j_shared, q), q)
    parent = jnp.where(parent == lane_all, -1, parent)
    parent = jnp.where(x.valid, parent, -1)
    has_child = (
        jnp.zeros(n, jnp.int32)
        .at[jnp.where(parent >= 0, parent, 0)]
        .max(jnp.where(parent >= 0, 1, 0))
    ).astype(bool)

    return dict(
        parent=parent, has_child=has_child,
        dkeys=dkeys, s_isq=s_isq, s_live=s_live, slane=slane,
        o_alive=o_alive, apos=apos, delta=delta,
    )


def delta_resolve(
    x: linker.LinkInput, cs: CtxStruct, seg: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(parent, has_child) — bit-identical to linker.resolve_parents over
    the same ring, paying only the since-advance delta."""
    core = _resolve_core(x, cs, seg)
    return core["parent"], core["has_child"]


def delta_link_context(
    x: linker.LinkInput, cs: CtxStruct, seg: int
) -> linker.LinkContext:
    """The fresh-read link context via the delta formulation."""
    core = _resolve_core(x, cs, seg)
    anc, root_ok = linker.chase_ancestors(
        core["parent"], jnp.where(x.valid, x.kind, 0)
    )
    return linker.apply_rules(
        x, core["parent"], core["has_child"], anc, root_ok
    )


def advance(x: linker.LinkInput, cs: CtxStruct, seg: int):
    """Advance the persistent ctx over the since-last-advance delta.

    Runs at rollup cadence (fused into rollup_step): resolves the
    current tree through the same delta core a read uses, then MERGES
    the delta entries into the stored sorted order — binary-searched
    merge ranks plus an alive-compaction, never a full-ring sort — and
    rebuilds run ids + safe candidates for the NEXT doom window.

    Returns (new_ctx, ctx_parent, ctx_anc, ctx_root, link_context): the
    resolved tree doubles as the rollup's emit context, so the rollup
    program stops paying for its own from-scratch link_context.
    """
    n = x.valid.shape[0]
    u = 2 * n
    core = _resolve_core(x, cs, seg)
    parent, has_child = core["parent"], core["has_child"]
    apos, delta = core["apos"], core["delta"]
    npos = (apos + delta) % n

    # ---- stable merge of delta entries into the surviving order -------
    alive = core["o_alive"]
    placed = core["s_live"]
    ac = jnp.cumsum(alive.astype(jnp.int32))
    ac_pad = jnp.concatenate([jnp.zeros((1,), jnp.int32), ac])
    pc = jnp.cumsum(placed.astype(jnp.int32))
    pc_pad = jnp.concatenate([jnp.zeros((1,), jnp.int32), pc])

    skeys = [cs.keys[0], cs.keys[1], cs.keys[2], cs.keys[3]]
    dkeys = core["dkeys"]
    # equal keys tie old-before-delta on both sides of the merge: the
    # relative order of equal-key entries inside a run is irrelevant to
    # run identity, it only has to be consistent
    lbd = _lower_bound(dkeys, skeys)             # delta strictly below old
    pos_old = (ac - 1) + pc_pad[lbd]
    lbo = _lower_bound(skeys, dkeys, strict=True)  # old at-or-below delta
    pos_delta = ac_pad[lbo] + (pc - placed.astype(jnp.int32))

    d_union_idx = jnp.where(
        core["s_isq"], n + core["slane"], core["slane"]
    )
    new_order = jnp.zeros((u,), jnp.int32)
    new_order = new_order.at[jnp.where(alive, pos_old, u)].set(
        cs.order, mode="drop"
    )
    new_order = new_order.at[jnp.where(placed, pos_delta, u)].set(
        d_union_idx, mode="drop"
    )

    # ---- rebuild keys / runs / inverse from the CURRENT ring ----------
    f_id, f_svc, _ = linker.union_key_lanes(x)
    nk = [f_id[0][new_order], f_id[1][new_order], f_id[2][new_order],
          f_svc[new_order]]
    ncoarse = linker._run_starts(nk[:3])
    nfine = ncoarse | jnp.asarray(segment_starts(nk[3]))
    nrid_c = jnp.cumsum(ncoarse.astype(jnp.int32))
    nrid_f = jnp.cumsum(nfine.astype(jnp.int32))
    ninv = jnp.zeros((u,), jnp.int32).at[new_order].set(
        jnp.arange(u, dtype=jnp.int32)
    )

    # ---- safe candidates for the NEXT doom window ---------------------
    n_lane = jnp.where(new_order < n, new_order, new_order - n)
    n_isq = new_order >= n
    n_age = (n_lane - npos) % n
    n_tbl_valid = ~n_isq & x.valid[n_lane]
    n_sh = x.shared[n_lane]
    bign = jnp.int32(n)

    def smin(guard, rid):
        tbl = jnp.full((u + 1,), bign, jnp.int32).at[rid].min(
            jnp.where(guard & (n_age >= seg), n_age, bign)
        )
        v = tbl[rid]
        return jnp.where(v < bign, (npos + v) % n, -1)

    nsafe_sh = smin(n_tbl_valid & n_sh, nrid_c)
    nsafe_ns = smin(n_tbl_valid & ~n_sh, nrid_c)
    nsafe_fsh = smin(n_tbl_valid & n_sh, nrid_f)

    new_cs = CtxStruct(
        order=new_order,
        keys=jnp.stack(nk),
        rid_c=nrid_c, rid_f=nrid_f, inv=ninv,
        safe_sh=nsafe_sh, safe_ns=nsafe_ns, safe_fsh=nsafe_fsh,
        pos=npos.astype(jnp.int32),
        delta=jnp.zeros((), jnp.int32),
    )

    anc, root_ok = linker.chase_ancestors(
        parent, jnp.where(x.valid, x.kind, 0)
    )
    ctx = linker.apply_rules(x, parent, has_child, anc, root_ok)
    return new_cs, parent, anc, root_ok, ctx
