"""32-bit avalanche hashing for device-side ids.

TPUs have no native 64-bit integer path worth using, so ids (trace/span ids
are 64/128-bit hex in the model, ``zipkin2/Span.java``) travel as pairs of
``uint32`` lanes and are mixed with murmur3's fmix32 finalizer. Used by the
HLL sketch (trace-id cardinality) and the span-id hash joins in the device
linker.
"""

from __future__ import annotations

import jax.numpy as jnp

_C1 = jnp.uint32(0x85EBCA6B)
_C2 = jnp.uint32(0xC2B2AE35)
_GOLDEN = jnp.uint32(0x9E3779B9)


def fmix32(x: jnp.ndarray) -> jnp.ndarray:
    """murmur3 finalizer: full-avalanche 32-bit mix."""
    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * _C1
    x = x ^ (x >> 13)
    x = x * _C2
    x = x ^ (x >> 16)
    return x


def hash2(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Mix two u32 lanes (one 64-bit id) into one well-distributed u32."""
    return fmix32(a.astype(jnp.uint32) ^ fmix32(b.astype(jnp.uint32) + _GOLDEN))


def hash4(a: jnp.ndarray, b: jnp.ndarray, c: jnp.ndarray, d: jnp.ndarray) -> jnp.ndarray:
    """Mix four u32 lanes (one 128-bit id) into one u32."""
    return hash2(hash2(a, b), hash2(c, d))


def floor_log2(x: jnp.ndarray) -> jnp.ndarray:
    """``floor(log2(x))`` for u32 ``x >= 1``, integer-only (f32 is not exact
    past 2**24 so no float detour). Returns int32; 0 maps to 0."""
    x = x.astype(jnp.uint32)
    e = jnp.zeros(x.shape, jnp.int32)
    for k in (16, 8, 4, 2, 1):
        big = (x >> jnp.uint32(k)) != 0
        e = e + jnp.where(big, k, 0).astype(jnp.int32)
        x = jnp.where(big, x >> jnp.uint32(k), x)
    return e
