"""HDR-style log2 latency histograms: the workhorse quantile store.

Per-(service, spanName) latency distributions kept as ``uint32`` count
arrays ``[keys, BUCKETS]``. Log2 bucketing with SUB sub-buckets per octave
gives a bounded *relative* error of 1/(2*SUB) at every scale — the same
guarantee HdrHistogram gives the JVM world — while being a pure
scatter-add / segment-sum update and **exactly mergeable by addition**,
which is what makes the cross-chip ``lax.psum`` merge correct (unlike
t-digest, whose merge is lossy; we keep both, SURVEY.md §7).

Durations are microseconds (``zipkin2/Span.java`` duration contract),
clamped to u32 (~71 minutes) — longer spans saturate the top bucket.
"""

from __future__ import annotations

import jax.numpy as jnp

from zipkin_tpu.ops.hashing import floor_log2

SUB_BITS = 5
SUB = 1 << SUB_BITS  # 32 sub-buckets per octave -> <= ~1.6% relative error
BUCKETS = (32 - SUB_BITS + 1) * SUB  # 896


def new_histograms(keys: int) -> jnp.ndarray:
    return jnp.zeros((keys, BUCKETS), jnp.uint32)


def bucket_of(duration_us: jnp.ndarray) -> jnp.ndarray:
    """Map u32 microsecond durations to bucket indices [0, BUCKETS)."""
    v = jnp.maximum(duration_us.astype(jnp.uint32), 0)
    e = floor_log2(jnp.maximum(v, 1))
    small = v < (1 << (SUB_BITS + 1))  # linear region: bucket == value
    shift = jnp.maximum(e - SUB_BITS, 0).astype(jnp.uint32)
    mant = (v >> shift).astype(jnp.int32) - SUB
    idx = (e - SUB_BITS + 1) * SUB + mant
    return jnp.where(small, v.astype(jnp.int32), idx)


def bucket_bounds(idx: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(low, width) of each bucket in microseconds, float32."""
    idx = idx.astype(jnp.int32)
    small = idx < 2 * SUB
    block = idx // SUB
    off = idx % SUB
    e = block + SUB_BITS - 1
    shift = jnp.maximum(e - SUB_BITS, 0)
    lo = ((SUB + off) << shift).astype(jnp.float32)
    width = (jnp.int32(1) << shift).astype(jnp.float32)
    return (
        jnp.where(small, idx.astype(jnp.float32), lo),
        jnp.where(small, 1.0, width),
    )


def update(
    histograms: jnp.ndarray,
    key_ids: jnp.ndarray,
    durations_us: jnp.ndarray,
    valid: jnp.ndarray,
) -> jnp.ndarray:
    """Count valid durations into ``histograms[key, bucket]``.

    Invalid lanes are routed to a key clamped in range with weight 0.
    """
    b = bucket_of(durations_us)
    w = valid.astype(histograms.dtype)
    k = jnp.clip(key_ids.astype(jnp.int32), 0, histograms.shape[0] - 1)
    return histograms.at[k, b].add(w)


def quantile(counts: jnp.ndarray, qs: jnp.ndarray) -> jnp.ndarray:
    """Quantiles per histogram row with linear interpolation inside the
    bucket. ``counts``: [..., BUCKETS]; ``qs``: [Q] in [0,1].
    Returns [..., Q] float32 (0 where the histogram is empty).
    """
    c = counts.astype(jnp.float32)
    total = jnp.sum(c, axis=-1, keepdims=True)
    cum = jnp.cumsum(c, axis=-1)
    targets = qs[None, :] * total.reshape(-1, 1)  # [R, Q]
    cum2 = cum.reshape(-1, BUCKETS)
    # first bucket whose cumulative count reaches the target
    idx = jnp.sum((cum2[:, :, None] < targets[:, None, :]), axis=1)
    idx = jnp.clip(idx, 0, BUCKETS - 1)
    lo, width = bucket_bounds(idx)
    cum_before = jnp.take_along_axis(
        jnp.concatenate([jnp.zeros_like(cum2[:, :1]), cum2], axis=1), idx, axis=1
    )
    in_bucket = jnp.take_along_axis(cum2, idx, axis=1) - cum_before
    frac = jnp.where(in_bucket > 0, (targets - cum_before) / jnp.maximum(in_bucket, 1e-9), 0.5)
    frac = jnp.clip(frac, 0.0, 1.0)
    out = lo + frac * width
    out = jnp.where(total.reshape(-1, 1) > 0, out, 0.0)
    return out.reshape(counts.shape[:-1] + (qs.shape[0],))


def merge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Exact union — addition; the psum combiner."""
    return a + b


def total_count(counts: jnp.ndarray) -> jnp.ndarray:
    return jnp.sum(counts.astype(jnp.uint32), axis=-1)
