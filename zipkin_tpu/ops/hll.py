"""HyperLogLog registers on device: distinct trace-id counting at line rate.

The aggregation BASELINE config[3] asks for: per-service (and global)
distinct-trace cardinality maintained as fixed-shape ``uint8`` register
arrays ``[rows, m]`` updated by scatter-max, merged across chips by
element-wise ``max`` (``lax.pmax``), estimated with the standard
bias-corrected harmonic mean + linear counting for the small range.

Replaces the reference's approach of delegating cardinality-ish questions
to backend aggregations (ES terms aggs, ``zipkin2/storage/InMemoryStorage``
set sizes) with O(1)-memory sketches.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from zipkin_tpu.ops.hashing import floor_log2


def new_registers(rows: int, precision: int = 11) -> jnp.ndarray:
    """Zeroed HLL registers: ``rows`` independent sketches of 2**precision
    registers each. Standard error ~= 1.04 / sqrt(2**precision)."""
    return jnp.zeros((rows, 1 << precision), jnp.uint8)


def update(
    registers: jnp.ndarray,
    row_ids: jnp.ndarray,
    hashes: jnp.ndarray,
    valid: jnp.ndarray,
) -> jnp.ndarray:
    """Scatter-max ``rho`` of each hash into ``registers[row, bucket]``.

    ``hashes`` are full-avalanche u32 (:func:`zipkin_tpu.ops.hashing.hash2`).
    Invalid lanes are routed to rho=0 which never lowers a register.
    """
    m = registers.shape[1]
    p = int(m).bit_length() - 1
    h = hashes.astype(jnp.uint32)
    bucket = (h >> jnp.uint32(32 - p)).astype(jnp.int32)
    rest = h & jnp.uint32((1 << (32 - p)) - 1)
    # rho = position of the leftmost 1-bit in the low (32-p) bits, counting
    # from the top of that field; all-zero rest -> (32-p)+1.
    rho = jnp.where(
        rest == 0,
        jnp.int32(32 - p + 1),
        jnp.int32(32 - p) - floor_log2(jnp.maximum(rest, 1)),
    )
    rho = jnp.where(valid, rho, 0).astype(jnp.uint8)
    return registers.at[row_ids, bucket].max(rho)


def merge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lossless sketch union — the cross-chip combiner (pmax over ICI)."""
    return jnp.maximum(a, b)


def estimate(registers: jnp.ndarray) -> jnp.ndarray:
    """Cardinality estimate per row, shape ``[rows]`` float32.

    Flajolet et al. bias-corrected estimator with linear counting below
    2.5m. The CLASSICAL 32-bit large-range correction
    (``-2^32 ln(1 - E/2^32)``) is deliberately ABSENT: it models an
    estimator whose raw value saturates at the count of distinct hash
    values, but this implementation's rho convention (all-zero rest ->
    33-p, :func:`update`) keeps the raw estimator nearly unbiased deep
    into hash-space saturation. Measured against exact register law +
    a 1e9-draw simulation (r5, tests/test_ops_sketches.py): bias -0.4%
    at n=5e8, -1.2% at n=1e9, -4.4% at 2e9 — all well inside the
    3*stderr gate at p=11 (6.9%) — while applying the classical
    correction at n=1e9 would ADD +13.6% error. Beyond ~4e9 (where the
    bias passes -14%) a 64-bit hash path would be needed, not a
    correction term.
    """
    m = registers.shape[-1]
    alpha = _alpha(m)
    regs = registers.astype(jnp.float32)
    harm = jnp.sum(jnp.exp2(-regs), axis=-1)
    raw = alpha * m * m / harm
    zeros = jnp.sum(registers == 0, axis=-1).astype(jnp.float32)
    linear = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    use_linear = (raw <= 2.5 * m) & (zeros > 0)
    return jnp.where(use_linear, linear, raw)


def _alpha(m: int) -> float:
    if m == 16:
        return 0.673
    if m == 32:
        return 0.697
    if m == 64:
        return 0.709
    return 0.7213 / (1.0 + 1.079 / m)


def standard_error(precision: int) -> float:
    return 1.04 / math.sqrt(1 << precision)
