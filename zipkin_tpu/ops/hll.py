"""HyperLogLog registers on device: distinct trace-id counting at line rate.

The aggregation BASELINE config[3] asks for: per-service (and global)
distinct-trace cardinality maintained as fixed-shape ``uint8`` register
arrays ``[rows, m]`` updated by scatter-max, merged across chips by
element-wise ``max`` (``lax.pmax``), estimated with the standard
bias-corrected harmonic mean + linear counting for the small range.

Replaces the reference's approach of delegating cardinality-ish questions
to backend aggregations (ES terms aggs, ``zipkin2/storage/InMemoryStorage``
set sizes) with O(1)-memory sketches.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from zipkin_tpu.ops.hashing import floor_log2


def new_registers(rows: int, precision: int = 11) -> jnp.ndarray:
    """Zeroed HLL registers: ``rows`` independent sketches of 2**precision
    registers each. Standard error ~= 1.04 / sqrt(2**precision)."""
    return jnp.zeros((rows, 1 << precision), jnp.uint8)


def update(
    registers: jnp.ndarray,
    row_ids: jnp.ndarray,
    hashes: jnp.ndarray,
    valid: jnp.ndarray,
) -> jnp.ndarray:
    """Scatter-max ``rho`` of each hash into ``registers[row, bucket]``.

    ``hashes`` are full-avalanche u32 (:func:`zipkin_tpu.ops.hashing.hash2`).
    Invalid lanes are routed to rho=0 which never lowers a register.
    """
    m = registers.shape[1]
    p = int(m).bit_length() - 1
    h = hashes.astype(jnp.uint32)
    bucket = (h >> jnp.uint32(32 - p)).astype(jnp.int32)
    rest = h & jnp.uint32((1 << (32 - p)) - 1)
    # rho = position of the leftmost 1-bit in the low (32-p) bits, counting
    # from the top of that field; all-zero rest -> (32-p)+1.
    rho = jnp.where(
        rest == 0,
        jnp.int32(32 - p + 1),
        jnp.int32(32 - p) - floor_log2(jnp.maximum(rest, 1)),
    )
    rho = jnp.where(valid, rho, 0).astype(jnp.uint8)
    return registers.at[row_ids, bucket].max(rho)


def merge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lossless sketch union — the cross-chip combiner (pmax over ICI)."""
    return jnp.maximum(a, b)


def estimate(registers: jnp.ndarray) -> jnp.ndarray:
    """Cardinality estimate per row, shape ``[rows]`` float32.

    Flajolet et al. bias-corrected estimator with linear counting below
    2.5m. The CLASSICAL 32-bit large-range correction
    (``-2^32 ln(1 - E/2^32)``) is deliberately ABSENT: it models an
    estimator whose raw value saturates at the count of distinct hash
    values, but this implementation's rho convention (all-zero rest ->
    33-p, :func:`update`) keeps the raw estimator nearly unbiased deep
    into hash-space saturation. Measured against exact register law +
    a 1e9-draw simulation (r5, tests/test_ops_sketches.py): bias -0.4%
    at n=5e8, -1.2% at n=1e9, -4.4% at 2e9 — all well inside the
    3*stderr gate at p=11 (6.9%) — while applying the classical
    correction at n=1e9 would ADD +13.6% error. Beyond ~4e9 (where the
    bias passes -14%) a 64-bit hash path would be needed, not a
    correction term.
    """
    m = registers.shape[-1]
    alpha = _alpha(m)
    regs = registers.astype(jnp.float32)
    harm = jnp.sum(jnp.exp2(-regs), axis=-1)
    raw = alpha * m * m / harm
    zeros = jnp.sum(registers == 0, axis=-1).astype(jnp.float32)
    linear = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    use_linear = (raw <= 2.5 * m) & (zeros > 0)
    return jnp.where(use_linear, linear, raw)


def _alpha(m: int) -> float:
    if m == 16:
        return 0.673
    if m == 32:
        return 0.697
    if m == 64:
        return 0.709
    return 0.7213 / (1.0 + 1.079 / m)


def standard_error(precision: int) -> float:
    return 1.04 / math.sqrt(1 << precision)


# analytic |bias| of the raw estimator vs distinct count, measured by the
# r5 register-law study (PROFILE_r05 §5, pinned by tests/test_ops_sketches
# billion-scale test): 32-bit hash-space saturation drives it, so the
# curve is a function of n (not of m) until the 4e9 hash boundary.
BIAS_CURVE = (
    (5.0e8, 0.004),
    (1.0e9, 0.012),
    (2.0e9, 0.044),
    (4.0e9, 0.140),
)


def bias_fraction(n: float) -> float:
    """|bias|/n of the raw estimator at ``n`` distinct values — log-log
    interpolation of :data:`BIAS_CURVE`, clamped to the measured range."""
    pts = BIAS_CURVE
    if n <= pts[0][0]:
        return pts[0][1]
    if n >= pts[-1][0]:
        return pts[-1][1]
    for (n0, b0), (n1, b1) in zip(pts, pts[1:]):
        if n <= n1:
            t = (math.log(n) - math.log(n0)) / (math.log(n1) - math.log(n0))
            return math.exp(
                math.log(b0) + t * (math.log(b1) - math.log(b0))
            )
    return pts[-1][1]  # pragma: no cover - loop always returns


def envelope_max(precision: int = 11) -> float:
    """Largest cardinality the estimator serves inside its operating
    envelope: where the analytic |bias| crosses HALF the 3·stderr noise
    gate — past that, bias stops hiding inside the noise floor and
    starts dominating the reported number. DERIVED from the measured
    curve (inverse of :func:`bias_fraction` by the same log-log
    segments), not hard-coded: ≈1.8e9 at p=11 (gate 3.45%). The bias is
    hash-width-driven, so only the gate moves with ``precision``;
    estimates beyond 4e9 are out of envelope at any precision (the
    32-bit hash boundary — a 64-bit path, not a correction, past it).
    """
    gate = 1.5 * standard_error(precision)
    pts = BIAS_CURVE
    if gate <= pts[0][1]:
        return pts[0][0]
    for (n0, b0), (n1, b1) in zip(pts, pts[1:]):
        if gate <= b1:
            t = (math.log(gate) - math.log(b0)) / (
                math.log(b1) - math.log(b0)
            )
            return math.exp(
                math.log(n0) + t * (math.log(n1) - math.log(n0))
            )
    return pts[-1][0]
