"""Windowed dependency linking on device.

The reference computes service dependency links two ways: online tree
walks in ``zipkin2/internal/DependencyLinker.java`` (the InMemory path,
SURVEY.md §3.5) or an **offline batch job** (the zipkin-dependencies Spark
job) writing daily link tables. The TPU design follows the batch shape —
it is the parallel-friendly one — but runs it on-device in milliseconds
over the retained span window, so links are as fresh as the last ingest.

Algorithm over a columnar span window (all arrays fixed-shape ``[n]``):

1. **Parent resolution** — three sort-merge equal-joins on
   (trace, span-id) keys replace the host's hash-map tree build:
   a shared (server-half) span resolves its own id against non-shared
   spans (its client half); a normal span resolves its ``parentId``
   preferring the shared rendition (the server half is the closer tree
   node, matching ``zipkin2/internal/SpanNode.java``'s index preference),
   falling back to non-shared. All joins ride ONE value-carrying
   ``lax.sort`` of the union; per-run first-wins candidates are
   segmented min scans over the contiguous sorted runs — no
   data-dependent control flow, no gather passes.
2. **has-child** marks (scatter-max) implement rule 1 of the linker
   (a CLIENT span with children defers to its server half).
3. **Nearest RPC ancestor** by pointer doubling: ``jump[i]`` points to the
   nearest ancestor-or-self with a kind; squaring it until the fixed
   point (convergence-bounded ``lax.while_loop``, pass count capped at
   ceil(log2 n) so malformed cycles terminate) resolves chains of any
   depth — the device analog of ``_find_rpc_ancestor``'s while-loop.
4. **Rule application** is a pure vectorized select emitting up to two
   edges per span (main + rule-6b backfill), then a scatter-add into the
   ``[services, services]`` call/error matrices — which merge across
   shards by ``psum``.

Parity with the host oracle is asserted span-for-span in
tests/test_ops_linker.py over the DependencyLinkerTest edge-case matrix.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp

from zipkin_tpu.ops.segments import segment_starts

def _doubling_passes(n: int) -> int:
    """Pointer-doubling passes needed to resolve ancestor chains of ANY
    depth in an n-lane window: ceil(log2(n+1)). A fixed small cap would
    silently misclassify spans deeper than 2**cap (legit 200-deep retry
    chains exist), dropping their edges."""
    return max((n).bit_length(), 1)

KIND_NONE, KIND_CLIENT, KIND_SERVER, KIND_PRODUCER, KIND_CONSUMER = range(5)


class LinkInput(NamedTuple):
    """Columnar span window (see zipkin_tpu.tpu.columnar.SpanColumns)."""

    trace_h: jnp.ndarray  # u32 hash of the full 128-bit trace id
    tl0: jnp.ndarray  # u32 low lanes of the trace id (join key lanes)
    tl1: jnp.ndarray
    s0: jnp.ndarray  # u32 span id lanes
    s1: jnp.ndarray
    p0: jnp.ndarray  # u32 parent id lanes (0,0 = absent)
    p1: jnp.ndarray
    shared: jnp.ndarray  # bool — server half of a shared-id RPC pair
    kind: jnp.ndarray  # i32 KIND_*
    svc: jnp.ndarray  # i32 local service id (0 = unknown)
    rsvc: jnp.ndarray  # i32 remote service id (0 = unknown)
    err: jnp.ndarray  # bool — span has an "error" tag
    valid: jnp.ndarray  # bool — lane holds a live span
    # insertion sequence: a permutation of [0, n) where LOWER = inserted
    # EARLIER. The host tree builder's tie-breaks are first-wins in
    # insertion order; for a circular ring the lane index stops tracking
    # insertion order after the first wrap, so the ring view derives age
    # from (lane - ring_pos) % R. None (plain batch windows) = lane order.
    seq: jnp.ndarray = None


def _run_starts(key_lanes: Sequence[jnp.ndarray]) -> jnp.ndarray:
    change = jnp.zeros(key_lanes[0].shape[0], bool).at[0].set(True)
    for lane in key_lanes:
        change = change | jnp.asarray(segment_starts(lane))
    return change


def _run_min(values: jnp.ndarray, change: jnp.ndarray, none: int) -> jnp.ndarray:
    """Per-run min of ``values`` over runs delimited by ``change`` (sorted
    lanes). ``none`` is the empty sentinel (values >= none mean absent);
    returns -1 for absent. Values are insertion-sequence ranks (see
    LinkInput.seq), so min = FIRST in insertion order, matching the host
    tree builder's first-wins candidate choice — even after a circular
    ring wraps and lane index stops tracking age."""
    run_id = jnp.cumsum(change.astype(jnp.int32)) - 1
    seg = jnp.full(values.shape[0], none, values.dtype).at[run_id].min(values)
    out = seg[run_id]
    return jnp.where(out >= none, -1, out)


def _run_min_ladder(channel_runs, none: int):
    """Segmented run-min BROADCAST via a flat shift-doubling ladder:
    each channel carries its own run identity; every doubling step is
    one fused elementwise kernel (min over self + left/right neighbor
    at distance d, guarded by run-id equality) over ALL channels.

    This replaces the associative-scan formulation (r5 chip A/B,
    benchmarks/resolve_variants.py + PROFILE_r05): the scans' tree
    sweeps cost ~15 ms of the 23.6 ms resolve at ring 2^18 and resisted
    every restructuring (channel fusion, reverse=True, forward-only
    dual-sort all measured flat or worse — XLA already CSEs identical
    scans); the ladder's ceil(log2 n) fused steps measure 18.9 ms for
    the whole resolve (-4.7 ms) and 29.6 ms for the full link context
    (-6.6 ms). ``channel_runs`` = [(values, run_id), ...]."""
    n = channel_runs[0][0].shape[0]
    vs = [v for v, _ in channel_runs]
    rids = [r for _, r in channel_runs]
    inf = jnp.int32(none)
    steps = max(int(n - 1).bit_length(), 1)
    for k in range(steps):
        d = 1 << k
        if d >= n:
            break
        new = []
        for v, rid in zip(vs, rids):
            rid_l = jnp.concatenate(
                [jnp.full((d,), -1, jnp.int32), rid[:-d]]
            )
            rid_r = jnp.concatenate(
                [rid[d:], jnp.full((d,), -2, jnp.int32)]
            )
            lv = jnp.concatenate([jnp.full((d,), inf), v[:-d]])
            rv = jnp.concatenate([v[d:], jnp.full((d,), inf)])
            v = jnp.minimum(v, jnp.where(rid == rid_l, lv, inf))
            v = jnp.minimum(v, jnp.where(rid == rid_r, rv, inf))
            new.append(v)
        vs = new
    return [jnp.where(v >= none, -1, v) for v in vs]


def union_key_lanes(x: LinkInput):
    """The four u32 sort-key lanes of the 2n-lane join union (table half
    then query half), invalid lanes keyed 0xFFFFFFFF."""
    has_parent = ((x.p0 | x.p1) != 0) & x.valid
    anyvalid = jnp.concatenate([x.valid, has_parent])

    def lane(t, q):
        return jnp.where(
            anyvalid,
            jnp.concatenate([t.astype(jnp.uint32), q.astype(jnp.uint32)]),
            jnp.uint32(0xFFFFFFFF),
        )

    # Join identity: (trace_h, id). trace_h is a 32-bit avalanche hash of
    # the FULL 128-bit trace id — dropping the exact low-64 lanes from
    # the sort key cuts the lexsort from 6 to 4 passes, and a false join
    # needs a 32-bit trace-hash collision AND a 64-bit span-id match
    # within one ring (~2^-40 per colliding pair; the reference tolerates
    # far larger sketch error elsewhere).
    id_lanes = [
        lane(x.trace_h, x.trace_h),
        lane(x.s0, x.p0),
        lane(x.s1, x.p1),
    ]
    # service lane: table lanes carry their OWN service, query lanes the
    # CHILD's — so a run of the (id, svc) composite matches candidates
    # whose service equals the child's, the endpoint-aware preference of
    # SpanNode._choose_parent. svc is the least-significant sort key, so
    # plain (id) runs stay contiguous and both granularities come from
    # ONE sort.
    svc_lane = lane(x.svc.astype(jnp.uint32), x.svc.astype(jnp.uint32))
    return id_lanes, svc_lane, has_parent


def _seg_min_scan(vals, flags, reverse=False):
    """Segmented inclusive min scan over contiguous runs (reset where
    ``flags``). The scans replace the scatter-min/gather formulation:
    at ring capacity 2^18 the scatter variant measured 59.3 ms for the
    whole resolve vs 23.6 ms with scans (benchmarks r4 A/B on chip)."""

    def combine(a, b):
        fa, va = a
        fb, vb = b
        return fa | fb, jnp.where(fb, vb, jnp.minimum(va, vb))

    if reverse:
        vals = jnp.flip(vals)
        flags = jnp.flip(flags)
    _, v = jax.lax.associative_scan(combine, (flags, vals))
    return jnp.flip(v) if reverse else v


def _run_min_bcast(vals, starts, none):
    """Per-run min broadcast to every lane of the run (sorted contiguous
    runs): forward segmented prefix-min covers [start..lane], backward
    covers [lane..end]; their minimum is the full-run min. ``none`` is
    the empty sentinel; absent runs return -1. Values are insertion-
    sequence ranks (see LinkInput.seq), so min = FIRST in insertion
    order, matching the host tree builder\'s first-wins candidate choice
    even after a circular ring wraps."""
    ends = jnp.concatenate([starts[1:], jnp.ones((1,), bool)])
    fwd = _seg_min_scan(vals, starts)
    bwd = _seg_min_scan(vals, ends, reverse=True)
    out = jnp.minimum(fwd, bwd)
    return jnp.where(out >= none, -1, out)


def resolve_parents(x: LinkInput) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Tree edges from id joins: returns (parent_row [n] with -1 for roots,
    has_child [n] bool).

    All three id joins (shared half -> client half, parent-id -> shared
    rendition, parent-id -> non-shared) ride ONE multi-operand
    ``lax.sort`` of a 2n-lane union — table lanes keyed by own
    (trace, span-id), query lanes keyed by (trace, parent-id) — that
    CARRIES the candidate values and selection flags through the sort.
    Everything after the sort is contiguous: run boundaries are
    adjacent-lane compares, per-run first-wins candidates are segmented
    min scans, and the SpanNode._choose_parent preference chain is
    evaluated in sorted space so only ONE combined candidate needs
    un-permuting.

    That shape is the r4 redesign of the fresh dependency read
    (VERDICT r3 order 1): the r3 formulation un-permuted three
    candidate arrays through gather/scatter passes and fixed-schedule
    pointer chases, costing 145.8 ms captured device time at ring
    capacity 2^18; this one measures 23.6 ms for the resolve and
    34.3 ms for the full link context (chip A/B, bit-identical output).
    """
    n = x.valid.shape[0]
    has_parent = ((x.p0 | x.p1) != 0) & x.valid
    nonshared = x.valid & ~x.shared
    sharedv = x.valid & x.shared
    # ALL spans with parents query the parent-id join — including shared
    # halves: a shared server span prefers its same-id client half, but
    # when that mate is absent it must fall back to its parentId exactly
    # like SpanNode.Builder does (found by the linker fuzz: a mateless
    # shared span previously became a root and re-attributed its edge)
    q_valid = has_parent

    id_lanes, svc_lane, _ = union_key_lanes(x)

    idx = jnp.arange(n, dtype=jnp.int32)
    # candidate VALUES are insertion-sequence ranks, not lane indices —
    # run-min then picks the first-INSERTED candidate (host first-wins)
    # regardless of where the ring cursor has wrapped to
    seq = idx if x.seq is None else x.seq.astype(jnp.int32)
    rank_to_idx = jnp.zeros(n, jnp.int32).at[seq].set(idx)
    sent = 2 * n  # run-min "absent" sentinel
    far = jnp.full((n,), sent, jnp.int32)
    val_sh = jnp.concatenate([jnp.where(sharedv, seq, sent), far])
    val_ns = jnp.concatenate([jnp.where(nonshared, seq, sent), far])
    # query half carries the span\'s shared flag so the sorted-space
    # selection can pick fallback-vs-preference without a second unsort
    qsh = jnp.concatenate([jnp.zeros((n,), bool), sharedv])
    uidx = jnp.arange(2 * n, dtype=jnp.int32)

    # zt-lint: disable=ZT07 — fresh entrypoints reach this only through dependency_links' ctx=None fallback, which they never take (they always pass the delta ctx from fresh_link_context); the full-ring sort runs at rollup cadence / cold rebuilds only
    sorted_ops = jax.lax.sort(
        tuple(id_lanes) + (svc_lane, val_sh, val_ns, qsh, uidx), num_keys=4
    )
    s_ids = sorted_ops[:3]
    s_svc, sh_s, ns_s, s_qsh, sord = sorted_ops[3:]

    coarse = _run_starts(list(s_ids))
    fine = coarse | jnp.asarray(segment_starts(s_svc))

    rid_c = jnp.cumsum(coarse.astype(jnp.int32))
    rid_f = jnp.cumsum(fine.astype(jnp.int32))
    # all three run-min broadcasts ride ONE shift-doubling ladder
    r_sh_any, r_ns_any, r_sh_fine = _run_min_ladder(
        [(sh_s, rid_c), (ns_s, rid_c), (sh_s, rid_f)], sent
    )  # any shared / first non-shared / shared with same service

    # Parent-id resolution in SpanNode._choose_parent preference order,
    # evaluated PER SORTED LANE: 1) first shared with the child\'s
    # service, 2) the FIRST non-shared (primary_by_id — the host never
    # service-scans non-shared candidates, it checks whether THE first
    # one\'s service matches), 3) first shared any service, 4) the first
    # non-shared regardless. s_svc carries the child\'s service on query
    # lanes (garbage on table lanes — never selected there).
    primary = r_ns_any
    p_idx = rank_to_idx[jnp.where(primary >= 0, primary, 0)]
    primary_svc = x.svc[p_idx].astype(jnp.uint32)
    primary_matches = (primary >= 0) & (primary_svc == s_svc)
    by_parent_id = primary
    by_parent_id = jnp.where(r_sh_any >= 0, r_sh_any, by_parent_id)
    by_parent_id = jnp.where(primary_matches, primary, by_parent_id)
    by_parent_id = jnp.where(r_sh_fine >= 0, r_sh_fine, by_parent_id)

    # per-lane combined candidate: table lanes only ever need the first
    # non-shared of their OWN-id run (the shared->client join); query
    # lanes of SHARED spans need the same of their PARENT-id run (the
    # host builder\'s shared fallback consults only primary_by_id — no
    # endpoint preference); query lanes of normal spans take the full
    # preference chain
    is_table = sord < n
    combined = jnp.where(is_table | s_qsh, r_ns_any, by_parent_id)

    # ONE unsort: scatter the combined rank, convert rank -> lane index
    inv = jnp.zeros(2 * n, jnp.int32).at[sord].set(combined)
    un = jnp.where(inv >= 0, rank_to_idx[jnp.where(inv >= 0, inv, 0)], -1)

    j_shared = jnp.where(sharedv, un[:n], -1)
    q = jnp.where(q_valid, un[n:], -1)
    parent = jnp.where(sharedv, jnp.where(j_shared >= 0, j_shared, q), q)
    # a span must not become its own parent (self-parent -> dangling root,
    # as the host builder treats a self-referential choice)
    parent = jnp.where(parent == idx, -1, parent)
    parent = jnp.where(x.valid, parent, -1)

    has_child = (
        jnp.zeros(n, jnp.int32)
        .at[jnp.where(parent >= 0, parent, 0)]
        .max(jnp.where(parent >= 0, 1, 0))
    )
    return parent, has_child.astype(bool)


def chase_ancestors(
    parent: jnp.ndarray, kind: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Both pointer-doubling chases of the link rules in ONE
    convergence-bounded loop: returns (anc [n] — nearest strict ancestor
    with a kind, else -1; root_ok [n] bool — the parent chain terminates
    at a root).

    Doubling squares two pointer arrays per pass: ``root`` chases
    ``parent`` toward the sentinel, ``jump`` chases the
    nearest-kinded-ancestor-or-self relation. A fixed
    ceil(log2(n)) schedule costs 19 passes at ring capacity 2^18 —
    70.6 ms captured device time, HALF the 145.8 ms fresh link-context
    rebuild (benchmarks/profile_link_ctx.py) — yet real trace forests
    are tens deep, converged after 5-8 passes. The lax.while_loop stops
    at the fixed point (captured: 10.7 ms, 6.6x) and stays EXACT for
    any depth: the fixed pass count remains as a bound only so
    malformed parent CYCLES (which never reach a fixed point — a
    3-cycle's pointers orbit forever) still terminate; capped cyclic
    lanes end mid-cycle, never at the sentinel, so ``root_ok`` stays
    False for them exactly as the host tree builder's reachability
    does (found by the linker fuzz).
    """
    n = parent.shape[0]
    sent = n
    par = jnp.where(parent >= 0, parent, sent)
    kind_ext = jnp.concatenate([kind, jnp.zeros((1,), kind.dtype)])
    par_ext = jnp.concatenate([par, jnp.full((1,), sent, par.dtype)])

    # jump[i] = i if span i has a kind, else its parent (toward the root)
    jump = jnp.where(kind_ext != 0, jnp.arange(n + 1), par_ext)
    jump = jump.at[sent].set(sent)
    root = par_ext
    max_passes = _doubling_passes(n)

    def cond(c):
        i, _, _, changed = c
        return changed & (i < max_passes)

    def body(c):
        i, jump, root, _ = c
        j2 = jump[jump]
        r2 = root[root]
        changed = jnp.any(j2 != jump) | jnp.any(r2 != root)
        return i + 1, j2, r2, changed

    # initial `changed` derives from the (possibly shard-varying) data so
    # the while carry types stay consistent under shard_map; jump holds
    # only non-negative lane ids, so this is always True
    _, jump, root, _ = jax.lax.while_loop(
        cond, body, (jnp.int32(0), jump, root, jnp.any(jump >= 0))
    )

    anc = jump[par]  # start the walk at the parent (strict ancestor)
    anc = jnp.where(anc == sent, -1, anc)
    # if the chain ended on a kindless root, there is no RPC ancestor
    anc = jnp.where(
        (anc >= 0) & (kind_ext[jnp.where(anc >= 0, anc, 0)] != 0), anc, -1
    )
    return anc, root[:n] == sent


def reaches_root(parent: jnp.ndarray) -> jnp.ndarray:
    """[n] bool: the parent chain terminates at a root (any depth).
    Malformed cyclic subgraphs (e.g. a span pair parenting each other
    through a shared-id join) never terminate — the host tree builder
    leaves them unreachable from the synthetic root, so its traversal
    never emits their links; this mask is the device analog (found by
    the linker fuzz)."""
    _, ok = chase_ancestors(parent, jnp.zeros_like(parent))
    return ok


def nearest_rpc_ancestor(
    parent: jnp.ndarray, kind: jnp.ndarray
) -> jnp.ndarray:
    """Row index of the nearest strict ancestor with a kind, else -1."""
    anc, _ = chase_ancestors(parent, kind)
    return anc


class LinkContext(NamedTuple):
    """Window-INDEPENDENT link evaluation of a span window: everything
    expensive (the parent join sort, pointer-doubling ancestors,
    reachability) distilled to per-lane edge candidates. Cache one per
    state version and apply any number of cheap windowed emits against
    it (zipkin_tpu.parallel.sharded caches it per write_version — the
    dependency query then costs an elementwise mask + scatter, not a
    re-sort of the ring)."""

    par_svc: jnp.ndarray  # i32 — main edge parent service (post rule 6)
    child_svc: jnp.ndarray  # i32 — main edge child service
    ok: jnp.ndarray  # bool — main edge passes every non-window rule
    err: jnp.ndarray  # bool — ok and the span carries an error tag
    anc_svc: jnp.ndarray  # i32 — nearest RPC ancestor service
    local: jnp.ndarray  # i32 — local service (rule 6b child)
    back: jnp.ndarray  # bool — rule 6b backfill passes non-window rules


def link_context(x: LinkInput) -> LinkContext:
    """Evaluate all link rules except the time window.

    Parent/ancestor joins run over every ``x.valid`` lane, so a windowed
    query still resolves tree context from outside the window — matching
    the reference's whole-trace linking (InMemory getDependencies links
    full traces whose span timestamps intersect the window, SURVEY.md
    §3.5).

    This is the FROM-SCRATCH formulation (full union sort + run-min
    ladder): the oracle the incremental delta path
    (ops/delta_linker.py) must match bit-for-bit, and the reference
    every parity test fuzzes against. Production fresh reads ride the
    delta formulation; this one remains the ground truth.
    """
    parent, has_child = resolve_parents(x)
    anc, root_ok = chase_ancestors(parent, jnp.where(x.valid, x.kind, 0))
    return apply_rules(x, parent, has_child, anc, root_ok)


def apply_rules(
    x: LinkInput,
    parent: jnp.ndarray,
    has_child: jnp.ndarray,
    anc: jnp.ndarray,
    root_ok: jnp.ndarray,
) -> LinkContext:
    """The pure elementwise rule half of :func:`link_context`: turn a
    resolved tree (parent rows, child marks, nearest-RPC ancestors,
    root reachability) into per-lane edge candidates. Shared verbatim by
    the from-scratch resolve and the incremental delta resolve so the
    two can only diverge in tree resolution, never in rule semantics."""
    anc_svc = jnp.where(anc >= 0, x.svc[jnp.where(anc >= 0, anc, 0)], 0)

    local, remote = x.svc, x.rsvc
    kind = x.kind

    # rule 1: client span with children defers to its server half;
    # spans in parent cycles never emit (host-traversal reachability)
    live = x.valid & root_ok
    live = live & ~((kind == KIND_CLIENT) & has_child)
    # rule 2: kindless spans with both sides known act like clients
    keff = jnp.where(
        (kind == KIND_NONE) & (local > 0) & (remote > 0), KIND_CLIENT, kind
    )
    live = live & (keff != KIND_NONE)

    is_server_like = (keff == KIND_SERVER) | (keff == KIND_CONSUMER)
    par_svc = jnp.where(is_server_like, remote, local)
    child_svc = jnp.where(is_server_like, local, remote)

    # rule 3: root server with unknown caller
    live = live & ~((keff == KIND_SERVER) & (parent < 0) & (remote == 0))

    is_messaging = (keff == KIND_PRODUCER) | (keff == KIND_CONSUMER)
    # rule 5: messaging needs both sides known, no tree walk through brokers
    live = live & ~(is_messaging & ((par_svc == 0) | (child_svc == 0)))

    # rule 6: RPC spans resolve the parent via the nearest RPC ancestor
    is_rpc = (keff == KIND_CLIENT) | (keff == KIND_SERVER)
    use_anc = is_rpc & (anc_svc > 0) & ((keff == KIND_SERVER) | (par_svc == 0))
    par_svc = jnp.where(use_anc, anc_svc, par_svc)

    main_ok = live & (par_svc > 0) & (child_svc > 0)

    # rule 6b: client whose service differs from its RPC ancestor implies an
    # uninstrumented hop — backfill ancestor->client (never an error)
    back_ok = (
        live
        & (keff == KIND_CLIENT)
        & (local > 0)
        & (anc_svc > 0)
        & (anc_svc != local)
    )
    return LinkContext(
        par_svc=par_svc, child_svc=child_svc, ok=main_ok,
        err=main_ok & x.err, anc_svc=anc_svc, local=local, back=back_ok,
    )


def link_edges(x: LinkInput, emit: jnp.ndarray = None):
    """Per-lane link-rule evaluation with an emit mask applied: returns
    (par_svc, child_svc, main_ok, main_err, anc_svc, local, back_ok)."""
    if emit is None:
        emit = x.valid
    ctx = link_context(x)
    return (
        ctx.par_svc, ctx.child_svc, ctx.ok & emit, ctx.err & emit,
        ctx.anc_svc, ctx.local, ctx.back & emit,
    )


def emit_links(
    ctx: LinkContext, emit: jnp.ndarray, num_services: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter a context's edges for the lanes in ``emit`` — the cheap
    half of a windowed dependency query (no sorts, no joins)."""
    s = num_services
    calls = jnp.zeros((s, s), jnp.uint32)
    errors = jnp.zeros((s, s), jnp.uint32)
    pc = jnp.clip(ctx.par_svc, 0, s - 1)
    cc = jnp.clip(ctx.child_svc, 0, s - 1)
    calls = calls.at[pc, cc].add((ctx.ok & emit).astype(jnp.uint32))
    errors = errors.at[pc, cc].add((ctx.err & emit).astype(jnp.uint32))
    bc = jnp.clip(ctx.anc_svc, 0, s - 1)
    lc = jnp.clip(ctx.local, 0, s - 1)
    calls = calls.at[bc, lc].add((ctx.back & emit).astype(jnp.uint32))
    return calls, errors


def link_window(
    x: LinkInput, num_services: int, emit: jnp.ndarray = None
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dependency links over one span window.

    Returns (calls, errors) — ``[num_services, num_services]`` uint32
    matrices indexed by interned service id (0 = unknown; row/col 0 is
    never emitted). Merge across shards/windows by addition (psum).
    """
    if emit is None:
        emit = x.valid
    return emit_links(link_context(x), emit, num_services)


def link_window_bucketed(
    x: LinkInput,
    num_services: int,
    slot: jnp.ndarray,
    num_slots: int,
    emit: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Same rules, but each emitting span scatters its edges into the
    time-bucket ``slot[i]`` of its OWN timestamp — the device form of the
    reference's per-day dependency rollup (links attributed to the day of
    the child span, SURVEY.md §2.3 cassandra ``dependency`` table)."""
    return emit_links_bucketed(
        link_context(x), slot, num_slots, emit, num_services
    )


def emit_links_bucketed(
    ctx: LinkContext,
    slot: jnp.ndarray,
    num_slots: int,
    emit: jnp.ndarray,
    num_services: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The cheap scatter half of :func:`link_window_bucketed` against a
    precomputed context — the rollup reuses the incremental advance's
    resolve instead of paying a second from-scratch link_context."""
    s = num_services
    d = jnp.clip(slot.astype(jnp.int32), 0, num_slots - 1)
    calls = jnp.zeros((num_slots, s, s), jnp.uint32)
    errors = jnp.zeros((num_slots, s, s), jnp.uint32)
    pc = jnp.clip(ctx.par_svc, 0, s - 1)
    cc = jnp.clip(ctx.child_svc, 0, s - 1)
    calls = calls.at[d, pc, cc].add((ctx.ok & emit).astype(jnp.uint32))
    errors = errors.at[d, pc, cc].add((ctx.err & emit).astype(jnp.uint32))
    bc = jnp.clip(ctx.anc_svc, 0, s - 1)
    lc = jnp.clip(ctx.local, 0, s - 1)
    calls = calls.at[d, bc, lc].add((ctx.back & emit).astype(jnp.uint32))
    return calls, errors
