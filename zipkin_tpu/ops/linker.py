"""Windowed dependency linking on device.

The reference computes service dependency links two ways: online tree
walks in ``zipkin2/internal/DependencyLinker.java`` (the InMemory path,
SURVEY.md §3.5) or an **offline batch job** (the zipkin-dependencies Spark
job) writing daily link tables. The TPU design follows the batch shape —
it is the parallel-friendly one — but runs it on-device in milliseconds
over the retained span window, so links are as fresh as the last ingest.

Algorithm over a columnar span window (all arrays fixed-shape ``[n]``):

1. **Parent resolution** — three sort-merge equal-joins on
   (trace, span-id) keys replace the host's hash-map tree build:
   a shared (server-half) span resolves its own id against non-shared
   spans (its client half); a normal span resolves its ``parentId``
   preferring the shared rendition (the server half is the closer tree
   node, matching ``zipkin2/internal/SpanNode.java``'s index preference),
   falling back to non-shared. Each join is one lexsort of the union +
   a per-run max — no data-dependent control flow.
2. **has-child** marks (scatter-max) implement rule 1 of the linker
   (a CLIENT span with children defers to its server half).
3. **Nearest RPC ancestor** by pointer doubling: ``jump[i]`` points to the
   nearest ancestor-or-self with a kind; squaring it ``ITERS`` times
   resolves chains up to depth ``2**ITERS`` in O(log depth) passes —
   the device analog of ``_find_rpc_ancestor``'s while-loop.
4. **Rule application** is a pure vectorized select emitting up to two
   edges per span (main + rule-6b backfill), then a scatter-add into the
   ``[services, services]`` call/error matrices — which merge across
   shards by ``psum``.

Parity with the host oracle is asserted span-for-span in
tests/test_ops_linker.py over the DependencyLinkerTest edge-case matrix.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp

from zipkin_tpu.ops.segments import segment_starts

# pointer-doubling passes: resolves ancestor chains up to depth 2**ITERS
ITERS = 7

KIND_NONE, KIND_CLIENT, KIND_SERVER, KIND_PRODUCER, KIND_CONSUMER = range(5)


class LinkInput(NamedTuple):
    """Columnar span window (see zipkin_tpu.tpu.columnar.SpanColumns)."""

    trace_h: jnp.ndarray  # u32 hash of the full 128-bit trace id
    tl0: jnp.ndarray  # u32 low lanes of the trace id (join key lanes)
    tl1: jnp.ndarray
    s0: jnp.ndarray  # u32 span id lanes
    s1: jnp.ndarray
    p0: jnp.ndarray  # u32 parent id lanes (0,0 = absent)
    p1: jnp.ndarray
    shared: jnp.ndarray  # bool — server half of a shared-id RPC pair
    kind: jnp.ndarray  # i32 KIND_*
    svc: jnp.ndarray  # i32 local service id (0 = unknown)
    rsvc: jnp.ndarray  # i32 remote service id (0 = unknown)
    err: jnp.ndarray  # bool — span has an "error" tag
    valid: jnp.ndarray  # bool — lane holds a live span


def _run_max(values: jnp.ndarray, key_lanes: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Per-run max of ``values`` over runs of equal composite keys (sorted)."""
    change = jnp.zeros(values.shape[0], bool).at[0].set(True)
    for lane in key_lanes:
        change = change | jnp.asarray(segment_starts(lane))
    run_id = jnp.cumsum(change.astype(jnp.int32)) - 1
    seg = jnp.full(values.shape[0], -1, values.dtype).at[run_id].max(values)
    return seg[run_id]


def resolve_parents(x: LinkInput) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Tree edges from id joins: returns (parent_row [n] with -1 for roots,
    has_child [n] bool).

    All three id joins (shared half -> client half, parent-id -> shared
    rendition, parent-id -> non-shared) ride ONE lexsort of a 2n-lane
    union — table lanes keyed by own (trace, span-id), query lanes keyed
    by (trace, parent-id) — with per-run maxima taken separately over
    shared and non-shared table indices. The r2 profile capture showed the
    original three independent sort-merge joins dominating the rollup
    program (PROFILE_r02.md); one sort does the work of three.
    """
    n = x.valid.shape[0]
    trace = (x.trace_h, x.tl0, x.tl1)
    has_parent = ((x.p0 | x.p1) != 0) & x.valid
    nonshared = x.valid & ~x.shared
    sharedv = x.valid & x.shared

    own_key = trace + (x.s0, x.s1)
    parent_key = trace + (x.p0, x.p1)
    q_valid = nonshared & has_parent

    anyvalid = jnp.concatenate([x.valid, q_valid])
    lanes = [
        jnp.where(
            anyvalid,
            jnp.concatenate([t.astype(jnp.uint32), q.astype(jnp.uint32)]),
            jnp.uint32(0xFFFFFFFF),
        )
        for t, q in zip(own_key, parent_key)
    ]
    idx = jnp.arange(n, dtype=jnp.int32)
    neg = jnp.full((n,), -1, jnp.int32)
    val_sh = jnp.concatenate([jnp.where(sharedv, idx, -1), neg])
    val_ns = jnp.concatenate([jnp.where(nonshared, idx, -1), neg])

    order = jnp.lexsort(tuple(lanes))
    sorted_lanes = [l[order] for l in lanes]
    rm_sh = _run_max(val_sh[order], sorted_lanes)
    rm_ns = _run_max(val_ns[order], sorted_lanes)
    inv = jnp.zeros(2 * n, jnp.int32)
    un_sh = inv.at[order].set(rm_sh)
    un_ns = inv.at[order].set(rm_ns)

    # table half: run-max over lanes sharing MY own id
    # query half: run-max over lanes whose own id equals MY parent id
    j_shared = jnp.where(sharedv, un_ns[:n], -1)
    j_to_shared = jnp.where(q_valid, un_sh[n:], -1)
    j_to_normal = jnp.where(q_valid, un_ns[n:], -1)
    # a span must not become its own parent (self-parent == root)
    self_idx = idx
    j_to_normal = jnp.where(j_to_normal == self_idx, -1, j_to_normal)

    parent = jnp.where(
        sharedv, j_shared, jnp.where(j_to_shared >= 0, j_to_shared, j_to_normal)
    )
    parent = jnp.where(x.valid, parent, -1)

    has_child = (
        jnp.zeros(n, jnp.int32)
        .at[jnp.where(parent >= 0, parent, 0)]
        .max(jnp.where(parent >= 0, 1, 0))
    )
    return parent, has_child.astype(bool)


def nearest_rpc_ancestor(
    parent: jnp.ndarray, kind: jnp.ndarray
) -> jnp.ndarray:
    """Row index of the nearest strict ancestor with a kind, else -1.

    Pointer doubling with a sentinel row ``n`` standing in for "none".
    """
    n = parent.shape[0]
    sent = n
    par = jnp.where(parent >= 0, parent, sent)
    kind_ext = jnp.concatenate([kind, jnp.zeros((1,), kind.dtype)])
    par_ext = jnp.concatenate([par, jnp.full((1,), sent, par.dtype)])

    # jump[i] = i if span i has a kind, else its parent (toward the root)
    jump = jnp.where(kind_ext != 0, jnp.arange(n + 1), par_ext)
    jump = jump.at[sent].set(sent)
    for _ in range(ITERS):
        jump = jump[jump]

    anc = jump[par]  # start the walk at the parent (strict ancestor)
    anc = jnp.where(anc == sent, -1, anc)
    # if the chain ended on a kindless root, there is no RPC ancestor
    anc = jnp.where((anc >= 0) & (kind_ext[jnp.where(anc >= 0, anc, 0)] != 0), anc, -1)
    return anc


def link_edges(x: LinkInput, emit: jnp.ndarray = None):
    """Per-lane link-rule evaluation shared by the flat and bucketed
    scatters: returns (par_svc, child_svc, main_ok, main_err, anc_svc,
    local, back_ok).

    ``emit`` restricts which spans may EMIT edges; parent/ancestor joins
    always run over every ``x.valid`` lane, so a windowed query still
    resolves tree context from outside the window — matching the
    reference's whole-trace linking (InMemory getDependencies links full
    traces whose span timestamps intersect the window, SURVEY.md §3.5).
    """
    if emit is None:
        emit = x.valid
    parent, has_child = resolve_parents(x)
    anc = nearest_rpc_ancestor(parent, jnp.where(x.valid, x.kind, 0))
    anc_svc = jnp.where(anc >= 0, x.svc[jnp.where(anc >= 0, anc, 0)], 0)

    local, remote = x.svc, x.rsvc
    kind = x.kind

    # rule 1: client span with children defers to its server half
    live = emit & x.valid & ~((kind == KIND_CLIENT) & has_child)
    # rule 2: kindless spans with both sides known act like clients
    keff = jnp.where(
        (kind == KIND_NONE) & (local > 0) & (remote > 0), KIND_CLIENT, kind
    )
    live = live & (keff != KIND_NONE)

    is_server_like = (keff == KIND_SERVER) | (keff == KIND_CONSUMER)
    par_svc = jnp.where(is_server_like, remote, local)
    child_svc = jnp.where(is_server_like, local, remote)

    # rule 3: root server with unknown caller
    live = live & ~((keff == KIND_SERVER) & (parent < 0) & (remote == 0))

    is_messaging = (keff == KIND_PRODUCER) | (keff == KIND_CONSUMER)
    # rule 5: messaging needs both sides known, no tree walk through brokers
    live = live & ~(is_messaging & ((par_svc == 0) | (child_svc == 0)))

    # rule 6: RPC spans resolve the parent via the nearest RPC ancestor
    is_rpc = (keff == KIND_CLIENT) | (keff == KIND_SERVER)
    use_anc = is_rpc & (anc_svc > 0) & ((keff == KIND_SERVER) | (par_svc == 0))
    par_svc = jnp.where(use_anc, anc_svc, par_svc)

    main_ok = live & (par_svc > 0) & (child_svc > 0)
    main_err = main_ok & x.err

    # rule 6b: client whose service differs from its RPC ancestor implies an
    # uninstrumented hop — backfill ancestor->client (never an error)
    back_ok = (
        live
        & (keff == KIND_CLIENT)
        & (local > 0)
        & (anc_svc > 0)
        & (anc_svc != local)
    )
    return par_svc, child_svc, main_ok, main_err, anc_svc, local, back_ok


def link_window(
    x: LinkInput, num_services: int, emit: jnp.ndarray = None
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dependency links over one span window.

    Returns (calls, errors) — ``[num_services, num_services]`` uint32
    matrices indexed by interned service id (0 = unknown; row/col 0 is
    never emitted). Merge across shards/windows by addition (psum).
    """
    par_svc, child_svc, main_ok, main_err, anc_svc, local, back_ok = link_edges(
        x, emit
    )
    s = num_services
    calls = jnp.zeros((s, s), jnp.uint32)
    errors = jnp.zeros((s, s), jnp.uint32)
    pc = jnp.clip(par_svc, 0, s - 1)
    cc = jnp.clip(child_svc, 0, s - 1)
    calls = calls.at[pc, cc].add(main_ok.astype(jnp.uint32))
    errors = errors.at[pc, cc].add(main_err.astype(jnp.uint32))
    bc = jnp.clip(anc_svc, 0, s - 1)
    lc = jnp.clip(local, 0, s - 1)
    calls = calls.at[bc, lc].add(back_ok.astype(jnp.uint32))
    return calls, errors


def link_window_bucketed(
    x: LinkInput,
    num_services: int,
    slot: jnp.ndarray,
    num_slots: int,
    emit: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Same rules, but each emitting span scatters its edges into the
    time-bucket ``slot[i]`` of its OWN timestamp — the device form of the
    reference's per-day dependency rollup (links attributed to the day of
    the child span, SURVEY.md §2.3 cassandra ``dependency`` table)."""
    par_svc, child_svc, main_ok, main_err, anc_svc, local, back_ok = link_edges(
        x, emit
    )
    s = num_services
    d = jnp.clip(slot.astype(jnp.int32), 0, num_slots - 1)
    calls = jnp.zeros((num_slots, s, s), jnp.uint32)
    errors = jnp.zeros((num_slots, s, s), jnp.uint32)
    pc = jnp.clip(par_svc, 0, s - 1)
    cc = jnp.clip(child_svc, 0, s - 1)
    calls = calls.at[d, pc, cc].add(main_ok.astype(jnp.uint32))
    errors = errors.at[d, pc, cc].add(main_err.astype(jnp.uint32))
    bc = jnp.clip(anc_svc, 0, s - 1)
    lc = jnp.clip(local, 0, s - 1)
    calls = calls.at[d, bc, lc].add(back_ok.astype(jnp.uint32))
    return calls, errors
