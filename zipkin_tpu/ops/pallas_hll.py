"""Pallas TPU kernel for the HLL register update (optional backend).

The sketch-update inner loop is where the reference hand-rolls Java
(``zipkin2/internal/WriteBuffer``-class code, SURVEY.md §2.7); the TPU
analog is a Pallas kernel below XLA. This one keeps the whole register
file VMEM-resident and applies the batch's scatter-max serially as
aligned (32, 128)-tile read-modify-writes, with the per-span indices
streamed through SMEM in chunks.

**Measured result (r2, real v5e chip): 10.25 ms vs XLA's 11.54 ms per
64k updates on [1025, 2048] u8 registers — ~11% faster.** XLA's
scatter lowering is already near-optimal for this shape, and the HLL
update is a small slice of the ingest step's device time — INGEST_r08
then showed the step itself is a minority of the wire-to-durable wall
next to host-side queue-wait (the coalesced ring dispatch in
tpu/mp_ingest.py attacks that), so the end-to-end win of a faster
scatter is well under 1% — which is why the default ingest path stays
on
:func:`zipkin_tpu.ops.hll.update` and this kernel is opt-in
(``TPU_PALLAS_HLL=1``). It is kept (a) as the measured evidence closing
SURVEY.md §7 P4's "Pallas only where profiling says so" question for
the sketch scatters, and (b) as the template for future kernels where
XLA's lowering is NOT optimal.

Run ``python -m benchmarks.pallas_bench`` on a TPU host to reproduce.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from zipkin_tpu.ops.hashing import floor_log2

LANES = 128  # lane tile (last dim)
SUB = 32  # u8 sublane tile
CHUNK = 2048  # spans per grid step (SMEM-resident indices)


def _kernel(r0_ref, rsub_ref, s0_ref, lane_ref, rho_ref, reg_in_ref, reg_ref):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _init():
        reg_ref[:, :] = reg_in_ref[:, :]

    row_iota = jax.lax.broadcasted_iota(jnp.int32, (SUB, LANES), 0)
    lane_iota = jax.lax.broadcasted_iota(jnp.int32, (SUB, LANES), 1)

    def body(i, _):
        r0 = pl.multiple_of(r0_ref[i], SUB)
        s0 = pl.multiple_of(s0_ref[i], LANES)
        mask = (row_iota == rsub_ref[i]) & (lane_iota == lane_ref[i])
        v = jnp.where(mask, rho_ref[i], 0)
        # u8 max is not legal in Mosaic; round-trip the tile through i32
        tile = reg_ref[pl.ds(r0, SUB), pl.ds(s0, LANES)].astype(jnp.int32)
        reg_ref[pl.ds(r0, SUB), pl.ds(s0, LANES)] = jnp.maximum(
            tile, v
        ).astype(jnp.uint8)
        return 0

    jax.lax.fori_loop(0, rho_ref.shape[0], body, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def update(
    registers: jnp.ndarray,
    row_ids: jnp.ndarray,
    hashes: jnp.ndarray,
    valid: jnp.ndarray,
    interpret: bool = False,
) -> jnp.ndarray:
    """Drop-in replacement for :func:`zipkin_tpu.ops.hll.update`.

    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU
    CI); shapes are padded internally to the (32, 128) u8 tile grid and
    the CHUNK boundary, so any register/batch shape is accepted.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows_n, m = registers.shape
    p = int(m).bit_length() - 1
    h = hashes.astype(jnp.uint32)
    bucket = (h >> jnp.uint32(32 - p)).astype(jnp.int32)
    rest = h & jnp.uint32((1 << (32 - p)) - 1)
    rho = jnp.where(
        rest == 0,
        jnp.int32(32 - p + 1),
        jnp.int32(32 - p) - floor_log2(jnp.maximum(rest, 1)),
    )
    rho = jnp.where(valid, rho, 0).astype(jnp.int32)

    # pad registers to the (sublane, lane) tile and the batch to the
    # chunk grid (precision < 7 gives m < 128 lanes)
    rpad = (-rows_n) % SUB
    cpad = (-m) % LANES
    regs = jnp.pad(registers, ((0, rpad), (0, cpad)))
    n = row_ids.shape[0]
    npad = (-n) % CHUNK
    rows = jnp.pad(row_ids.astype(jnp.int32), (0, npad))
    bucket = jnp.pad(bucket, (0, npad))
    rho = jnp.pad(rho, (0, npad))  # pad lanes carry rho 0: inert

    r0 = (rows // SUB) * SUB
    rsub = rows % SUB
    s0 = (bucket // LANES) * LANES
    lane = bucket % LANES

    smem = lambda: pl.BlockSpec(
        (CHUNK,), lambda i: (i,), memory_space=pltpu.SMEM
    )
    shape = regs.shape
    out = pl.pallas_call(
        _kernel,
        grid=((n + npad) // CHUNK,),
        in_specs=[
            smem(), smem(), smem(), smem(), smem(),
            pl.BlockSpec(shape, lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec(shape, lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct(shape, regs.dtype),
        interpret=interpret,
    )(r0, rsub, s0, lane, rho, regs)
    return out[:rows_n, :m]
