"""Sorted-segment reductions — the TPU idiom replacing scatter contention.

Many spans in one batch hit the same (service, spanName) key; raw
scatter-adds serialize on those collisions. The XLA-friendly pattern
(SURVEY.md §7 hard-part 3) is: sort by key once, then do segment sums /
cumulative sums over the sorted runs, which lower to fast scans.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def segment_starts(sorted_ids: jnp.ndarray) -> jnp.ndarray:
    """Boolean mask marking the first element of each run in sorted ids."""
    first = jnp.ones((1,) + sorted_ids.shape[1:], dtype=bool)
    return jnp.concatenate([first, sorted_ids[1:] != sorted_ids[:-1]], axis=0)


def run_start_indices(sorted_ids: jnp.ndarray) -> jnp.ndarray:
    """For each element, the index where its run of equal ids begins."""
    idx = jnp.arange(sorted_ids.shape[0])
    start_idx = jnp.where(segment_starts(sorted_ids), idx, 0)
    return jax.lax.associative_scan(jnp.maximum, start_idx)


def sorted_segment_cumsum(values: jnp.ndarray, sorted_ids: jnp.ndarray) -> jnp.ndarray:
    """Inclusive cumulative sum within each run of equal sorted ids.

    Two scans, no scatter: subtract from the global inclusive cumsum the
    global *exclusive* cumsum at each element's run start.
    """
    cum = jnp.cumsum(values, axis=0)
    excl = cum - values
    return cum - excl[run_start_indices(sorted_ids)]


def sorted_segment_total(values: jnp.ndarray, sorted_ids: jnp.ndarray) -> jnp.ndarray:
    """For each element, the total of its run (broadcast segment sum)."""
    cum = sorted_segment_cumsum(values, sorted_ids)
    # run total = cumsum at the run's last element; the last element of run r
    # is the element before the next run's start (or the final element).
    n = values.shape[0]
    starts = segment_starts(sorted_ids)
    # index of the run end for each element: scan run-start indices from the
    # right — the next start minus one.
    idx = jnp.arange(n)
    next_start = jnp.where(starts, idx, n)
    next_start = jax.lax.associative_scan(jnp.minimum, next_start, reverse=True)
    # next_start here is the start of MY run scanned from the right; shift to
    # find the start of the NEXT run instead:
    nxt = jnp.concatenate([next_start[1:], jnp.full((1,), n, next_start.dtype)])
    return cum[nxt - 1]


def segment_sum_scatter(
    values: jnp.ndarray, ids: jnp.ndarray, num_segments: int
) -> jnp.ndarray:
    """Plain scatter-add segment sum (ids need not be sorted)."""
    out = jnp.zeros((num_segments,) + values.shape[1:], values.dtype)
    return out.at[ids].add(values)
