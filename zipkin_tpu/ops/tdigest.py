"""Merging t-digest with sort-based compaction, vectorized over key slots.

BASELINE's north star names t-digest as the per-(service, spanName)
percentile sketch. The classic implementation is pointer-chasing
(insertion buffers + centroid lists) — hostile to XLA. This one is the
*merging digest* formulation recast as fixed-shape array ops, the TPU-first
design (SURVEY.md §7 hard-part 2):

1. flatten existing centroids [U, C, 2] and the incoming (slot, value,
   weight) triples into one point list;
2. one lexsort by (slot, mean) — sorts are XLA-friendly;
3. within-slot cumulative weights -> quantile position q of each point;
4. cluster id via the k1 scale function (arcsin), which concentrates
   cluster resolution at the tails;
5. segment-sum (weight, weight*mean) by (slot, cluster) -> new centroids.

Every step is static-shape; the whole update jits to sort + scans +
one scatter-add. Cross-shard reads merge by concatenating centroid lists
and re-compacting (:func:`merge`).

Accuracy: with C=64 centroids, tail quantiles (p99) land within ~0.5% of
exact on 1M-point streams (see tests/test_ops_sketches.py), comfortably
inside BASELINE config[1]'s epsilon.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from zipkin_tpu.ops.segments import sorted_segment_cumsum, sorted_segment_total


def cluster_q_width(c: int, q: float) -> float:
    """Width in q-space of the k1-scale cluster covering quantile ``q``
    with ``c`` centroids: dq/dk = pi*sqrt(q(1-q))/c, plus a 1/(2c)
    floor for the interpolation half-step near the extremes. This is
    the digest's intrinsic rank resolution — the accuracy observatory
    (obs/accuracy.py) converts it to a VALUE bound by evaluating the
    ground-truth reservoir at ``q ± cluster_q_width``, which is what
    makes the stated confidence bound distribution-free."""
    return min(0.5, math.pi * math.sqrt(max(q * (1.0 - q), 0.0)) / c
               + 0.5 / c)


def new_digests(slots: int, centroids: int = 64) -> jnp.ndarray:
    """Zeroed digest state: [slots, centroids, 2] (mean, weight) float32."""
    return jnp.zeros((slots, centroids, 2), jnp.float32)


def _cluster_ids(q: jnp.ndarray, c: int) -> jnp.ndarray:
    """k1 scale function: cluster = floor(C * (asin(2q-1)/pi + 1/2))."""
    x = jnp.clip(2.0 * q - 1.0, -1.0, 1.0)
    k = jnp.arcsin(x) / jnp.pi + 0.5
    return jnp.clip((k * c).astype(jnp.int32), 0, c - 1)


def update(
    digests: jnp.ndarray,
    slot_ids: jnp.ndarray,
    values: jnp.ndarray,
    weights: jnp.ndarray,
) -> jnp.ndarray:
    """Merge a batch of weighted values into their slots' digests.

    ``slot_ids`` int32 in [0, slots); lanes with weight 0 are inert (point
    them at slot 0). Returns digests of the same shape.
    """
    u, c, _ = digests.shape
    st_mean = digests[..., 0].reshape(-1)
    st_w = digests[..., 1].reshape(-1)
    st_slot = jnp.repeat(jnp.arange(u, dtype=jnp.int32), c)

    mean = jnp.concatenate([st_mean, values.astype(jnp.float32)])
    w = jnp.concatenate([st_w, weights.astype(jnp.float32)])
    slot = jnp.concatenate([st_slot, slot_ids.astype(jnp.int32)])

    # empty centroids / inert lanes: push to +inf so they sort to the slot
    # tail and contribute weight 0 everywhere.
    mean = jnp.where(w > 0, mean, jnp.inf)

    order = jnp.lexsort((mean, slot))
    mean, w, slot = mean[order], w[order], slot[order]

    cum = sorted_segment_cumsum(w, slot)
    total = sorted_segment_total(w, slot)
    q = jnp.where(total > 0, (cum - 0.5 * w) / jnp.maximum(total, 1e-9), 0.0)
    cluster = _cluster_ids(q, c)

    dest = slot * c + cluster
    wsum = jnp.zeros((u * c,), jnp.float32).at[dest].add(w)
    msum = jnp.zeros((u * c,), jnp.float32).at[dest].add(
        w * jnp.where(jnp.isfinite(mean), mean, 0.0)
    )
    new_mean = jnp.where(wsum > 0, msum / jnp.maximum(wsum, 1e-9), 0.0)
    return jnp.stack([new_mean, wsum], axis=-1).reshape(u, c, 2)


def compact_points(
    slot_ids: jnp.ndarray,
    values: jnp.ndarray,
    weights: jnp.ndarray,
    slots: int,
    c: int,
) -> jnp.ndarray:
    """Compact a flat weighted point list into per-slot partial digests
    ``[slots, c, 2]`` with ONE sort of the point list.

    This is the cheap half of the flush split: unlike :func:`update`, the
    existing centroids are NOT re-sorted (the round-1 profile showed the
    flush's 655k-lane lexsort dominating ingest at 66% of step time);
    the partials are folded in afterwards by :func:`row_merge`.
    """
    mean = jnp.where(weights > 0, values.astype(jnp.float32), jnp.inf)
    w = weights.astype(jnp.float32)
    slot = slot_ids.astype(jnp.int32)

    order = jnp.lexsort((mean, slot))
    mean, w, slot = mean[order], w[order], slot[order]

    cum = sorted_segment_cumsum(w, slot)
    total = sorted_segment_total(w, slot)
    q = jnp.where(total > 0, (cum - 0.5 * w) / jnp.maximum(total, 1e-9), 0.0)
    cluster = _cluster_ids(q, c)

    dest = slot * c + cluster
    wsum = jnp.zeros((slots * c,), jnp.float32).at[dest].add(w)
    msum = jnp.zeros((slots * c,), jnp.float32).at[dest].add(
        w * jnp.where(jnp.isfinite(mean), mean, 0.0)
    )
    new_mean = jnp.where(wsum > 0, msum / jnp.maximum(wsum, 1e-9), 0.0)
    return jnp.stack([new_mean, wsum], axis=-1).reshape(slots, c, 2)


def row_merge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Merge digests slot-wise with row-parallel sorts: ``[K, Ca, 2]`` +
    ``[K, Cb, 2]`` -> ``[K, Ca, 2]``.

    Per-row argsort of Ca+Cb lanes vectorizes across all K slots on the
    TPU (vs one global K*(Ca+Cb)-lane lexsort), which is what makes both
    the buffered-flush path and the cross-shard read merge cheap. Standard
    merging-digest semantics: clusters of clusters, same as :func:`merge`.
    """
    k, ca, _ = a.shape
    m = jnp.concatenate([a[..., 0], b[..., 0]], axis=-1)  # [K, Ca+Cb]
    w = jnp.concatenate([a[..., 1], b[..., 1]], axis=-1)
    m = jnp.where(w > 0, m, jnp.inf)

    order = jnp.argsort(m, axis=-1)
    m = jnp.take_along_axis(m, order, axis=-1)
    w = jnp.take_along_axis(w, order, axis=-1)

    cum = jnp.cumsum(w, axis=-1)
    total = cum[..., -1:]
    q = jnp.where(total > 0, (cum - 0.5 * w) / jnp.maximum(total, 1e-9), 0.0)
    cluster = _cluster_ids(q, ca)  # [K, Ca+Cb], non-decreasing per row

    # No scatter: aggregate per-cluster sums as a batched one-hot matmul —
    # [K, 2, P] @ [K, P, Ca] on the MXU. XLA TPU scatter serializes per
    # lane (two [K*(Ca+Cb)]-lane scatter-adds here were ~2/3 of the flush
    # cost in the round-2 profile); the equality one-hot is bulk HBM
    # traffic instead, which the MXU contraction eats in well under 1 ms.
    m0 = jnp.where(jnp.isfinite(m), m, 0.0)
    onehot = (
        cluster[..., None] == jnp.arange(ca, dtype=cluster.dtype)
    ).astype(jnp.float32)  # [K, P, Ca]
    stacked = jnp.stack([w, w * m0], axis=1)  # [K, 2, P]
    sums = jnp.einsum(
        "kxp,kpc->kxc", stacked, onehot, preferred_element_type=jnp.float32
    )
    wsum = sums[:, 0]
    msum = sums[:, 1]
    new_mean = jnp.where(wsum > 0, msum / jnp.maximum(wsum, 1e-9), 0.0)
    return jnp.stack([new_mean, wsum], axis=-1)


def quantile(digests: jnp.ndarray, qs: jnp.ndarray) -> jnp.ndarray:
    """Quantiles per slot: [slots, Q] float32, 0 for empty slots.

    Standard t-digest interpolation: centroid means at cumulative-weight
    midpoints, linear in between.
    """
    means = digests[..., 0]
    ws = digests[..., 1]
    # centroids are mean-sorted by construction; make x strictly usable for
    # interp by masking empties to the running max.
    cum = jnp.cumsum(ws, axis=-1) - 0.5 * ws
    total = jnp.sum(ws, axis=-1, keepdims=True)
    x = jnp.where(ws > 0, means, -jnp.inf)
    x = jax.lax.associative_scan(jnp.maximum, x, axis=-1)
    x = jnp.where(jnp.isfinite(x), x, 0.0)

    targets = qs[None, :] * total  # [slots, Q]

    def one(cum_row, x_row, t_row):
        return jnp.interp(t_row, cum_row, x_row)

    out = jax.vmap(one)(cum, x, targets)
    return jnp.where(total > 0, out, 0.0)


def merge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Merge two digest states slot-wise (row-parallel re-compaction)."""
    return row_merge(a, b)


@jax.jit
def _merge_many_jit(states: jnp.ndarray) -> jnp.ndarray:
    """[D, U, C, 2] -> [U, C, 2] in ONE dispatch: concatenate every
    shard's centroids along the centroid axis and recluster row-wise
    (replaces the round-1 sequential host loop of D-1 global sorts)."""
    d, u, c, _ = states.shape
    all_c = jnp.moveaxis(states, 0, 1).reshape(u, d * c, 2)
    return row_merge(jnp.zeros((u, c, 2), jnp.float32), all_c)


def merge_many(states) -> jnp.ndarray:
    """Merge [shards, U, C, 2] into one [U, C, 2] (single jitted dispatch)."""
    arr = jnp.asarray(states)
    if arr.shape[0] == 1:
        return arr[0]
    return _merge_many_jit(arr)
