"""Host-side merge kernels for the time-disaggregated sketch tier.

A windowed query covers a run of sealed time-bucket segments (compact
host arrays, tpu/timetier.py) plus at most one device pull for the
unsealed current bucket. The merges here are the host mirrors of the
device combiners — t-digest cluster recluster (ops/tdigest.row_merge),
HLL register-max + the bias-corrected estimate (ops/hll.estimate), and
edge-count sums — over numpy arrays, so serving a sealed window costs
NO device dispatch at all (the paper's read-the-compact-segments move).

Determinism contract: every function here is a pure, order-defined
numpy computation in float32 — merging the same segment list always
produces the same bits. That is what lets the windowed bit-identity
oracle (tests/test_timetier.py) compare a live store's merged answers
against a from-scratch rebuild segment by segment: per-bucket segments
are bit-identical on device (per-slot segmented compaction), and the
host fold over equal inputs is bit-equal by construction. The host
recluster does NOT need to reproduce the device ``row_merge`` bitwise —
only to be deterministic and standard-merging-digest correct.
"""

from __future__ import annotations

import math
from typing import Sequence

import numpy as np


def _cluster_ids(q: np.ndarray, c: int) -> np.ndarray:
    """k1 scale function (host mirror of ops/tdigest._cluster_ids)."""
    x = np.clip(2.0 * q - 1.0, -1.0, 1.0).astype(np.float32)
    k = np.arcsin(x) / np.float32(np.pi) + np.float32(0.5)
    return np.clip((k * c).astype(np.int32), 0, c - 1)


def merge_digests(parts: Sequence[np.ndarray]) -> np.ndarray:
    """Fold per-bucket digests ``[K, C, 2]`` into one ``[K, C, 2]``.

    The merge_many formulation: concatenate every part's clusters along
    the centroid axis, then ONE row-parallel recluster — stable
    mean-sort, k1-scale cluster assignment, weighted mean per cluster.
    Accumulation runs through np.add.at in sorted-lane order, so the
    result is a pure function of the input list (segment epoch order —
    the tier always folds ascending)."""
    parts = [np.asarray(p, np.float32) for p in parts]
    if not parts:
        raise ValueError("merge_digests needs at least one part")
    k, c, _ = parts[0].shape
    m = np.concatenate([p[..., 0] for p in parts], axis=-1)
    w = np.concatenate([p[..., 1] for p in parts], axis=-1)
    m = np.where(w > 0, m, np.float32(np.inf))

    order = np.argsort(m, axis=-1, kind="stable")
    m = np.take_along_axis(m, order, axis=-1)
    w = np.take_along_axis(w, order, axis=-1)

    cum = np.cumsum(w, axis=-1, dtype=np.float32)
    total = cum[..., -1:]
    q = np.where(
        total > 0, (cum - np.float32(0.5) * w) / np.maximum(total, 1e-9), 0.0
    ).astype(np.float32)
    cluster = _cluster_ids(q, c)

    row = np.broadcast_to(np.arange(k, dtype=np.int64)[:, None], cluster.shape)
    dest = row * c + cluster
    wsum = np.zeros(k * c, np.float32)
    msum = np.zeros(k * c, np.float32)
    m0 = np.where(np.isfinite(m), m, 0.0).astype(np.float32)
    np.add.at(wsum, dest.ravel(), w.ravel())
    np.add.at(msum, dest.ravel(), (w * m0).ravel())
    new_mean = np.where(wsum > 0, msum / np.maximum(wsum, 1e-9), 0.0)
    return np.stack(
        [new_mean.astype(np.float32), wsum], axis=-1
    ).reshape(k, c, 2)


def digest_quantile(digest: np.ndarray, qs) -> np.ndarray:
    """[K, Q] quantiles from a merged digest — the host mirror of
    ops/tdigest.quantile (centroid means at cumulative-weight midpoints,
    linear in between; 0 for empty rows)."""
    digest = np.asarray(digest, np.float32)
    qs = np.asarray(qs, np.float32)
    means = digest[..., 0]
    ws = digest[..., 1]
    cum = np.cumsum(ws, axis=-1, dtype=np.float32) - np.float32(0.5) * ws
    total = ws.sum(axis=-1, keepdims=True, dtype=np.float32)
    x = np.where(ws > 0, means, -np.inf)
    x = np.maximum.accumulate(x, axis=-1)
    x = np.where(np.isfinite(x), x, 0.0).astype(np.float32)
    out = np.empty((digest.shape[0], qs.shape[0]), np.float32)
    targets = qs[None, :] * total
    for i in range(digest.shape[0]):
        out[i] = np.interp(targets[i], cum[i], x[i])
    return np.where(total > 0, out, 0.0).astype(np.float32)


def merge_hll(parts: Sequence[np.ndarray]) -> np.ndarray:
    """Register-wise max over per-bucket register arrays — the lossless
    HLL union (same combiner as the cross-shard pmax)."""
    if not parts:
        raise ValueError("merge_hll needs at least one part")
    out = np.asarray(parts[0], np.uint8)
    for p in parts[1:]:
        out = np.maximum(out, np.asarray(p, np.uint8))
    return out


def _alpha(m: int) -> float:
    if m == 16:
        return 0.673
    if m == 32:
        return 0.697
    if m == 64:
        return 0.709
    return 0.7213 / (1.0 + 1.079 / m)


def hll_estimate(registers: np.ndarray) -> np.ndarray:
    """[rows] f32 cardinality estimates — exact host port of
    ops/hll.estimate (bias-corrected harmonic mean, linear counting
    below 2.5m, no classical large-range correction — see the device
    docstring for why), so windowed and cumulative cardinalities read
    off the same estimator."""
    registers = np.asarray(registers, np.uint8)
    m = registers.shape[-1]
    alpha = np.float32(_alpha(m))
    regs = registers.astype(np.float32)
    harm = np.sum(np.exp2(-regs), axis=-1, dtype=np.float32)
    raw = alpha * np.float32(m) * np.float32(m) / harm
    zeros = np.sum(registers == 0, axis=-1).astype(np.float32)
    linear = (
        np.float32(m) * np.log(np.float32(m) / np.maximum(zeros, 1.0))
    ).astype(np.float32)
    use_linear = (raw <= 2.5 * m) & (zeros > 0)
    return np.where(use_linear, linear, raw).astype(np.float32)


def merge_edges(parts: Sequence[np.ndarray]) -> np.ndarray:
    """Sum per-bucket edge-count matrices ``[S, S]`` (uint64 accumulate
    — merging many buckets must not wrap the u32 segment dtype)."""
    if not parts:
        raise ValueError("merge_edges needs at least one part")
    out = np.zeros(np.asarray(parts[0]).shape, np.uint64)
    for p in parts:
        out += np.asarray(p, np.uint64)
    return out


def digest_total(digest: np.ndarray) -> np.ndarray:
    """[K] total folded weight per key row (the windowed count column
    quantile responses report alongside the percentiles)."""
    return np.asarray(digest, np.float32)[..., 1].sum(
        axis=-1, dtype=np.float32
    )


def cluster_q_width(c: int, q: float) -> float:
    """Rank resolution of a ``c``-centroid merged digest at quantile
    ``q`` (host copy of ops/tdigest.cluster_q_width — the windowed
    accuracy observatory converts it to a value bound)."""
    return min(
        0.5, math.pi * math.sqrt(max(q * (1.0 - q), 0.0)) / c + 0.5 / c
    )
