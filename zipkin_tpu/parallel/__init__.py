"""Multi-chip scale-out: mesh construction, trace-affine routing, and
``shard_map``-based SPMD over the aggregate state.

The reference scales by stateless server fan-out + storage sharding
(Cassandra token ring / ES shards, SURVEY.md §2.8). The TPU equivalent:
spans are routed host-side by trace hash to a shard (trace affinity makes
parent joins shard-local), each shard folds its sub-batch with the same
pure ingest step, and reads merge shard states with XLA collectives over
ICI (``psum`` for histograms/edges, ``pmax`` for HLL) — never NCCL/MPI.
"""

from zipkin_tpu.parallel.mesh import SHARD_AXIS, make_mesh
from zipkin_tpu.parallel.sharded import ShardedAggregator

__all__ = ["SHARD_AXIS", "make_mesh", "ShardedAggregator"]
