"""Device mesh construction for the aggregation tier.

One logical axis, ``shard``: span-hash data parallelism (SURVEY.md §2.8).
A second axis is deliberately absent — every cross-shard interaction is a
commutative sketch merge, so a flat ring over ICI is the whole topology.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

SHARD_AXIS = "shard"


def make_mesh(
    n_devices: Optional[int] = None, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """A 1-D mesh over ``n_devices`` (default: all local devices)."""
    import numpy as np

    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SHARD_AXIS,))
