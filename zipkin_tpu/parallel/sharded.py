"""ShardedAggregator: the SPMD aggregate tier over a device mesh.

State lives as one pytree with a leading ``[shards, ...]`` axis sharded
over the mesh; ingest is ``shard_map`` of the pure single-shard step;
reads merge with ``psum``/``pmax`` over ICI (SURVEY.md §2.8 mapping
table). Runs identically on one real TPU chip (mesh of 1), a v5e-8, or
the 8-virtual-device CPU backend used in CI.
"""

from __future__ import annotations

import functools
import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
except ImportError:  # jax < 0.6 ships it under experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zipkin_tpu import obs, readpack
from zipkin_tpu.obs import critpath
from zipkin_tpu.obs import device as obs_device
from zipkin_tpu.obs import querytrace
from zipkin_tpu.ops import linker as dlink
from zipkin_tpu.tpu import ingest as ing
from zipkin_tpu.tpu.columnar import (
    SpanColumns,
    concat_remap,
    fuse_columns,
    remap_fused,
    route_columns,
    route_fused,
)
from zipkin_tpu.tpu.state import AggConfig, AggState, init_state

SHARD_AXIS = "shard"


def unfuse_columns(fz: jnp.ndarray) -> SpanColumns:
    """Device-side inverse of :func:`zipkin_tpu.tpu.columnar.fuse_columns`:
    ``[11, n] u32`` packed wire image -> typed SpanColumns. The unpack is
    shifts/masks XLA fuses into the consuming ops — the 44 B/span wire
    (vs 68 B unpacked) is pure tunnel-transfer savings."""
    sr = fz[9]
    kf = fz[10]
    u = jnp.uint32
    i32 = lambda a: a.astype(jnp.int32)
    return SpanColumns(
        trace_h=fz[0], tl0=fz[1], tl1=fz[2],
        s0=fz[3], s1=fz[4], p0=fz[5], p1=fz[6],
        shared=(kf & u(2)) != 0,
        kind=i32((kf >> u(4)) & u(7)),
        svc=i32(sr >> u(16)), rsvc=i32(sr & u(0xFFFF)),
        key=i32(kf >> u(8)),
        err=(kf & u(4)) != 0,
        dur=fz[7],
        has_dur=(kf & u(8)) != 0,
        ts_min=fz[8],
        valid=(kf & u(1)) != 0,
    )


@functools.lru_cache(maxsize=8)
def _compiled_programs(config: AggConfig, mesh: Mesh):
    """Compiled SPMD programs shared by every aggregator with the same
    (config, mesh) — constructing a store must not trigger recompiles."""
    n_shards = int(np.prod(mesh.devices.shape))
    sharding = NamedSharding(mesh, P(SHARD_AXIS))

    def _packed(inner, name):
        """Production wire variant of a read program: the same device
        program with a readpack.pack stage fused on the end, so the
        whole answer is ONE 1-D uint32 buffer — one device→host pull
        per query, however many logical outputs. ``name`` keeps the
        XPlane program attribution (jit_spmd_*) stable across rounds."""

        def wrapper(*args):
            out = inner(*args)
            if not isinstance(out, tuple):
                out = (out,)
            return readpack.pack(out)

        wrapper.__name__ = name
        return jax.jit(wrapper)

    # shard_map's static replication/varying-manual-axes check can't see
    # through all_gather+row_merge, and older jax (< 0.5) additionally
    # has no replication rule at all for lax.while_loop (the linker's
    # ancestor chase) — so every program tracing those turns the check
    # off. The flag is check_vma on current jax, check_rep before 0.6.
    import inspect

    _sm_params = inspect.signature(shard_map).parameters
    if "check_vma" in _sm_params:
        _vma_off = dict(check_vma=False)
    elif "check_rep" in _sm_params:
        _vma_off = dict(check_rep=False)
    else:  # pragma: no cover - future jax with neither knob
        _vma_off = {}

    def _init() -> AggState:
        # broadcast the REAL initial leaves, not zeros: init_state's
        # sentinels are load-bearing (link_perm must be a permutation,
        # pend_key/epoch slots use -1 = empty; a zero-filled pend_key
        # even let an early flush fold phantom key-0 points)
        return jax.tree_util.tree_map(
            lambda a: jnp.broadcast_to(a, (n_shards,) + a.shape),
            init_state(config),
        )

    init = jax.jit(_init, out_shardings=sharding)

    one = functools.partial(ing.ingest_step, config)

    def _make_step(pre_flush: bool, pre_rollup: bool):
        """Step program variants with the periodic maintenance programs
        FUSED in front: when the host decides a flush and/or rollup is
        due, dispatching one combined program instead of two or three
        saves the tunnel's fixed per-dispatch round trip (~23ms each —
        ~10% of a steady-state batch when both fire)."""

        def spmd(state: AggState, fused: jnp.ndarray) -> AggState:
            squeeze = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)
            expand = lambda t: jax.tree_util.tree_map(lambda a: a[None], t)
            s = squeeze(state)
            if pre_flush:
                s = ing.flush_digest(config, s)
            if pre_rollup:
                s = ing.rollup_step(config, s)
            return expand(one(s, unfuse_columns(fused[0])))

        return jax.jit(
            shard_map(
                spmd,
                mesh=mesh,
                in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                out_specs=P(SHARD_AXIS),
                **_vma_off,
            ),
            donate_argnums=(0,),
        )

    step_variants = {
        (flush, rollup): _make_step(flush, rollup)
        for flush in (False, True)
        for rollup in (False, True)
    }

    def spmd_link_ctx(state: AggState):
        """The window-independent half of a dependency query, via the
        INCREMENTAL delta formulation (ops/delta_linker.py): persistent
        ctx advanced at rollup cadence + a sort of only the since-rollup
        delta segment — bit-identical to the from-scratch
        linker.link_context oracle (fuzzed in tests/test_incremental_ctx)
        without the full-ring union sort that cost ~29.6 ms of the
        41.3 ms r5 fresh read."""
        s = jax.tree_util.tree_map(lambda a: a[0], state)
        ctx = ing.fresh_link_context(config, s)
        return jax.tree_util.tree_map(lambda a: a[None], ctx)

    link_ctx = jax.jit(
        shard_map(
            spmd_link_ctx, mesh=mesh,
            in_specs=(P(SHARD_AXIS),), out_specs=P(SHARD_AXIS),
            **_vma_off,
        )
    )

    def spmd_links(ctx, state: AggState, ts_lo, ts_hi):
        s = jax.tree_util.tree_map(lambda a: a[0], state)
        c = jax.tree_util.tree_map(lambda a: a[0], ctx)
        calls, errors = ing.dependency_links(config, s, ts_lo, ts_hi, ctx=c)
        return jax.lax.psum(calls, SHARD_AXIS), jax.lax.psum(errors, SHARD_AXIS)

    links_sm = shard_map(
        spmd_links,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(), P()),
        out_specs=P(),
        **_vma_off,
    )
    links = _packed(links_sm, "spmd_links")

    def spmd_merge(state: AggState):
        s = jax.tree_util.tree_map(lambda a: a[0], state)
        return (
            jax.lax.psum(s.hist, SHARD_AXIS),
            jax.lax.pmax(s.hll, SHARD_AXIS),
            jax.lax.psum(s.counters, SHARD_AXIS),
        )

    merge_sm = shard_map(
        spmd_merge, mesh=mesh, in_specs=(P(SHARD_AXIS),), out_specs=P(),
        **_vma_off,
    )
    merge = _packed(merge_sm, "spmd_merge")

    def spmd_flush(state: AggState) -> AggState:
        s = jax.tree_util.tree_map(lambda a: a[0], state)
        out = ing.flush_digest(config, s)
        return jax.tree_util.tree_map(lambda a: a[None], out)

    flush = jax.jit(
        shard_map(
            spmd_flush, mesh=mesh, in_specs=(P(SHARD_AXIS),),
            out_specs=P(SHARD_AXIS), **_vma_off,
        ),
        donate_argnums=(0,),
    )

    def spmd_rollup(state: AggState) -> AggState:
        s = jax.tree_util.tree_map(lambda a: a[0], state)
        out = ing.rollup_step(config, s)
        return jax.tree_util.tree_map(lambda a: a[None], out)

    rollup = jax.jit(
        shard_map(
            spmd_rollup, mesh=mesh, in_specs=(P(SHARD_AXIS),),
            out_specs=P(SHARD_AXIS), **_vma_off,
        ),
        donate_argnums=(0,),
    )

    def spmd_whist(state: AggState, ts_lo, ts_hi):
        s = jax.tree_util.tree_map(lambda a: a[0], state)
        return jax.lax.psum(
            ing.windowed_hist(config, s, ts_lo, ts_hi), SHARD_AXIS
        )

    whist_sm = shard_map(
        spmd_whist, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(), P()), out_specs=P(), **_vma_off,
    )
    whist = _packed(whist_sm, "spmd_whist")

    def _gather_recluster(local):
        """all_gather per-shard [K, C, 2] digests over ICI and recluster
        row-wise into one [K, C, 2] — shared by every digest read so the
        pending and no-pending variants stay bit-identical.

        On a ONE-shard mesh this is the identity: a shard's digest rows
        are already complete mean-sorted digests, and the r3 SLO capture
        showed the pointless self-merge was most of the 35.2 ms
        single-shard percentile read (VERDICT r3 order 7). n_shards is a
        trace-time constant, so each mesh compiles the right program."""
        from zipkin_tpu.ops import tdigest

        if n_shards == 1:
            return local
        allc = jax.lax.all_gather(local, SHARD_AXIS)  # [D, K, C, 2]
        d = allc.shape[0]
        k = config.max_keys
        c = config.digest_centroids
        flat = jnp.moveaxis(allc, 0, 1).reshape(k, d * c, 2)
        return tdigest.row_merge(jnp.zeros((k, c, 2), jnp.float32), flat)

    def _merged_digest_of(state: AggState):
        """Complete cross-shard digest as a PURE READ: fold each shard's
        pending points into a local partial (state untouched — a
        percentile query no longer stalls ingest with a flush-on-read),
        then gather + recluster."""
        from zipkin_tpu.ops import tdigest

        s = jax.tree_util.tree_map(lambda a: a[0], state)
        w = (s.pend_key >= 0).astype(jnp.float32)
        keys = jnp.clip(s.pend_key, 0, config.max_keys - 1)
        partial = tdigest.compact_points(
            keys, s.pend_val, w, config.max_keys, config.digest_centroids
        )
        local = tdigest.row_merge(s.digest, partial)  # [K, C, 2]
        return _gather_recluster(local)

    digest_read_sm = shard_map(
        _merged_digest_of, mesh=mesh, in_specs=(P(SHARD_AXIS),),
        out_specs=P(), **_vma_off,
    )
    digest_read = _packed(digest_read_sm, "spmd_digest_read")

    # quantile reads computed ON DEVICE: one dispatch, [K, Q] + [K] counts
    # over the tunnel instead of the dense [K, BUCKETS] histogram (28MB at
    # default shapes — the round-1 query path pulled it per request)
    def spmd_quant_digest(state: AggState, qs):
        from zipkin_tpu.ops import histogram, tdigest

        s = jax.tree_util.tree_map(lambda a: a[0], state)
        merged = _merged_digest_of(state)
        counts = jax.lax.psum(histogram.total_count(s.hist), SHARD_AXIS)
        return tdigest.quantile(merged, qs), counts

    quant_digest_sm = shard_map(
        spmd_quant_digest, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P()), out_specs=P(), **_vma_off,
    )
    quant_digest = _packed(quant_digest_sm, "spmd_quant_digest")

    def spmd_quant_digest_nopend(state: AggState, qs):
        """Digest quantiles when the host KNOWS the pending buffer is
        empty (right after a flush): skips the 131k-lane pending fold —
        the one cost above the dispatch floor in the r2 query profile."""
        from zipkin_tpu.ops import histogram, tdigest

        s = jax.tree_util.tree_map(lambda a: a[0], state)
        merged = _gather_recluster(s.digest)
        counts = jax.lax.psum(histogram.total_count(s.hist), SHARD_AXIS)
        return tdigest.quantile(merged, qs), counts

    quant_digest_nopend_sm = shard_map(
        spmd_quant_digest_nopend, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P()), out_specs=P(), **_vma_off,
    )
    quant_digest_nopend = _packed(
        quant_digest_nopend_sm, "spmd_quant_digest_nopend"
    )

    def spmd_quant_hist(state: AggState, qs):
        from zipkin_tpu.ops import histogram

        s = jax.tree_util.tree_map(lambda a: a[0], state)
        merged = jax.lax.psum(s.hist, SHARD_AXIS)
        return histogram.quantile(merged, qs), histogram.total_count(merged)

    quant_hist_sm = shard_map(
        spmd_quant_hist, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P()), out_specs=P(), **_vma_off,
    )
    quant_hist = _packed(quant_hist_sm, "spmd_quant_hist")

    def spmd_quant_whist(state: AggState, ts_lo, ts_hi, qs):
        from zipkin_tpu.ops import histogram

        merged = spmd_whist(state, ts_lo, ts_hi)
        return histogram.quantile(merged, qs), histogram.total_count(merged)

    quant_whist_sm = shard_map(
        spmd_quant_whist, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(), P(), P()), out_specs=P(), **_vma_off,
    )
    quant_whist = _packed(quant_whist_sm, "spmd_quant_whist")

    # dependency edges compacted ON DEVICE: the first E nonzero cells of
    # the merged [S, S] call matrix via prefix-sum compaction (cumsum +
    # searchsorted + gather), so a query ships 3 small [E] vectors over
    # the tunnel instead of two dense matrices. Equivalent to the r4
    # top-E-by-calls: both exist to ship EVERY nonzero edge when they
    # fit in E — and when they don't, every returned slot is live, which
    # is exactly the host's dense-fallback trigger (store.py). The
    # compaction measured 0.88 ms vs top_k's 1.09 at [1024^2] (r5 A/B).
    num_edges = min(4096, config.max_services * config.max_services)

    def _edge_topk(calls, errors):
        cf = jax.lax.psum(calls, SHARD_AXIS).reshape(-1)
        ef = jax.lax.psum(errors, SHARD_AXIS).reshape(-1)
        nz = (cf > 0).astype(jnp.int32)
        cs = jnp.cumsum(nz)
        pos = jnp.searchsorted(
            cs, jnp.arange(1, num_edges + 1, dtype=jnp.int32), side="left"
        )
        pos = jnp.clip(pos, 0, cf.shape[0] - 1)
        have = jnp.arange(num_edges) < cs[-1]
        return (
            jnp.where(have, pos, 0).astype(jnp.int32),
            jnp.where(have, cf[pos], 0),
            jnp.where(have, ef[pos], 0),
        )

    def spmd_edges(ctx, state: AggState, ts_lo, ts_hi):
        s = jax.tree_util.tree_map(lambda a: a[0], state)
        c = jax.tree_util.tree_map(lambda a: a[0], ctx)
        calls, errors = ing.dependency_links(config, s, ts_lo, ts_hi, ctx=c)
        return _edge_topk(calls, errors)

    edges_sm = shard_map(
        spmd_edges, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(), P()), out_specs=P(),
        **_vma_off,
    )
    edges = _packed(edges_sm, "spmd_edges")

    def spmd_edges_fresh(ctxless_state: AggState, ts_lo, ts_hi):
        """The FRESH dependency read: first query after a write. One
        dispatch computes the link context — via the incremental DELTA
        formulation: persistent ctx + a sort of only the since-rollup
        segment (ops/delta_linker.py), never a full-ring sort — plus the
        windowed top-E edges, and returns both so the host caches the
        ctx for follow-up windows. This program GATES the <50 ms query
        SLO with no amortized exclusions (VERDICT r3 order 1): r3 paid
        145.8 ms + 6.8 ms in two dispatches, r5's from-scratch fused
        read 41.3 ms, the delta read only the since-rollup segment."""
        s = jax.tree_util.tree_map(lambda a: a[0], ctxless_state)
        c = ing.fresh_link_context(config, s)
        calls, errors = ing.dependency_links(config, s, ts_lo, ts_hi, ctx=c)
        ctx_out = jax.tree_util.tree_map(lambda a: a[None], c)
        return ctx_out, _edge_topk(calls, errors)

    edges_fresh_sm = shard_map(
        spmd_edges_fresh, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(), P()),
        out_specs=(P(SHARD_AXIS), P()),
        **_vma_off,
    )

    def _edges_fresh_packed(state, ts_lo, ts_hi):
        # ctx stays ON DEVICE (it primes the per-version cache; only the
        # edge triple crosses the tunnel, as one packed buffer)
        ctx, triple = edges_fresh_sm(state, ts_lo, ts_hi)
        return ctx, readpack.pack(triple)

    _edges_fresh_packed.__name__ = "spmd_edges_fresh"
    edges_fresh = jax.jit(_edges_fresh_packed)

    def spmd_edges_rolled(state: AggState, ts_lo, ts_hi):
        """Edges from the rollup buckets ALONE — no ring sort, no link
        context: the read path for windows the host proves cannot touch
        the live ring (the reference's read-the-daily-table path)."""
        s = jax.tree_util.tree_map(lambda a: a[0], state)
        calls, errors = ing.rolled_links(config, s, ts_lo, ts_hi)
        return _edge_topk(calls, errors)

    edges_rolled_sm = shard_map(
        spmd_edges_rolled, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(), P()), out_specs=P(), **_vma_off,
    )
    edges_rolled = _packed(edges_rolled_sm, "spmd_edges_rolled")
    # device-side state clone for snapshots: runs in ms on device, so
    # the aggregator lock is held only for the dispatch — the host pull
    # of the copy (~state_bytes over the transport) happens lock-free
    # while ingest continues against the original buffers
    snap_copy = jax.jit(
        lambda s: jax.tree_util.tree_map(jnp.copy, s),
        out_shardings=sharding,
    )

    def spmd_card(state: AggState):
        from zipkin_tpu.ops import hll as hll_ops

        s = jax.tree_util.tree_map(lambda a: a[0], state)
        merged = jax.lax.pmax(s.hll, SHARD_AXIS)
        return hll_ops.estimate(merged)  # [S+1] f32 — KBs, not registers

    card_sm = shard_map(
        spmd_card, mesh=mesh, in_specs=(P(SHARD_AXIS),), out_specs=P()
    )
    card = _packed(card_sm, "spmd_card")

    def spmd_overview(state: AggState, qs):
        """The coalesced sketch read: digest quantiles + per-key counts
        + HLL cardinalities in ONE dispatch — what the server's
        /api/v2/tpu/overview endpoint serves, replacing three separate
        aggregator dispatches (and three HTTP round trips from the UI
        sketch page) with one packed pull. Assumes the pending digest
        buffer is empty (the host flushes first, as the digest quantile
        path already does)."""
        from zipkin_tpu.ops import histogram, tdigest
        from zipkin_tpu.ops import hll as hll_ops

        s = jax.tree_util.tree_map(lambda a: a[0], state)
        merged = _gather_recluster(s.digest)
        counts = jax.lax.psum(histogram.total_count(s.hist), SHARD_AXIS)
        est = hll_ops.estimate(jax.lax.pmax(s.hll, SHARD_AXIS))
        return tdigest.quantile(merged, qs), counts, est

    overview_sm = shard_map(
        spmd_overview, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P()), out_specs=P(), **_vma_off,
    )
    overview = _packed(overview_sm, "spmd_overview")

    def spmd_ttread(ctx, state: AggState, lo_ep, hi_ep):
        """Time-tier windowed read (tpu/timetier.py): each shard masks
        its current-bucket leaves to the ``[lo_ep, hi_ep]`` bucket range
        (edges ride the cached link ``ctx`` for the live-ring half),
        then one cross-shard merge per sketch family — register-max for
        HLL, row-parallel recluster for the digests (the
        _gather_recluster idiom at the tier's own centroid count), psum
        for the edge counts. The sealer calls it with lo==hi to freeze
        one bucket into a segment; queries call it for the unsealed
        suffix of a window. ONE dispatch, one packed pull."""
        from zipkin_tpu.ops import tdigest

        s = jax.tree_util.tree_map(lambda a: a[0], state)
        c = jax.tree_util.tree_map(lambda a: a[0], ctx)
        ep, regs, digest, calls, errs = ing.tt_sketches(
            config, s, lo_ep, hi_ep, ctx=c
        )
        if n_shards > 1:
            ep = jax.lax.pmax(ep, SHARD_AXIS)
            regs = jax.lax.pmax(regs, SHARD_AXIS)
            allc = jax.lax.all_gather(digest, SHARD_AXIS)
            d = allc.shape[0]
            k = config.max_keys
            cw = config.time_digest_centroids
            flat = jnp.moveaxis(allc, 0, 1).reshape(k, d * cw, 2)
            digest = tdigest.row_merge(
                jnp.zeros((k, cw, 2), jnp.float32), flat
            )
            calls = jax.lax.psum(calls, SHARD_AXIS)
            errs = jax.lax.psum(errs, SHARD_AXIS)
        return ep, regs, digest, calls, errs

    ttread_sm = shard_map(
        spmd_ttread, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(), P()), out_specs=P(),
        **_vma_off,
    )
    ttread = _packed(ttread_sm, "spmd_ttread")

    # the pre-pack (multi-output) jits, kept compilable for the packed
    # wire parity tests and the transfers-3→1 A/B in benchmarks — jit is
    # lazy, so an un-dispatched raw variant costs nothing
    raw = {
        "merge": jax.jit(merge_sm),
        "links": jax.jit(links_sm),
        "whist": jax.jit(whist_sm),
        "digest_read": jax.jit(digest_read_sm),
        "edges": jax.jit(edges_sm),
        "edges_fresh": jax.jit(edges_fresh_sm),
        "edges_rolled": jax.jit(edges_rolled_sm),
        "quant_digest": jax.jit(quant_digest_sm),
        "quant_digest_nopend": jax.jit(quant_digest_nopend_sm),
        "quant_hist": jax.jit(quant_hist_sm),
        "quant_whist": jax.jit(quant_whist_sm),
        "card": jax.jit(card_sm),
        "overview": jax.jit(overview_sm),
        "ttread": jax.jit(ttread_sm),
    }
    # Device-program observatory (obs/device.py): every dispatchable
    # program counts calls/compiles through a thin wrapper — the runtime
    # recompile detector. The raw variants stay unwrapped (parity-test
    # only, never dispatched in production).
    _w = obs_device.OBSERVATORY.wrap
    init = _w("spmd_init", init)
    step_variants = {
        k: _w("spmd_step" + ("_flush" if k[0] else "")
              + ("_rollup" if k[1] else ""), v)
        for k, v in step_variants.items()
    }
    links = _w("spmd_links", links)
    merge = _w("spmd_merge", merge)
    flush = _w("spmd_flush", flush)
    rollup = _w("spmd_rollup", rollup)
    whist = _w("spmd_whist", whist)
    digest_read = _w("spmd_digest_read", digest_read)
    edges = _w("spmd_edges", edges)
    edges_fresh = _w("spmd_edges_fresh", edges_fresh)
    edges_rolled = _w("spmd_edges_rolled", edges_rolled)
    quant_digest = _w("spmd_quant_digest", quant_digest)
    quant_digest_nopend = _w("spmd_quant_digest_nopend", quant_digest_nopend)
    quant_hist = _w("spmd_quant_hist", quant_hist)
    quant_whist = _w("spmd_quant_whist", quant_whist)
    card = _w("spmd_card", card)
    link_ctx = _w("spmd_link_ctx", link_ctx)
    snap_copy = _w("spmd_snap_copy", snap_copy)
    overview = _w("spmd_overview", overview)
    ttread = _w("spmd_ttread", ttread)
    return (
        init, step_variants, links, merge, flush, rollup, whist, digest_read,
        edges, edges_fresh, edges_rolled, quant_digest, quant_digest_nopend,
        quant_hist, quant_whist, card, link_ctx, snap_copy, sharding,
        overview, ttread, raw,
    )


class ShardedAggregator:
    """Owns the sharded state and the compiled SPMD update/read programs."""

    def __init__(self, config: AggConfig, mesh: Optional[Mesh] = None) -> None:
        if mesh is None:
            from zipkin_tpu.parallel.mesh import make_mesh

            mesh = make_mesh()
        self.config = config
        self.mesh = mesh
        self.n_shards = int(np.prod(mesh.devices.shape))
        (
            init, self._step_variants, self._links, self._merge, self._flush,
            self._rollup, self._whist, self._digest_read, self._edges,
            self._edges_fresh, self._edges_rolled, self._quant_digest,
            self._quant_digest_nopend, self._quant_hist, self._quant_whist,
            self._card, self._link_ctx, self._snap_copy, self._sharding,
            self._overview, self._ttread, self._raw,
        ) = _compiled_programs(config, mesh)
        self._step = self._step_variants[(False, False)]
        # device-resident LinkContext for the current write_version (the
        # sorted/joined half of dependency queries, reused across windows)
        self._ctx_cache = (-1, None)
        self.state: AggState = init()
        # Exact host-side counters: the device counters are u32 and wrap
        # after ~4.3B spans (~72 min at the north-star rate); these are the
        # source of truth for the API and snapshot resume markers.
        self.host_counters = {
            "spans": 0,
            "spansWithDuration": 0,
            "spansWithError": 0,
            "batches": 0,
            # tail-sampling verdict tallies (exact, host-counted at the
            # ingest_fused funnel; 0 when the sampling tier is off)
            "sampledKept": 0,
            "sampledDropped": 0,
        }
        # Guards every touch of self.state. Ingest DONATES the state
        # buffers, so a reader racing a step would touch deleted arrays
        # (or, for the flush-on-read path, silently drop a batch by
        # overwriting the step's result). Reentrant: read paths nest.
        # Instrumented (ISSUE 12): outermost wait/hold land in the
        # contention ledger and the query_lock_wait stage — the number
        # ROADMAP item 4's epoch-published read mirror must drive to
        # zero. Uncontended acquires take a non-blocking fast path.
        self.lock = querytrace.InstrumentedRLock(name="agg")
        # Host mirror of the per-shard digest pend_pos (identical on every
        # shard: each advances by the same padded lane count per step).
        # The host dispatches the flush program when the next batch would
        # overflow — keeping the decision out of the step removed a
        # lax.cond that copied both pending buffers every step (~45% of
        # step device time, PROFILE_r02.md).
        self._pend_lanes = 0
        # Lanes written since the last link rollup. When the next batch
        # would push this past rollup_segment (= R/2), the rollup program
        # runs first: it links + invalidates the half-ring ahead of the
        # cursor, so spans are never overwritten before their links are
        # folded into the time-bucketed rollup matrices.
        self._lanes_since_rollup = 0
        # Ring-RESIDENT time range: (ts_lo, ts_hi, cursor-before) per
        # batch still physically in some shard's ring — popped only when
        # EVERY shard has advanced ring_capacity past the batch's start
        # (per-shard cursors, since routing skews live counts). A query
        # window disjoint from every entry cannot touch any ring span —
        # live OR rolled-but-join-visible — so it is served from the
        # rollup matrices alone (no ring sort; VERDICT r2 order 4).
        # Batches with unknown range are recorded as covering everything.
        from collections import deque

        self._resident: "deque" = deque()
        self._shard_cursor = np.zeros(self.n_shards, np.int64)
        # Highest bucket epoch any ingested span has touched (host
        # mirror, from the same ts_range the resident ledger uses). The
        # time-tier sealer (tpu/timetier.py) seals epochs strictly below
        # this — the max-epoch bucket is the UNSEALED current bucket.
        self._tt_max_epoch = -1
        self.read_stats = {
            "rolled_only_reads": 0,
            "ctx_reads": 0,
            # device→host pulls made on behalf of queries (should track
            # query count 1:1 — the one-transfer invariant; pinned by
            # tests/test_readpack.py)
            "host_transfers": 0,
        }
        # Incremental link-ctx maintenance telemetry (/metrics gauges
        # ctxDeltaLanes / ctxMaintenanceMs / ctxAdvances): advances run
        # fused inside the rollup dispatch, so the ms figure is the HOST
        # WALL of the last ctx-advancing dispatch (async — the device
        # cost lives in the rollup budget, see benchmarks/query_slo.py).
        self.ctx_stats = {"ctx_advances": 0, "ctx_maintenance_ms": 0.0}
        # write-ahead log seam (tpu/wal.py): when set, every fused batch
        # is logged inside the state lock and wal_seq records the last
        # sequence folded into self.state — snapshots read both under
        # the same lock so replay-from-snapshot is exact.
        self.wal_hook: Optional[callable] = None
        self.wal_seq = 0
        # tail-sampling gate (zipkin_tpu/sampling.HostSampler): when
        # installed, every batch through ingest_fused is scored with the
        # bit-exact host reference — observations feed the controller,
        # and the WAL persists only the KEPT lanes. Installed by the
        # storage adapter AFTER boot restore/replay (replayed batches are
        # already compacted and must not be re-observed).
        self.sampler = None
        # Monotonic counter bumped on EVERY state mutation (step, flush,
        # rollup, restore) — the read-cache invalidation key. Batch count
        # alone is not enough: rollup_now()/flush change query-visible
        # state without a new batch.
        self.write_version = 0

    # -- write path ------------------------------------------------------

    def ingest(self, cols: SpanColumns) -> None:
        """Route one host batch across shards and fold it in (the batch
        ships as one fused u32 array — one transfer, not 17)."""
        live_ts = cols.ts_min[cols.valid]
        t0 = time.perf_counter()
        routed = route_fused(cols, self.n_shards)
        obs.record("route", time.perf_counter() - t0)
        self.ingest_fused(
            routed,
            n_spans=int(cols.valid.sum()),
            n_dur=int((cols.valid & cols.has_dur).sum()),
            n_err=int((cols.valid & cols.err).sum()),
            ts_range=(
                (int(live_ts.min()), int(live_ts.max()))
                if live_ts.size
                else (0, 0)
            ),
        )

    def ingest_fused(
        self,
        fused: np.ndarray,
        n_spans: int,
        n_dur: int,
        n_err: int,
        ts_range=None,
    ) -> None:  # zt-dispatch-critical: the per-chunk device entry point — one device_put + one fused jitted step under the state lock
        """Fold one PRE-ROUTED packed wire image ``[shards, 11, per]``
        into the state — the entry point for producers that already hold
        the wire format (the multi-process parse tier, WAL replay). The
        caller supplies the live/duration/error counts (they are cheap
        at pack time and the image would need unpacking to recount)."""
        lanes = int(fused.shape[-1])  # per-shard lane count (padded)
        if lanes > min(self.config.digest_buffer, self.config.rollup_segment):
            raise ValueError(
                f"batch of {lanes} lanes/shard exceeds digest_buffer "
                f"({self.config.digest_buffer}) or rollup_segment "
                f"({self.config.rollup_segment}); chunk before ingest"
            )
        device_batch = jax.device_put(fused, self._sharding)
        with self.lock:
            # contention-ledger attribution: this hold is the write path
            self.lock.relabel("ingest_fused")
            # fold due maintenance into ONE fused dispatch with the step
            need_flush = self._pend_lanes + lanes > self.config.digest_buffer
            need_rollup = (
                self._lanes_since_rollup + lanes > self.config.rollup_segment
            )
            t0 = time.perf_counter()
            self.state = self._step_variants[(need_flush, need_rollup)](
                self.state, device_batch
            )
            # host wall of the enqueue (async dispatch: this is the cost
            # ingest actually pays, consistent with ctx_maintenance_ms)
            step_wall = time.perf_counter() - t0
            obs.record("device_dispatch", step_wall)
            if need_flush:
                self._pend_lanes = 0
            if need_rollup:
                self._lanes_since_rollup = 0
                self.ctx_stats["ctx_advances"] += 1
                self.ctx_stats["ctx_maintenance_ms"] = step_wall * 1000.0
                obs.record("rollup", step_wall)
            self._pend_lanes += lanes
            self._lanes_since_rollup += lanes
            self.write_version += 1
            c = self.host_counters
            c["spans"] += n_spans
            c["spansWithDuration"] += n_dur
            c["spansWithError"] += n_err
            c["batches"] += 1
            # resident-range bookkeeping (see __init__); unknown range =
            # (0, 2^32-1), conservatively intersecting every window
            lo, hi = ts_range if ts_range is not None else (0, (1 << 32) - 1)
            if (
                n_spans > 0
                and self.config.timetier_enabled
                and ts_range is not None
            ):
                self._tt_max_epoch = max(
                    self._tt_max_epoch,
                    int(hi) // self.config.time_bucket_minutes,
                )
            if n_spans > 0:
                # per-shard live counts straight from the wire image's
                # valid bits (row 10 bit 0) — the ring cursor advances by
                # live count, not padded lanes
                live_per_shard = (fused[:, 10, :] & 1).sum(
                    axis=1, dtype=np.int64
                )
                self._resident.append((lo, hi, self._shard_cursor.copy()))
                self._shard_cursor = self._shard_cursor + live_per_shard
            # zt-lint: disable=ZT09 — per RETIRED resident range (ring-wrap bookkeeping, one pop per overwritten batch), never per span
            while self._resident and (
                (self._shard_cursor - self._resident[0][2]).min()
                >= self.config.ring_capacity
            ):
                self._resident.popleft()
            if self.sampler is not None:
                # host reference verdicts over the SAME published tables
                # the device step just read (both under this lock, so a
                # controller publish can never straddle a batch): exact
                # tallies for the controller + kept-lane WAL compaction
                keep2d = self.sampler.verdict_fused(fused)
                seen_b, kept_b = self.sampler.observe(fused, keep2d)
                c["sampledKept"] += kept_b
                c["sampledDropped"] += seen_b - kept_b
                if self.wal_hook is not None:
                    compacted = self.sampler.compact_fused(fused, keep2d)  # zt-lint: disable=ZT09 — per SHARD (mesh-sized) fancy-index gather; the per-lane work inside is vectorized
                    if compacted is not None:
                        cf, k_spans, k_dur, k_err, k_ts = compacted
                        self.wal_seq = self.wal_hook(
                            cf, k_spans, k_dur, k_err, k_ts,
                            # pre-compaction tallies: replay restores the
                            # exact host counters from these (the record
                            # itself only carries the kept lanes)
                            extra={
                                "seen": seen_b, "kept": kept_b,
                                "seen_dur": n_dur, "seen_err": n_err,
                            },
                        )
            elif self.wal_hook is not None:
                self.wal_seq = self.wal_hook(
                    fused, n_spans, n_dur, n_err, ts_range
                )

    @property
    def lane_cap(self) -> int:
        """Hard per-shard lane ceiling of one fused batch — the coalesce
        planner packs groups up to this (see :meth:`ingest_fused`)."""
        return min(self.config.digest_buffer, self.config.rollup_segment)

    def ingest_fused_multi(
        self,
        parts,
        n_spans: int,
        n_dur: int,
        n_err: int,
        ts_range=None,
        pad_to_multiple: int = 256,
    ) -> None:  # zt-dispatch-critical: the coalesced multi-chunk device entry point
        """Coalesce N pre-routed chunk images into ONE device batch and
        fold it with a single jitted step — the span-ring dispatcher's
        multi-chunk entry point (one ``concat_remap`` + one dispatch +
        one WAL record for the whole run of ready slots).

        ``parts`` is a sequence of ``(fused, svc_map, key_map)``; each
        ``fused`` may be a zero-copy ring-slot view — the gather into
        the freshly allocated bucket image is the only copy it takes,
        and the remap happens on the copied lanes. The bucket ladder
        (:func:`zipkin_tpu.tpu.ingest.lane_bucket`) keeps the device
        shape static across coalesce depths (ZT03). The counts are the
        caller's sums over the member chunks; pad lanes are zero
        (valid=0) so the image replays through :meth:`ingest_fused`
        bit-identically to having ingested it live.
        """
        if len(parts) == 1:
            # degenerate run: identical to the per-chunk path (remap in
            # place, no bucket padding) so coalesce_max=1 stays
            # byte-for-byte the pre-ring WAL stream
            fused, svc_map, key_map = parts[0]
            t0 = time.perf_counter()
            t0_ns = time.perf_counter_ns()
            remap_fused(fused, svc_map, key_map)
            obs.record("mp_lut_remap", time.perf_counter() - t0)
            critpath.stamp_active(
                critpath.SEG_LUT_REMAP, t0_ns, time.perf_counter_ns()
            )
            self.ingest_fused(fused, n_spans, n_dur, n_err, ts_range)
            return
        # zt-lint: disable=ZT09 — per CHUNK of the coalesced run (bounded
        # by coalesce_max), integer shape reads only
        total = sum(int(p[0].shape[-1]) for p in parts)
        cap = self.lane_cap
        if total > cap:
            raise ValueError(
                f"coalesced run of {total} lanes/shard exceeds the lane "
                f"cap ({cap}); the planner must split the run"
            )
        bucket = ing.lane_bucket(total, pad_to_multiple, cap)
        shards, rows = parts[0][0].shape[0], parts[0][0].shape[1]
        t0 = time.perf_counter()
        t0_ns = time.perf_counter_ns()
        out = np.zeros((shards, rows, bucket), np.uint32)
        concat_remap(parts, out)
        obs.record("coalesce", time.perf_counter() - t0)
        critpath.stamp_active(
            critpath.SEG_COALESCE, t0_ns, time.perf_counter_ns()
        )
        self.ingest_fused(out, n_spans, n_dur, n_err, ts_range)

    def set_sampler_tables(
        self, rate: np.ndarray, tail: np.ndarray, link: np.ndarray
    ) -> None:
        """Publish host-computed sampling tables to the device leaves.

        NOT a compiled program: a zero-copy leaf swap (device_put of the
        replicated tables + ``_replace``) under the state lock, so the
        next step — and every later one until the next publish — scores
        against exactly these tables. Publishing changes no query-visible
        answer (verdicts only gate retention), so write_version stays."""
        bt = lambda a: jax.device_put(
            np.ascontiguousarray(
                np.broadcast_to(a, (self.n_shards,) + a.shape)
            ),
            self._sharding,
        )
        with self.lock:
            self.state = self.state._replace(
                s_rate=bt(rate), s_tail=bt(tail), s_link=bt(link)
            )

    # -- read path (merged across shards over ICI) -----------------------
    #
    # Every entrypoint below ends in exactly ONE device→host transfer:
    # the compiled program packs its outputs into a single ZPK1 buffer on
    # device (readpack.pack fused as the program's last stage) and
    # self._pull makes the one counted jax.device_get. Do not add bare
    # np.asarray pulls here — ZT-lint rejects them (rules ZT01/ZT02,
    # gated in tier-1 by tests/test_lint_clean.py).

    def _pull(self, packed) -> list:  # zt-lint: disable=ZT04 — every caller holds self.lock (contract in the docstring); read_stats has no separate lock
        """THE query-path device→host pull: one counted transfer, then
        zero-copy unpack of the ZPK1 sections (callers hold the lock)."""
        self.read_stats["host_transfers"] += 1
        if querytrace.active() is not None:
            # device_wall: dispatch-done -> result device-ready, split
            # out from the transfer below so the per-query waterfall
            # separates device time from wire time. Only a traced query
            # pays the extra block (it is free on the CPU backend and
            # the pull would block identically anyway).
            t0 = time.perf_counter_ns()
            # zt-lint: disable=ZT06 — measurement IS the contract: only
            # a traced query takes this branch, and the pull below would
            # block identically; the split makes device wall observable
            packed = jax.block_until_ready(packed)
            querytrace.stamp_active(
                querytrace.QSEG_DEVICE_WALL, t0, time.perf_counter_ns()
            )
        return readpack.pull(packed)

    def merged_sketches(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(hist [K,B], hll [S+1,m], counters) merged over all shards."""
        with self.lock:
            hist, hll_regs, counters = self._pull(self._merge(self.state))
            return hist, hll_regs, counters

    def _link_context_cached(self):  # zt-lint: disable=ZT04 — callers (dependency_matrices, dependency_edges) hold self.lock around the cache check+fill
        """Device LinkContext for the current state (callers hold lock)."""
        version = self.write_version
        if self._ctx_cache[0] != version:
            t0 = time.perf_counter()
            self._ctx_cache = (version, self._link_ctx(self.state))
            obs.record("ctx_advance", time.perf_counter() - t0)
        return self._ctx_cache[1]

    def dependency_matrices(
        self, ts_lo_min: int, ts_hi_min: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        with self.lock:
            calls, errors = self._pull(self._links(
                self._link_context_cached(), self.state,
                jnp.uint32(ts_lo_min), jnp.uint32(ts_hi_min),
            ))
            return calls, errors

    def merged_digest(self) -> np.ndarray:
        """[K, C, 2] t-digest merged across shards in ONE device dispatch.

        A PURE READ: each shard's pending points are folded into a
        temporary partial on device (state untouched — no flush-on-read
        stalling ingest), shards all_gather over ICI, one row-parallel
        recluster, and only the final [K, C, 2] crosses to the host.
        """
        with self.lock:
            (digest,) = self._pull(self._digest_read(self.state))
            return digest

    def window_fully_rolled(self, ts_lo_min: int, ts_hi_min: int) -> bool:
        """True when no ring-resident span's timestamp can fall in the
        window — the rollup matrices alone then answer it exactly."""
        with self.lock:
            return all(
                ts_hi_min < lo or ts_lo_min > hi
                for lo, hi, _ in self._resident
            )

    def dependency_edges(
        self, ts_lo_min: int, ts_hi_min: int
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(flat_index, calls, errors) [E] — the nonzero-dominant cells of
        the merged link matrix, compacted on device (top-E by call count)
        so a dependency query pulls ~KBs, not two dense [S, S] matrices.

        Windows that cannot intersect any ring-resident span skip the
        link-context half entirely (the reference's read-the-daily-table
        path): one cheap masked-sum dispatch instead of the ring lexsort.
        """
        with self.lock:
            if self.window_fully_rolled(ts_lo_min, ts_hi_min):
                self.read_stats["rolled_only_reads"] += 1
                packed = self._edges_rolled(
                    self.state, jnp.uint32(ts_lo_min), jnp.uint32(ts_hi_min)
                )
            elif self._ctx_cache[0] != self.write_version:
                # FRESH read (first query after a write): one fused
                # dispatch computes ctx from the maintained sort order +
                # the windowed edges, and primes the ctx cache for
                # follow-up windows at this version. The ctx stays on
                # device; only the packed edge triple crosses.
                self.read_stats["ctx_reads"] += 1
                ctx, packed = self._edges_fresh(
                    self.state, jnp.uint32(ts_lo_min), jnp.uint32(ts_hi_min)
                )
                self._ctx_cache = (self.write_version, ctx)
            else:
                self.read_stats["ctx_reads"] += 1
                packed = self._edges(
                    self._ctx_cache[1], self.state,
                    jnp.uint32(ts_lo_min), jnp.uint32(ts_hi_min),
                )
            idx, calls, errors = self._pull(packed)
            return idx, calls, errors

    def _flush_now(self) -> None:  # zt-lint: disable=ZT04 — callers hold self.lock; the state swap + mirror reset must be one critical section, which is why this helper is lock-free
        """Compact the pending digest buffer and reset the host mirror —
        the ONLY correct way to run the flush program (state swap and
        mirror reset are one invariant). Callers hold the lock.

        Deliberately does NOT bump write_version: a flush is
        query-INVISIBLE (the pend-fold and no-pend digest reads are
        bit-identical by construction, and flush touches nothing else),
        so cached reads and the link context stay valid — which is what
        lets a percentile read flush opportunistically without
        invalidating every other cached answer."""
        self.state = self._flush(self.state)
        self._pend_lanes = 0
        self._wal_marker("ttflush")

    def _wal_marker(self, tag: str) -> None:  # zt-lint: disable=ZT04 — called from _flush_now/rollup_now, both under self.lock (same critical section as the state swap being recorded)
        """Log a ZERO-lane WAL record marking an explicit flush/rollup.

        The fused-step flush/rollup variants are replay-deterministic
        (the host re-derives them from lane counts), but the EXPLICIT
        paths — a percentile read's flush-then-read, the time-tier
        sealer's pre-seal flush/rollup — are not: t-digest folding is
        order-sensitive, so replay must re-apply them at the exact
        stream position for the time-bucket digests to come back
        bit-identical. Replay (tpu/wal.py) maps the marker back to
        flush_now/rollup_now; wal_hook is None during replay, so
        replayed markers never re-log."""
        if self.wal_hook is not None and self.config.timetier_enabled:
            self.wal_seq = self.wal_hook(
                np.zeros((self.n_shards, 11, 0), np.uint32),
                0, 0, 0, (0, 0), extra={tag: 1},
            )

    def warm_programs(self, cols: SpanColumns) -> None:
        """Compile every program the steady-state ingest loop can
        dispatch (all fused step variants that can occur for this batch
        size, plus the standalone flush/rollup) by running them on a real
        batch. First compiles through a remote-compile tunnel take
        minutes and must never land inside a timed or serving window.
        Ingests ``cols`` several times — call before real traffic."""
        for force_flush, force_rollup in (
            (False, False), (True, False), (False, True), (True, True)
        ):
            with self.lock:
                if force_flush:
                    self._pend_lanes = self.config.digest_buffer
                if force_rollup:
                    self._lanes_since_rollup = self.config.rollup_segment
            # ingest() picks the variant from the (possibly forced)
            # counters; when a non-forced combination cannot occur at
            # this batch size, ingest lawfully dispatches the variant
            # that WOULD run in production instead — also fine to warm.
            self.ingest(cols)
        self.rollup_now()
        self.flush_now()
        # zt-lint: disable=ZT06 — warm-up's whole point: retire every
        # compile before a timed or serving window can start
        self.block_until_ready()

    def rollup_now(self) -> None:
        """Run the link-rollup program (rollup_step — which also advances
        the persistent incremental link ctx) and reset the write-distance
        tracker. Public for tests and shutdown paths."""
        with self.lock:
            t0 = time.perf_counter()
            self.state = self._rollup(self.state)
            self._lanes_since_rollup = 0
            self.ctx_stats["ctx_advances"] += 1
            self.ctx_stats["ctx_maintenance_ms"] = (
                time.perf_counter() - t0
            ) * 1000.0
            self.write_version += 1
            self._wal_marker("ttroll")

    def flush_now(self) -> None:
        """Public digest flush (compile warm-up, shutdown, tests)."""
        with self.lock:
            self._flush_now()

    def windowed_histograms(self, ts_lo_min: int, ts_hi_min: int) -> np.ndarray:
        """[K, BUCKETS] histogram over the window, merged across shards
        (empty rows where the window predates the slice retention)."""
        with self.lock:
            (out,) = self._pull(self._whist(
                self.state, jnp.uint32(ts_lo_min), jnp.uint32(ts_hi_min)
            ))
            return out

    def quantiles(
        self,
        qs,
        source: str = "digest",
        ts_lo_min: Optional[int] = None,
        ts_hi_min: Optional[int] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """([K, Q] quantiles, [K] counts) computed ON device in a single
        dispatch; ``source`` is "digest" or "hist"; a (ts_lo_min,
        ts_hi_min) window uses the time-sliced histograms — both bounds
        required (a half-open window has no defined slice selection)."""
        if (ts_lo_min is None) != (ts_hi_min is None):
            raise ValueError(
                "ts_lo_min and ts_hi_min must be given together "
                f"(got ts_lo_min={ts_lo_min!r}, ts_hi_min={ts_hi_min!r})"
            )
        qarr = jnp.asarray(np.asarray(qs, np.float32))
        with self.lock:
            if ts_lo_min is not None:
                packed = self._quant_whist(
                    self.state, jnp.uint32(ts_lo_min), jnp.uint32(ts_hi_min),
                    qarr,
                )
            elif source == "digest":
                if self._pend_lanes:
                    # flush-then-read beats the pend-fold read variant:
                    # the fold costs the same compaction (75ms device at
                    # full shapes, QUERY_SLO r3 capture) WITHOUT
                    # advancing state, so every query would re-pay it;
                    # the flush pays it once and the read itself rides
                    # the cheap no-pend program
                    self._flush_now()
                packed = self._quant_digest_nopend(self.state, qarr)
            else:
                packed = self._quant_hist(self.state, qarr)
            q, n = self._pull(packed)
            return q, n

    def tt_read(self, lo_ep: int, hi_ep: int):
        """(slot_epochs [W], hll_regs [S+1, m], digest [K, Cw, 2],
        calls [S, S], errs [S, S]) for the bucket-epoch range
        ``[lo_ep, hi_ep]``, merged across shards on device — ONE packed
        pull (the tier's only device transfer per windowed query: the
        unsealed-suffix read; sealed buckets merge host-side from
        segments). A digest flush runs first so the bucket digests
        include every pending point (same flush-then-read economics as
        quantiles(); explicit-flush replay determinism is covered by the
        ttflush WAL marker)."""
        with self.lock:
            if self._pend_lanes:
                self._flush_now()
            ep, regs, digest, calls, errs = self._pull(self._ttread(
                self._link_context_cached(), self.state,
                jnp.int32(lo_ep), jnp.int32(hi_ep),
            ))
            return ep, regs, digest, calls, errs

    @property
    def tt_max_epoch(self) -> int:
        """Highest bucket epoch ingest has touched (-1: none yet)."""
        return self._tt_max_epoch

    def cardinalities(self) -> np.ndarray:
        """[S+1] HLL distinct-trace estimates (last row global), computed
        on device — only the estimates cross the tunnel, not registers."""
        with self.lock:
            (est,) = self._pull(self._card(self.state))
            return est

    def sketch_overview(self, qs) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """([K, Q] digest quantiles, [K] counts, [S+1] HLL estimates) in
        ONE dispatch and ONE transfer — the coalesced read behind the
        server's /api/v2/tpu/overview endpoint, which previously issued
        three aggregator reads (quantiles + cardinalities + counters)
        per HTTP request."""
        qarr = jnp.asarray(np.asarray(qs, np.float32))
        with self.lock:
            if self._pend_lanes:
                self._flush_now()  # same flush-then-read as quantiles()
            q, n, est = self._pull(self._overview(self.state, qarr))
            return q, n, est

    def sync_pend_lanes(self) -> None:
        """Re-derive the host pend mirror from device state (call after
        replacing ``self.state`` wholesale, e.g. snapshot restore)."""
        with self.lock:
            # routed through the counted chokepoint: a restore-time pull
            # is rare but should still show in the transfer ledger. ONE
            # packed pull covers the pend mirror and (tier on) the
            # restored current-bucket epochs — both i32 lanes.
            lanes = [self.state.pend_pos.reshape(-1)]
            if self.config.timetier_enabled:
                lanes.append(self.state.tb_epoch.reshape(-1))
            packed = readpack.device_get(jnp.concatenate(lanes))
            n_pend = self.state.pend_pos.size
            self._pend_lanes = int(packed[:n_pend].max())
            # write distance since the last rollup is not recorded in
            # state; assume the worst so the next batch rolls up first
            self._lanes_since_rollup = self.config.rollup_segment
            # restored ring content has unknown timestamps: one entry
            # covering every window keeps rolled-only reads conservative
            # until a full ring of new writes has displaced it
            self._resident.clear()
            self._resident.append(
                (0, (1 << 32) - 1, self._shard_cursor.copy())
            )
            if self.config.timetier_enabled:
                # restored current-bucket epochs ARE recorded in state;
                # the freshest one is the unsealed bucket after resume
                self._tt_max_epoch = int(packed[n_pend:].max())
            self.write_version += 1

    def state_arrays(self) -> list:
        """Consistent host copy of every state leaf (see state_clone)."""
        clone, _, _ = self.state_clone()
        return [np.asarray(leaf) for leaf in clone]

    def state_clone(self):
        """(device clone, wal_seq, host_counters copy), all captured
        ATOMICALLY under the lock — everything the snapshot records
        about one instant must come from the same locked section, or a
        batch ingested during the multi-second host pull would be both
        inside the recorded counters and after the recorded wal_seq
        (WAL replay would then double-count it). The lock is held only
        for the clone DISPATCH (ms); callers pull the clone's leaves
        lock-free while ingest continues against the live buffers."""
        with self.lock:
            return (
                self._snap_copy(self.state),
                self.wal_seq,
                dict(self.host_counters),
            )

    def block_until_ready(self) -> None:
        with self.lock:
            jax.tree_util.tree_map(lambda a: a.block_until_ready(), self.state)
