"""One-transfer query reads: pack program outputs into a single buffer.

Every query program used to end in several separate ``np.asarray(...)``
device→host pulls (three for dependency edges, three for the merged
sketches, two for percentiles...). On a high-latency PJRT link each pull
pays the relay's fixed round trip, so a 42.9 ms device program showed an
822 ms quiesced wall — ~8 relay floors of pure transfer amplification
(VERDICT r5). This module makes **exactly one device→host transfer per
query** a structural invariant:

- **Device side** (:func:`pack`): the last stage of every read program
  flattens its output arrays into a single 1-D ``uint32`` buffer with a
  small fixed header, so the whole answer is one wire object — the
  "serve merged sketch reads as one compact wire object" shape of
  "Sketch Disaggregation Across Time and Space" (PAPERS.md).
- **Host side** (:func:`unpack`): one :func:`device_get` pulls the
  buffer; sections come back as zero-copy NumPy **views** into it.
- **Chokepoint** (:func:`device_get`): the only sanctioned device→host
  pull on the query path, with a process-wide transfer counter — so
  amplification is observable (``read_stats``/``/prometheus``) and
  regression-pinnable (tests/test_readpack.py asserts ==1 per query).

Wire format (all little-endian ``uint32`` words)::

    word 0                MAGIC 0x5A504B31 ("ZPK1": format + version)
    word 1                n_sections
    words 2 .. 2+8n-1     per-section header, 8 words each:
                            [0] dtype code (see DTYPE_CODES)
                            [1] byte offset of the section payload,
                                from the start of the buffer
                            [2] payload byte length (unpadded)
                            [3] ndim (0..4)
                            [4..7] dims (unused slots 0)
    then the payloads, each padded to a 4-byte (word) boundary

Shapes and dtypes are static at trace time, so the header is a compiled
constant — packing adds only the concatenation copy on device (KBs for
every query program; the dense state never crosses). Sections are
word-aligned by construction, which is what lets :func:`unpack` return
``.view(dtype)`` slices without copies. Booleans are stored as ``u8``
(NumPy bools are 1 byte, so the view back is also copy-free).
"""

from __future__ import annotations

import threading
import time
from typing import List, Sequence, Tuple

import numpy as np

from zipkin_tpu import obs
from zipkin_tpu.obs import querytrace

MAGIC = 0x5A504B31  # "ZPK1"
_SECTION_WORDS = 8
_MAX_NDIM = 4

# dtype code <-> NumPy dtype. Codes are part of the wire format: append
# only, never renumber (snapshots/benchmark artifacts may hold buffers).
DTYPE_CODES = {
    np.dtype(np.uint8): 0,
    np.dtype(np.uint32): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.float32): 3,
    np.dtype(np.bool_): 4,
    np.dtype(np.uint64): 5,
    np.dtype(np.int64): 6,
    np.dtype(np.float64): 7,
}
CODE_DTYPES = {v: k for k, v in DTYPE_CODES.items()}

# -- transfer accounting (the single chokepoint) -------------------------

_counter_lock = threading.Lock()
_transfers = 0
_transfer_bytes = 0


def device_get(x) -> np.ndarray:
    """THE device→host pull for the query read path. Counts every call
    (and its byte volume) so transfers-per-query is observable;
    everything that serves a query must come through here (pinned by
    ZT-lint rule ZT01 via tests/test_lint_clean.py)."""
    global _transfers, _transfer_bytes
    with _counter_lock:
        _transfers += 1
    import jax

    t0 = time.perf_counter_ns()
    out = np.asarray(jax.device_get(x))
    t1 = time.perf_counter_ns()
    obs.record("readpack_transfer", (t1 - t0) / 1e9)
    querytrace.stamp_active(querytrace.QSEG_READPACK_TRANSFER, t0, t1)
    with _counter_lock:
        _transfer_bytes += out.nbytes
    return out


def transfer_count() -> int:
    """Process-wide device→host transfer count (monotonic)."""
    with _counter_lock:
        return _transfers


def transfer_bytes() -> int:
    """Process-wide device→host transfer volume in bytes (monotonic)."""
    with _counter_lock:
        return _transfer_bytes


# -- device-side pack ----------------------------------------------------


def _section_words(a):
    """Flatten one array into uint32 words (device-side, trace-safe)."""
    import jax
    import jax.numpy as jnp

    a = jnp.asarray(a)
    if a.dtype == jnp.bool_:
        a = a.astype(jnp.uint8)
    flat = a.reshape(-1)
    itemsize = np.dtype(a.dtype).itemsize
    if itemsize == 4:
        return jax.lax.bitcast_convert_type(flat, jnp.uint32)
    if itemsize == 1:
        pad = (-flat.shape[0]) % 4
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        return jax.lax.bitcast_convert_type(
            flat.reshape(-1, 4), jnp.uint32
        )
    if itemsize == 8:
        # widens to [n, 2] words, low word first — matches the host's
        # little-endian view on every platform this runs on
        return jax.lax.bitcast_convert_type(flat, jnp.uint32).reshape(-1)
    raise NotImplementedError(
        f"readpack: unsupported dtype {a.dtype} (itemsize {itemsize})"
    )


def pack(arrays: Sequence) -> "jax.Array":  # noqa: F821 - doc type
    """Pack arrays into one 1-D uint32 wire buffer (device-side).

    Runs as the LAST stage inside a jitted read program: shapes/dtypes
    are static, so the header is a baked constant and XLA fuses the
    bitcasts; only the final concatenated buffer leaves the device.
    """
    import jax.numpy as jnp

    arrays = [jnp.asarray(a) for a in arrays]
    n = len(arrays)
    if n == 0:
        raise ValueError("readpack.pack: need at least one section")
    header_words = 2 + _SECTION_WORDS * n
    header = np.zeros(header_words, np.uint32)
    header[0] = MAGIC
    header[1] = n
    sections = []
    off = header_words * 4
    for i, a in enumerate(arrays):
        if a.ndim > _MAX_NDIM:
            raise ValueError(
                f"readpack.pack: ndim {a.ndim} > {_MAX_NDIM} (section {i})"
            )
        stored = np.dtype(np.uint8) if a.dtype == jnp.bool_ else np.dtype(a.dtype)
        code = DTYPE_CODES.get(
            np.dtype(np.bool_) if a.dtype == jnp.bool_ else np.dtype(a.dtype)
        )
        if code is None:
            raise NotImplementedError(f"readpack: unsupported dtype {a.dtype}")
        nbytes = int(np.prod(a.shape, dtype=np.int64)) * stored.itemsize
        h = 2 + _SECTION_WORDS * i
        header[h + 0] = code
        header[h + 1] = off
        header[h + 2] = nbytes
        header[h + 3] = a.ndim
        for d, dim in enumerate(a.shape):
            header[h + 4 + d] = dim
        words = _section_words(a)
        sections.append(words)
        off += int(words.shape[0]) * 4
    return jnp.concatenate([jnp.asarray(header)] + sections)


# -- host-side unpack ----------------------------------------------------


def unpack(buf: np.ndarray) -> List[np.ndarray]:
    """Split one pulled wire buffer back into its arrays, as zero-copy
    views (every returned array shares ``buf``'s memory)."""
    buf = np.asarray(buf)
    if buf.ndim != 1 or buf.dtype != np.uint32:
        raise ValueError(
            f"readpack.unpack: expected 1-D uint32, got {buf.dtype}{buf.shape}"
        )
    if buf.shape[0] < 2 or int(buf[0]) != MAGIC:
        raise ValueError("readpack.unpack: bad magic (not a ZPK1 buffer)")
    n = int(buf[1])
    raw = buf.view(np.uint8)
    out: List[np.ndarray] = []
    for i in range(n):
        h = buf[2 + _SECTION_WORDS * i : 2 + _SECTION_WORDS * (i + 1)]
        dt = CODE_DTYPES[int(h[0])]
        off, nbytes, ndim = int(h[1]), int(h[2]), int(h[3])
        dims = tuple(int(d) for d in h[4 : 4 + ndim])
        out.append(raw[off : off + nbytes].view(dt).reshape(dims))
    return out


def pull(packed) -> List[np.ndarray]:
    """One transfer + unpack: the host half of a packed query read."""
    buf = device_get(packed)
    if querytrace.active() is None:
        return unpack(buf)
    t0 = time.perf_counter_ns()
    out = unpack(buf)
    querytrace.stamp_active(
        querytrace.QSEG_UNPACK, t0, time.perf_counter_ns()
    )
    return out


def describe(buf: np.ndarray) -> List[Tuple[str, tuple, int]]:
    """Header introspection: [(dtype_name, shape, byte_len), ...]."""
    return [
        (a.dtype.name, a.shape, a.nbytes) for a in unpack(np.asarray(buf))
    ]
