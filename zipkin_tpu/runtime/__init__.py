"""Host-side runtime supervision for long ingest runs (ISSUE 3)."""
