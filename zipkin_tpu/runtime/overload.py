"""Overload control plane: the brownout ladder over the signal floor.

PRs 6-12 built the measurement stack — queue saturation and Little's-
law occupancy from the critical-path stitcher, windowed stage p99s,
lock-waiter gauges from the query ledger, snapshot age, burn-rate SLOs
— but nothing *acted* on those signals: a flood piled up behind bare
429s and a full disk was a crash. This module converts measurement
into survival behavior (ISSUE 13):

- **Load index**: every telemetry tick folds the already-published
  signals into one normalized scalar. Each signal is scaled by its
  design limit (the same limits the SLO specs use), the fold is a MAX
  — overload is a bottleneck property, a healthy mean does not excuse
  a saturated queue — and the result is EMA-smoothed so one noisy tick
  cannot flap the ladder.
- **Brownout ladder** B0→B3, hysteretic (enter thresholds above exit
  thresholds, plus a minimum dwell before stepping DOWN; stepping UP is
  immediate and may jump levels):

  - **B0** normal operation.
  - **B1** sheds expensive observability (self-spans, slowest-chunk
    timelines) and serves reads cache-first within a stated staleness
    bound — reads stay servable lock-free under pressure, the "Fast
    Concurrent Data Sketches" split.
  - **B2** adds probabilistic ingest admission by VALUE class ("Trace
    Sampling 2.0": when admission tightens, error traffic must survive
    while bulk is shed): error-carrying payloads always admit, bulk
    admits with a probability that falls as the load index climbs, and
    every bulk shed nudges the sampling ``RateController``'s pressure
    hook so sustained overload degrades into lower sampling rates
    rather than more rejections.
  - **B3** serves cached-only reads and admits essential (error-class)
    ingest only. Nothing is EVER acked without reaching the same
    durability path as B0 traffic — a shed is an explicit 429 /
    RESOURCE_EXHAUSTED with backoff guidance, never a silent 2xx.

- **Backoff guidance**: sheds carry a retry delay and a SCOPE. A
  global shed's delay derives from the live load index (jittered so a
  synchronized retry storm decorrelates); a tenant shed's delay derives
  from that tenant's own bucket deficit, not global load. Both surface
  as HTTP ``Retry-After`` / ``X-Shed-Scope`` and gRPC ``retry-delay`` /
  ``shed-scope`` trailing metadata at the server boundary, so a client
  can distinguish "you are being limited" from "the system is browning
  out".
- **Tenant fold** (ISSUE 18): when a :class:`TenantAdmission` table is
  attached, :meth:`admit` consults the offending tenant's budget FIRST
  — a flooding tenant is driven to B2/B3-style admission on its own
  while every other tenant (and this global ladder) stays B0. The
  global ladder engages only when aggregate signals — HBM, WAL fsync,
  queue saturation — trip, exactly as before.
- **Provability**: ladder state, load index, per-class admit/shed
  counters, and the transition history publish to ``/metrics``,
  ``/prometheus`` (``zipkin_tpu_overload_*``), and the statusz
  ``overload`` section; every transition fires the incident recorder
  (PR 12) so the flight around a brownout is captured.

The controller is deliberately storage-agnostic: it reads the counter
dict the windowed plane already samples and the windowed stage
histograms, so tests drive it with synthetic ticks and the server
wires it with one ``windows.on_tick`` subscription.
"""

from __future__ import annotations

import collections
import random
import threading
import time
from typing import Callable, Dict, List, Optional

B0, B1, B2, B3 = 0, 1, 2, 3
LEVEL_NAMES = ("B0", "B1", "B2", "B3")

# value classes for admission accounting; "error" is the essential
# class (B3 still admits it), everything unclassified is "bulk"
CLASS_ERROR = "error"
CLASS_BULK = "bulk"

# cheap value-class probe: Zipkin JSON/proto error spans carry the
# literal tag key "error" in their serialized bytes; a substring scan
# is one C-level memmem pass over a payload we have not parsed yet —
# the boundary cannot afford a parse just to decide admission. It
# over-matches (any "error" annotation text), which errs on the side
# of admitting: acceptable for a shed heuristic, fatal the other way.
_ERROR_PROBE = b"error"


class OverloadController:
    """Folds published signals into a hysteretic brownout ladder."""

    def __init__(
        self,
        *,
        short_s: float = 10.0,
        enter: tuple = (0.70, 0.85, 0.95),
        exit_margin: float = 0.10,
        dwell_ticks: int = 5,
        ema_alpha: float = 0.5,
        min_bulk_admit: float = 0.05,
        max_stale_ms: int = 5000,
        retry_base_s: float = 0.25,
        retry_cap_s: float = 30.0,
        rate_controller=None,
        history: int = 64,
        seed: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
        # per-signal design limits: gauge value / limit = pressure 1.0
        queue_saturation_limit: float = 0.9,
        occupancy_limit: float = 0.95,
        wire_to_ack_p99_limit_us: int = 250_000,
        wal_fsync_p99_limit_us: int = 100_000,
        query_wall_p99_limit_us: int = 50_000,
        lock_waiters_limit: float = 4.0,
        snapshot_age_limit_s: float = 1800.0,
        hbm_limit_frac: float = 0.92,
        hbm_stats: Optional[Callable[[], Dict]] = None,
    ) -> None:
        if not (len(enter) == 3 and enter[0] < enter[1] < enter[2]):
            raise ValueError("enter thresholds must be 3 ascending values")
        self.short_s = float(short_s)
        self.enter = tuple(float(x) for x in enter)
        self.exit_margin = float(exit_margin)
        self.dwell_ticks = max(1, int(dwell_ticks))
        self.ema_alpha = min(1.0, max(0.01, float(ema_alpha)))
        self.min_bulk_admit = min(1.0, max(0.0, float(min_bulk_admit)))
        self.max_stale_ms = int(max_stale_ms)
        self.retry_base_s = float(retry_base_s)
        self.retry_cap_s = float(retry_cap_s)
        self.rate_controller = rate_controller
        # per-tenant budget table (runtime/tenant.py); admit() consults
        # it first so a flooding tenant sheds alone while the global
        # ladder stays wherever the aggregate signals put it
        self.tenant_admission = None
        self._clock = clock
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._level = B0
        self._load = 0.0
        self._raw_load = 0.0
        self._signals: Dict[str, float] = {}
        self._top_signal = ""
        self._ticks_at_level = 0
        self._limits = dict(
            queue_saturation=queue_saturation_limit,
            occupancy=occupancy_limit,
            wire_to_ack_p99_us=float(wire_to_ack_p99_limit_us),
            wal_fsync_p99_us=float(wal_fsync_p99_limit_us),
            query_wall_p99_us=float(query_wall_p99_limit_us),
            lock_waiters=lock_waiters_limit,
            snapshot_age_s=snapshot_age_limit_s,
            hbm=hbm_limit_frac,
        )
        if hbm_stats is None:
            from zipkin_tpu.obs.device import hbm_stats as _hbm

            hbm_stats = _hbm
        self._hbm_stats = hbm_stats
        # admission state: fractional-credit scheduler so a p of 0.25
        # admits exactly every 4th bulk payload instead of relying on a
        # coin flip to average out over a short flood
        self._bulk_credit = 0.0
        # counters (monotonic; merged into the /metrics gauge export)
        self.transitions = 0
        self.admitted_total = 0
        self.admitted_essential = 0
        self.shed_bulk = 0
        self.shed_total = 0
        self.shed_tenant = 0
        self.deadline_expired = 0
        self.ticks = 0
        self.history: collections.deque = collections.deque(maxlen=history)
        # on_transition(event_dict) fires once per level change, outside
        # the controller lock — the incident recorder registers here
        self.on_transition: List[Callable[[Dict], None]] = []

    # -- signal fold ---------------------------------------------------

    def on_tick(self, win) -> None:
        """``WindowedTelemetry.on_tick`` subscriber: sample the signal
        set from the windowed plane and advance the ladder."""
        counters = win.current_counters()
        w = win.window(self.short_s)
        p99 = {}
        for stage in ("wire_to_ack", "wal_fsync", "query_wall"):
            try:
                stat = w.stage(stage)
                p99[stage] = float(stat.p99_us) if stat.count else 0.0
            except KeyError:
                p99[stage] = 0.0
        self.evaluate(counters, p99)

    def evaluate(self, counters: Dict[str, float],
                 p99_us: Optional[Dict[str, float]] = None) -> int:
        """One control step from explicit inputs (the testable core).
        Returns the post-step level."""
        p99_us = p99_us or {}
        lim = self._limits
        signals = {
            "queue_saturation":
                float(counters.get("critpathQueueSaturation", 0.0))
                / lim["queue_saturation"],
            "occupancy":
                float(counters.get("critpathWorkerOccupancy", 0.0))
                / lim["occupancy"],
            "wire_to_ack_p99":
                p99_us.get("wire_to_ack", 0.0) / lim["wire_to_ack_p99_us"],
            "wal_fsync_p99":
                p99_us.get("wal_fsync", 0.0) / lim["wal_fsync_p99_us"],
            "query_wall_p99":
                p99_us.get("query_wall", 0.0) / lim["query_wall_p99_us"],
            "lock_waiters":
                float(counters.get("queryLockWaiters", 0.0))
                / lim["lock_waiters"],
            "snapshot_age":
                float(counters.get("snapshotAgeS", 0.0))
                / lim["snapshot_age_s"],
        }
        hbm = None
        try:
            hbm = self._hbm_stats()
        except Exception:
            hbm = None
        if hbm and hbm.get("bytesLimit"):
            signals["hbm"] = (
                hbm["bytesInUse"] / hbm["bytesLimit"] / lim["hbm"]
            )
        raw = max(signals.values()) if signals else 0.0
        top = max(signals, key=signals.get) if signals else ""
        with self._lock:
            self.ticks += 1
            self._raw_load = raw
            self._signals = signals
            self._top_signal = top
            self._load = (
                self.ema_alpha * raw + (1.0 - self.ema_alpha) * self._load
            )
            event = self._step_locked()
        ta = self.tenant_admission
        if ta is not None:
            try:
                ta.tick()
            except Exception:
                pass
        if event is not None:
            for cb in list(self.on_transition):
                try:
                    cb(event)
                except Exception:
                    pass
        return self._level

    def _step_locked(self) -> Optional[Dict]:
        """Advance the ladder one tick. UP is immediate (jumps to the
        highest entered level); DOWN is one level per dwell window and
        only once the load has cleared the level's exit threshold
        (enter - exit_margin) — classic hysteresis so the ladder cannot
        flap around a threshold."""
        load = self._load
        target_up = B0
        for i, thr in enumerate(self.enter):
            if load >= thr:
                target_up = i + 1
        new = self._level
        if target_up > self._level:
            new = target_up
        else:
            self._ticks_at_level += 1
            if self._level > B0 and self._ticks_at_level >= self.dwell_ticks:
                exit_thr = self.enter[self._level - 1] - self.exit_margin
                if load < exit_thr:
                    new = self._level - 1
        if new == self._level:
            return None
        event = {
            "at": time.time(),
            "mono": self._clock(),
            "from": LEVEL_NAMES[self._level],
            "to": LEVEL_NAMES[new],
            "fromLevel": self._level,
            "toLevel": new,
            "loadIndex": round(load, 4),
            "topSignal": self._top_signal,
            "signals": {k: round(v, 4) for k, v in self._signals.items()},
        }
        self._level = new
        self._ticks_at_level = 0
        self.transitions += 1
        self.history.append(event)
        return event

    # -- read side -----------------------------------------------------

    @property
    def level(self) -> int:
        return self._level

    @property
    def level_name(self) -> str:
        return LEVEL_NAMES[self._level]

    @property
    def load_index(self) -> float:
        return self._load

    def shed_observability(self) -> bool:
        """B1+: skip self-span emission and slowest-chunk timeline
        capture — the observability the observer can live without."""
        return self._level >= B1

    def read_mode(self) -> str:
        """``normal`` | ``cache_first`` | ``cache_only``. Cache-first
        serves a cached result within ``max_stale_ms`` before touching
        the device plane; cache-only (B3) never touches it."""
        if self._level >= B3:
            return "cache_only"
        if self._level >= B1:
            return "cache_first"
        return "normal"

    # -- admission -----------------------------------------------------

    @staticmethod
    def classify(data: bytes) -> str:
        """Cheap value-class probe over unparsed payload bytes."""
        return CLASS_ERROR if _ERROR_PROBE in data else CLASS_BULK

    def admit(self, data: bytes = b"", tenant: Optional[str] = None,
              value_class: Optional[str] = None):
        """Tenant-aware admission chokepoint: classify once, consult
        the tenant's own budget first (scope ``tenant`` — everyone else
        is unaffected), then the global brownout ladder (scope
        ``global``). Returns an :class:`AdmitVerdict` carrying the
        scope and per-scope Retry-After guidance, so the boundary can
        tell a limited tenant apart from a browning-out system.
        """
        # zt-tenant-admission: single chokepoint every boundary-
        # reachable ingest path must traverse before device dispatch
        from zipkin_tpu.runtime.tenant import (
            DEFAULT_TENANT, AdmitVerdict,
        )

        t = tenant if tenant else DEFAULT_TENANT
        ta = self.tenant_admission
        cls = value_class
        if cls is None:
            # classify only when someone will act on the class — the
            # substring probe is cheap but not free at B0 line rate.
            # An accounting-only tenant table (no byte budget, no
            # retained table) can never shed, so it does not count.
            ta_can_shed = (
                ta is not None and ta.enabled
                and (ta.bytes_per_s > 0 or ta.retained_table is not None)
            )
            if ta_can_shed or self._level >= B2:
                cls = self.classify(data)
            else:
                cls = CLASS_BULK
        if ta is not None and ta.enabled:
            ok, retry = ta.admit(t, len(data), cls)
            if not ok:
                with self._lock:
                    self.shed_tenant += 1
                rc = self.rate_controller
                if rc is not None:
                    try:
                        rc.note_pressure()
                    except Exception:
                        pass
                return AdmitVerdict(False, cls, "tenant", t, retry)
        admitted, cls = self.admit_ingest(data, value_class=cls)
        if not admitted:
            return AdmitVerdict(False, cls, "global", t,
                                self.retry_after_s())
        return AdmitVerdict(True, cls, "none", t, 0.0)

    def admit_ingest(self, data: bytes = b"",
                     value_class: Optional[str] = None) -> tuple:
        """GLOBAL-ladder admission verdict for one ingest payload:
        ``(admitted, value_class)``. B0/B1 admit everything; B2 always
        admits the error class and sheds bulk probabilistically
        (fractional-credit, so the admit rate tracks the target
        exactly); B3 admits the error class only. Every bulk shed
        nudges the sampling controller's pressure hook. Tenant-scoped
        budgets do NOT apply here — the boundary goes through
        :meth:`admit`, which folds them in first."""
        cls = value_class if value_class is not None else (
            self.classify(data) if self._level >= B2 else CLASS_BULK
        )
        level = self._level
        if level < B2:
            with self._lock:
                self.admitted_total += 1
            return True, cls
        if cls == CLASS_ERROR:
            with self._lock:
                self.admitted_total += 1
                self.admitted_essential += 1
            return True, cls
        if level >= B3:
            self._note_shed()
            return False, cls
        p = self._bulk_admit_p()
        with self._lock:
            self._bulk_credit += p
            if self._bulk_credit >= 1.0:
                self._bulk_credit -= 1.0
                self.admitted_total += 1
                return True, cls
        self._note_shed()
        return False, cls

    def _bulk_admit_p(self) -> float:
        """Bulk admit probability in B2: 1.0 at the B2 threshold,
        falling linearly to ``min_bulk_admit`` at the B3 threshold."""
        lo, hi = self.enter[1], self.enter[2]
        frac = (self._load - lo) / max(1e-9, hi - lo)
        return max(self.min_bulk_admit, 1.0 - min(1.0, max(0.0, frac)))

    def _note_shed(self) -> None:
        with self._lock:
            self.shed_total += 1
            self.shed_bulk += 1
        rc = self.rate_controller
        if rc is not None:
            try:
                rc.note_pressure()
            except Exception:
                pass

    def note_deadline_expired(self, n: int = 1) -> None:
        """Server boundary dropped work already past its deadline."""
        with self._lock:
            self.deadline_expired += n

    # -- backoff guidance ----------------------------------------------

    def retry_after_s(self, tenant: Optional[str] = None) -> float:
        """Shed backoff. With a ``tenant`` and an attached tenant
        table, guidance is that tenant's own bucket-refill horizon —
        its load, not global load. Otherwise (global sheds) it grows
        with the load index, jittered ±30% so a synchronized client
        fleet decorrelates its retries instead of re-flooding on one
        boundary."""
        ta = self.tenant_admission
        if tenant is not None and ta is not None and ta.enabled:
            try:
                return ta.retry_after_s(tenant)
            except Exception:
                pass
        base = self.retry_base_s * (
            1.0 + 4.0 * min(2.0, max(0.0, self._load))
            + 2.0 * self._level
        )
        jitter = 0.7 + 0.6 * self._rng.random()
        return min(self.retry_cap_s, max(0.05, base * jitter))

    # -- export --------------------------------------------------------

    def counters(self) -> Dict[str, float]:
        """Scalar gauges for the /metrics merge."""
        out = {
            "overloadLevel": self._level,
            "overloadLoadIndex": round(self._load, 4),
            "overloadRawLoadIndex": round(self._raw_load, 4),
            "overloadTransitions": self.transitions,
            "overloadAdmitted": self.admitted_total,
            "overloadAdmittedEssential": self.admitted_essential,
            "overloadShedBulk": self.shed_bulk,
            "overloadShedTotal": self.shed_total,
            "overloadShedTenant": self.shed_tenant,
            "overloadObsShed": int(self.shed_observability()),
            "deadlineExpired": self.deadline_expired,
        }
        ta = self.tenant_admission
        if ta is not None:
            try:
                out.update(ta.counters())
            except Exception:
                pass
        return out

    def status(self) -> Dict:
        """Full dict for the statusz ``overload`` section."""
        ta = self.tenant_admission
        tenants = None
        if ta is not None:
            try:
                tenants = ta.status()
            except Exception:
                tenants = None
        with self._lock:
            return {
                "tenants": tenants,
                "level": self._level,
                "levelName": LEVEL_NAMES[self._level],
                "loadIndex": round(self._load, 4),
                "rawLoadIndex": round(self._raw_load, 4),
                "topSignal": self._top_signal,
                "signals": {k: round(v, 4)
                            for k, v in self._signals.items()},
                "readMode": self.read_mode(),
                "maxStaleMs": self.max_stale_ms,
                "bulkAdmitP": round(self._bulk_admit_p(), 4)
                if self._level >= B2 else 1.0,
                "enterThresholds": list(self.enter),
                "exitMargin": self.exit_margin,
                "dwellTicks": self.dwell_ticks,
                "ticks": self.ticks,
                "counters": {
                    "admitted": self.admitted_total,
                    "admittedEssential": self.admitted_essential,
                    "shedBulk": self.shed_bulk,
                    "shedTotal": self.shed_total,
                    "shedTenant": self.shed_tenant,
                    "deadlineExpired": self.deadline_expired,
                    "transitions": self.transitions,
                },
                "history": list(self.history),
            }
