"""Background at-rest CRC scrubber for the durability plane (ISSUE 7).

Restore-time digest verification only catches rot when a boot happens
to read the rotted artifact; at the north-star scale (1B spans on
disk) silent media corruption is an expected event, so sealed WAL
segments, archive frames, and retained snapshot generations are
re-verified while the server RUNS — the classic storage-system scrub
(ZFS/ceph posture), paced by a byte budget so a terabyte of cold
segments never competes with line-rate ingest.

Quarantine semantics (shared with tpu/snapshot.py restore fallback):

- a bad artifact is renamed aside with ``.quarantine`` — NEVER
  unlinked; it is the postmortem evidence of what rotted and when;
- an archive segment with a bad frame leaves the read set whole
  (searches skip it with accounting — ``spansQuarantined`` — instead
  of failing the query; in-flight snapshots keep reading via the
  retained fd);
- a WAL segment is quarantined only when every record it holds is
  already covered by the newest durable snapshot — replay would seek
  past them anyway, so pulling the file is loss-free. A corrupt record
  in the UNCOVERED suffix is left in place (replay's torn-tail rule
  salvages the good prefix) and surfaced as ``scrubCorruptDetected``;
- a snapshot generation failing its leaf-digest manifest is
  quarantined exactly like a restore-time mismatch; the next boot
  falls back to an older retained generation + the longer WAL suffix.

Counters flow through ``TpuStorage.ingest_counters()`` to ``/metrics``
and ``/prometheus``; ``status()`` feeds the durability section of
``/api/v2/tpu/statusz``.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)


class Scrubber:
    """Paced background scanner over a store's durable artifacts.

    ``interval_s`` is the idle gap between full passes;
    ``bytes_per_sec`` caps read bandwidth WITHIN a pass (0 = unpaced —
    tests and the overhead benchmark's worst case)."""

    def __init__(
        self,
        store,
        *,
        interval_s: float = 300.0,
        bytes_per_sec: int = 8 << 20,
    ) -> None:
        self.store = store
        self.interval_s = float(interval_s)
        self.bytes_per_sec = int(bytes_per_sec)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._counters = {
            "scrubBytes": 0,
            "scrubPasses": 0,
            "scrubFiles": 0,
            "segmentsQuarantined": 0,
            "spansQuarantined": 0,
            "scrubCorruptDetected": 0,
        }
        self._last_pass: Optional[dict] = None
        # pacing state (single scan thread; never touched under _lock)
        self._t0 = 0.0
        self._debt = 0.0

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="zipkin-tpu-scrub", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        # first pass only after one full interval: the boot restore just
        # verified everything a restore touches, so scrubbing at t=0
        # would double-read the hot set during startup
        while not self._stop.wait(self.interval_s):
            try:
                self.scan_once()
            except Exception:  # pragma: no cover - defensive
                logger.exception("scrub pass failed; will retry next interval")

    # -- pacing ----------------------------------------------------------

    def _pace(self, nbytes: int) -> None:
        if self.bytes_per_sec <= 0 or nbytes <= 0:
            return
        self._debt += nbytes / self.bytes_per_sec
        while not self._stop.is_set():
            ahead = self._debt - (time.monotonic() - self._t0)
            if ahead <= 0:
                break
            self._stop.wait(min(ahead, 0.2))

    # -- one full pass ---------------------------------------------------

    def scan_once(self) -> dict:
        """Verify every at-rest artifact once; returns this pass's
        summary (also retained for ``status()``). Safe to call from
        tests/benchmarks without ``start()``."""
        t_start = time.time()
        self._t0 = time.monotonic()
        self._debt = 0.0
        stats = dict(
            files=0, bytes=0, corrupt=0, quarantined=0, spans_quarantined=0
        )
        self._scrub_wal(stats)
        self._scrub_archive(stats)
        self._scrub_generations(stats)
        self._scrub_vocab_sidecar(stats)
        pass_ms = round((time.time() - t_start) * 1000.0, 3)
        with self._lock:
            self._counters["scrubPasses"] += 1
            self._counters["scrubFiles"] += stats["files"]
            self._counters["scrubBytes"] += stats["bytes"]
            self._counters["scrubCorruptDetected"] += stats["corrupt"]
            self._counters["segmentsQuarantined"] += stats["quarantined"]
            self._counters["spansQuarantined"] += stats["spans_quarantined"]
            self._last_pass = {
                "at": t_start,
                "ms": pass_ms,
                "files": stats["files"],
                "bytes": stats["bytes"],
                "corruptDetected": stats["corrupt"],
                "quarantined": stats["quarantined"],
            }
        if stats["corrupt"] or stats["quarantined"]:
            logger.warning(
                "scrub pass: %d files / %d bytes verified, %d corrupt, "
                "%d quarantined",
                stats["files"], stats["bytes"], stats["corrupt"],
                stats["quarantined"],
            )
        return dict(stats, ms=pass_ms)

    def _snapshot_covered_seq(self) -> int:
        """wal_seq of the newest durable snapshot (meta.json) — the
        loss-free WAL quarantine bar. 0 when no snapshot exists (then
        NO record is covered and no WAL segment is ever quarantined)."""
        directory = getattr(self.store, "checkpoint_dir", None)
        if not directory:
            return 0
        from zipkin_tpu.tpu.snapshot import META_FILE

        try:
            with open(os.path.join(directory, META_FILE)) as f:
                return int(json.load(f).get("wal_seq", 0))
        except (OSError, ValueError):
            return 0

    def _scrub_wal(self, stats: dict) -> None:
        wal = getattr(self.store, "wal", None)
        if wal is None:
            return
        from zipkin_tpu.tpu import wal as wal_mod

        for path in wal.sealed_segment_paths():
            try:
                size = os.path.getsize(path)
                res = wal_mod.verify_segment(path)
            except OSError:
                continue  # truncate_covered raced us; nothing to verify
            stats["files"] += 1
            stats["bytes"] += size
            self._pace(size)
            if res["ok"]:
                continue
            stats["corrupt"] += 1
            covered = self._snapshot_covered_seq()
            if res["max_seq"] <= covered:
                # every readable record is snapshot-covered and the
                # unreadable tail is unreplayable either way: pulling
                # the file is loss-equivalent and cleans the next boot
                try:
                    os.replace(path, path + ".quarantine")
                    stats["quarantined"] += 1
                    logger.warning(
                        "WAL segment %s quarantined (bad record seq %s at "
                        "offset %s; all %d readable records <= covered %d)",
                        path, res["bad_seq"], res["bad_offset"],
                        res["records"], covered,
                    )
                except OSError:
                    pass
            else:
                logger.warning(
                    "WAL segment %s has a bad record (seq %s at offset %s) "
                    "in the UNCOVERED suffix; leaving in place for replay's "
                    "torn-tail salvage", path, res["bad_seq"],
                    res["bad_offset"],
                )

    def _scrub_archive(self, stats: dict) -> None:
        disk = getattr(self.store, "_disk", None)
        if disk is None:
            return
        from zipkin_tpu.tpu import archive as archive_mod

        for path in disk.sealed_segment_paths():
            try:
                size = os.path.getsize(path)
                res = archive_mod.verify_frames(path)
            except OSError:
                continue  # retention unlinked it mid-pass
            stats["files"] += 1
            stats["bytes"] += size
            self._pace(size)
            if res["ok"]:
                continue
            stats["corrupt"] += 1
            n = disk.quarantine_segment(path)
            if n or not os.path.exists(path):
                stats["quarantined"] += 1
                stats["spans_quarantined"] += n

    def _scrub_generations(self, stats: dict) -> None:
        directory = getattr(self.store, "checkpoint_dir", None)
        if not directory or not os.path.isdir(directory):
            return
        from zipkin_tpu.tpu import snapshot as snap_mod

        for _, name in snap_mod._state_generations(directory):
            gm_path = os.path.join(directory, snap_mod._gen_meta_name(name))
            state_path = os.path.join(directory, name)
            try:
                with open(gm_path) as f:
                    crcs = json.load(f).get("leaf_crcs")
            except (OSError, ValueError):
                continue  # orphan or pre-manifest generation: unjudgeable
            bad = False
            try:
                size = os.path.getsize(state_path)
                loaded = np.load(state_path)
                leaves = [loaded[k] for k in loaded.files]
                got = snap_mod.leaf_digests(leaves)
                bad = crcs is None or len(crcs) != len(got) or any(
                    int(w) != g for w, g in zip(crcs, got)
                )
            except FileNotFoundError:
                continue  # pruned mid-pass
            except Exception:
                bad = True
                size = 0
                try:
                    size = os.path.getsize(state_path)
                except OSError:
                    pass
            stats["files"] += 1
            stats["bytes"] += size
            self._pace(size)
            if bad:
                stats["corrupt"] += 1
                stats["quarantined"] += 1
                snap_mod.quarantine_generation(directory, name)

    def _scrub_vocab_sidecar(self, stats: dict) -> None:
        """The archive vocab sidecar self-records a payload crc32 (see
        store._persist_archive_vocab); rot there would remap every id
        on recovered segments at the NEXT boot — catch it now."""
        path = getattr(self.store, "_archive_vocab_path", None)
        if not path or not os.path.exists(path):
            return
        import zlib

        try:
            size = os.path.getsize(path)
            with open(path) as f:
                meta = json.load(f)
            want = meta.pop("crc32", None)
            ok = want is None or zlib.crc32(
                json.dumps(meta, sort_keys=True, separators=(",", ":")).encode()
            ) == int(want)
        except (OSError, ValueError):
            ok, size = False, 0
        stats["files"] += 1
        stats["bytes"] += size
        self._pace(size)
        if not ok:
            stats["corrupt"] += 1
            # do not quarantine out from under a RUNNING store — its
            # vocab is live in memory and the next persist rewrites the
            # sidecar whole; boot-time verification handles a cold read
            logger.warning(
                "archive vocab sidecar %s failed its digest at rest; the "
                "next vocab growth rewrites it", path,
            )

    # -- surfaces --------------------------------------------------------

    def counters(self) -> dict:
        with self._lock:
            return dict(self._counters)

    def status(self) -> dict:
        with self._lock:
            last = dict(self._last_pass) if self._last_pass else None
        return {
            "running": self._thread is not None,
            "intervalS": self.interval_s,
            "bytesPerSec": self.bytes_per_sec,
            "lastPass": last,
        }
