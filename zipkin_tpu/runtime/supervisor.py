"""Resume supervisor: snapshot-and-exit-restartable on degraded windows.

VERDICT r5: the flagship run died at 90.7% of 1B inside a degraded
relay window — wire rate collapsed, the deadline passed, and nothing
could persist the accumulated state and hand off to a fresh window.
This module is that missing piece: it watches the ingest wire-rate
against a rolling baseline of healthy windows, and when the rate stays
collapsed (or a wall deadline arrives) it drains in-flight device
work, takes a snapshot (which truncates covered WAL segments), and
tells the host loop to exit with :data:`EX_RESTART` so an outer driver
(evals/resume_driver.py, systemd, k8s) relaunches it against the same
resume dir — boot restore then continues the run with zero acked-span
loss.

Two ways to drive it:

- **passive** (deterministic, used by evals + tests): the ingest loop
  calls :meth:`ResumeSupervisor.observe` with the cumulative span
  count after each batch; a non-None return is the trip reason and the
  loop should call :meth:`finalize` and exit.
- **threaded**: :meth:`start` samples ``store.ingest_counters()``
  every window on a daemon thread and invokes ``on_trip(reason)`` once
  tripped (the callback decides how to stop the host loop). The thread
  is the ONLY writer of supervisor state after start(), so the class
  needs no lock.
"""

from __future__ import annotations

import logging
import statistics
import threading
import time
from collections import deque
from typing import Callable, Optional

logger = logging.getLogger(__name__)

# BSD sysexits EX_TEMPFAIL: "transient failure, retry" — the contract
# between a supervised window and its relauncher.
EX_RESTART = 75


class RespawnBackoff:
    """Per-child respawn pacing: exponential delay, reset on a child
    that stayed up past ``healthy_s``. Shared by the reader-process
    supervisor (`serving/supervisor.py`) and usable by any relauncher
    that must not hot-loop a crash-on-boot child.

    ``ready_at`` answers "may child ``key`` respawn now?" without
    sleeping — supervisor loops poll, they do not block per child."""

    def __init__(
        self,
        *,
        base_s: float = 0.5,
        max_s: float = 30.0,
        healthy_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.base_s = float(base_s)
        self.max_s = float(max_s)
        self.healthy_s = float(healthy_s)
        self._clock = clock
        # key -> [consecutive_fails, earliest_respawn_at]
        self._state: dict = {}
        self.respawns = 0

    def note_spawn(self, key) -> None:
        st = self._state.setdefault(key, [0, 0.0])
        self._state[key] = [st[0], self._clock()]

    def note_death(self, key, uptime_s: float) -> float:
        """Record a child death; returns the delay before its respawn
        (0 when the child had been up long enough to reset the run)."""
        st = self._state.setdefault(key, [0, 0.0])
        fails = 0 if uptime_s >= self.healthy_s else st[0] + 1
        delay = (
            0.0 if fails == 0
            else min(self.max_s, self.base_s * (2 ** (fails - 1)))
        )
        self._state[key] = [fails, self._clock() + delay]
        self.respawns += 1
        return delay

    def ready_at(self, key) -> float:
        return self._state.get(key, [0, 0.0])[1]

    def ready(self, key) -> bool:
        return self._clock() >= self.ready_at(key)


class ResumeSupervisor:
    def __init__(
        self,
        store,
        *,
        window_s: float = 5.0,
        baseline_windows: int = 12,
        warmup_windows: int = 3,
        degraded_fraction: float = 0.25,
        degraded_windows: int = 3,
        deadline_s: float = 0.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        """``degraded_fraction``: a window is degraded when its rate is
        below this fraction of the rolling baseline (median of the last
        ``baseline_windows`` healthy windows); ``degraded_windows``
        consecutive degraded windows trip. ``deadline_s`` (0 = off)
        trips unconditionally at that wall age. ``clock`` is injectable
        so tests fabricate time."""
        self.store = store
        self.window_s = float(window_s)
        self.warmup_windows = int(warmup_windows)
        self.degraded_fraction = float(degraded_fraction)
        self.degraded_windows = int(degraded_windows)
        self.deadline_s = float(deadline_s)
        self._clock = clock
        self._baseline: deque = deque(maxlen=int(baseline_windows))
        self._t0: Optional[float] = None
        self._last_t = 0.0
        self._last_spans = 0
        self._degraded_run = 0
        self._tripped: Optional[str] = None
        self.windows = 0
        self.last_rate = 0.0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- sampling --------------------------------------------------------

    @property
    def tripped(self) -> Optional[str]:
        return self._tripped

    def baseline_rate(self) -> float:
        return statistics.median(self._baseline) if self._baseline else 0.0

    def observe(self, spans_total: int) -> Optional[str]:
        """Feed the cumulative span count; returns the trip reason
        ("degraded" / "deadline", sticky) or None while healthy."""
        if self._tripped is not None:
            return self._tripped
        now = self._clock()
        if self._t0 is None:
            self._t0 = now
            self._last_t, self._last_spans = now, int(spans_total)
            return None
        if self.deadline_s and now - self._t0 >= self.deadline_s:
            return self._trip("deadline")
        elapsed = now - self._last_t
        if elapsed < self.window_s:
            return None
        rate = (int(spans_total) - self._last_spans) / elapsed
        self._last_t, self._last_spans = now, int(spans_total)
        self.windows += 1
        self.last_rate = rate
        baseline = self.baseline_rate()
        if (
            len(self._baseline) >= self.warmup_windows
            and rate < self.degraded_fraction * baseline
        ):
            self._degraded_run += 1
            logger.warning(
                "supervisor: degraded window %d/%d (%.0f spans/s vs "
                "baseline %.0f)",
                self._degraded_run, self.degraded_windows, rate, baseline,
            )
            if self._degraded_run >= self.degraded_windows:
                return self._trip("degraded")
        else:
            # only healthy windows feed the baseline, so a long
            # degradation cannot talk the baseline down to itself
            self._degraded_run = 0
            self._baseline.append(rate)
        return None

    def _trip(self, reason: str) -> str:
        self._tripped = reason
        logger.warning(
            "supervisor tripped (%s) after %d windows: snapshot and "
            "exit restartable (exit code %d)",
            reason, self.windows, EX_RESTART,
        )
        return reason

    # -- the exit-restartable sequence -----------------------------------

    def finalize(self) -> Optional[str]:
        """Drain in-flight batches, snapshot (truncates covered WAL).
        After this returns, the process may exit with EX_RESTART and a
        relaunch against the same dirs resumes with zero acked loss."""
        agg = getattr(self.store, "agg", None)
        if agg is not None:
            # zt-lint: disable=ZT06 — quiesce-before-snapshot seam: the
            # supervisor's contract is that no in-flight device batch is
            # lost between the last ack and the exit snapshot
            agg.block_until_ready()
        path = None
        if hasattr(self.store, "snapshot"):
            path = self.store.snapshot()
        logger.info("supervisor: exit snapshot %s", path or "(no dir)")
        return path

    def stats(self) -> dict:
        """Gauge-shaped telemetry for /metrics-style surfaces."""
        return {
            "supervisorWindows": self.windows,
            "supervisorLastRate": round(self.last_rate, 3),
            "supervisorBaselineRate": round(self.baseline_rate(), 3),
            "supervisorTripped": self._tripped or "",
        }

    # -- optional threaded driver ----------------------------------------

    def start(self, on_trip: Callable[[str], None]) -> None:
        """Sample ``store.ingest_counters()["spans"]`` every window on a
        daemon thread; call ``on_trip(reason)`` once when tripped."""
        if self._thread is not None:
            raise RuntimeError("supervisor already started")

        def loop() -> None:
            while not self._stop.wait(self.window_s):
                reason = self.observe(
                    self.store.ingest_counters().get("spans", 0)
                )
                if reason is not None:
                    on_trip(reason)
                    return

        self._thread = threading.Thread(
            target=loop, name="zt-resume-supervisor", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.window_s + 5.0)
            self._thread = None
