"""Tenant admission: per-tenant identity, budgets, and flood containment.

The tenant id enters at the transport boundary — the ``X-Tenant-Id``
HTTP header or the ``x-tenant-id`` gRPC metadata key — and rides a
contextvar (:data:`CURRENT_TENANT`) from the aiohttp/grpc handler
through ``asyncio.to_thread`` into the collector's admission chokepoint.
Legacy traffic with no header lands on :data:`DEFAULT_TENANT`, so a
single-tenant deployment behaves exactly as before.

:class:`TenantAdmission` is the budget side of the overload story
(runtime/overload.py): where the global brownout ladder folds
*aggregate* signals (HBM, WAL fsync, queue saturation), this table
holds one token bucket per tenant over ingest bytes/sec plus a
demand/budget pressure EMA, and drives only the *flooding* tenant to
B2/B3-style admission while every other tenant stays B0. A shed here is
scope ``"tenant"``: the client is told "you are being limited", with
Retry-After guidance derived from that tenant's own bucket deficit —
not from global load.

Bounded key spaces are a rule, not a convention (the ``ttq:`` demand
registry in tpu/mirror.py is the template): the tenant table is a
bounded LRU — a hostile stream of unique tenant ids evicts the oldest
entry (never the default tenant) and counts the eviction, so state
cannot grow without bound.
"""

from __future__ import annotations

import contextvars
import re
import threading
import time
from collections import OrderedDict
from typing import Dict, NamedTuple, Optional

DEFAULT_TENANT = "default"
TENANT_HEADER = "X-Tenant-Id"
TENANT_METADATA_KEY = "x-tenant-id"

# Boundary handlers set this; the collector chokepoint reads it. The
# contextvar crosses asyncio.to_thread (the ctx is copied into the
# worker), which is exactly the hop accept_spans_bytes makes.
CURRENT_TENANT: contextvars.ContextVar[str] = contextvars.ContextVar(
    "zipkin_tpu_tenant", default=DEFAULT_TENANT
)

_TENANT_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")


def normalize_tenant(raw: Optional[str]) -> str:
    """Map a wire-supplied tenant id onto the bounded id alphabet.

    Empty, missing, over-long, or hostile ids (label-breaking quotes,
    control bytes, path separators) collapse to the default tenant
    rather than erroring: tenancy must never turn a legacy client's
    traffic into 4xx noise.
    """
    if not raw:
        return DEFAULT_TENANT
    s = str(raw).strip()
    if not s or not _TENANT_RE.match(s):
        return DEFAULT_TENANT
    return s


def tenant_slug(tenant: str) -> str:
    """Flat-counter-safe slug (``tenantShed_<slug>`` etc.)."""
    return re.sub(r"[^A-Za-z0-9_]", "_", tenant)


class AdmitVerdict(NamedTuple):
    """Rich admission verdict from ``OverloadController.admit``.

    ``scope`` says who is shedding: ``"tenant"`` — this tenant's budget
    (everyone else is fine), ``"global"`` — the brownout ladder (the
    system is degrading), ``"none"`` — admitted.
    """

    admitted: bool
    cls: str
    scope: str
    tenant: str
    retry_after_s: float


class _TenantState:
    """Per-tenant bucket + ladder posture. Mutated under the table lock."""

    __slots__ = (
        "tokens", "last_refill", "level", "calm_ticks", "pressure",
        "offered", "offered_bytes", "admitted", "shed",
        "retained_spans", "retained_shed",
    )

    def __init__(self, now: float, burst_bytes: float) -> None:
        self.tokens = burst_bytes
        self.last_refill = now
        self.level = 0          # 0=B0 admit, 2=B2 bulk-shed, 3=B3 essential
        self.calm_ticks = 0
        self.pressure = 0.0     # EMA of offered-rate / budget-rate
        self.offered = 0
        self.offered_bytes = 0
        self.admitted = 0
        self.shed = 0
        self.retained_spans = 0
        self.retained_shed = 0


class TenantAdmission:
    """Bounded-LRU table of per-tenant ingest budgets.

    ``bytes_per_s <= 0`` means accounting-only: every tenant is
    admitted, but offered/admitted tallies, the pressure EMA, and the
    ``{tenant=}`` observability families still populate. With a budget
    set, each tenant gets a token bucket of ``bytes_per_s`` with
    ``burst_s`` seconds of burst; a payload that cannot be paid for is
    shed with scope ``"tenant"`` unless it is error-class (error
    payloads keep the same lifeline the global ladder's B3 grants).

    The per-tenant ladder is demand-driven: sustained demand at
    ``flood_ratio``x budget escalates the tenant to level 2 (bulk
    shed), 2x that to level 3 (essential-only); ``dwell_ticks`` calm
    ticks (no sheds, bucket refilled) step back down one level at a
    time — the same enter-fast/exit-slow hysteresis the global ladder
    uses, scoped to one tenant.
    """

    def __init__(
        self,
        *,
        bytes_per_s: float = 0.0,
        burst_s: float = 2.0,
        max_tenants: int = 64,
        flood_ratio: float = 2.0,
        dwell_ticks: int = 3,
        ema_alpha: float = 0.5,
        clock=time.monotonic,
        retained_table=None,
    ) -> None:
        self.bytes_per_s = float(bytes_per_s)
        self.burst_s = float(burst_s)
        self.max_tenants = max(1, int(max_tenants))
        self.flood_ratio = max(1.0, float(flood_ratio))
        self.dwell_ticks = max(1, int(dwell_ticks))
        self.ema_alpha = float(ema_alpha)
        self.clock = clock
        # Optional sampling-tier coupling: retained-spans/sec budgets
        # live in the RateController's TenantBudgetTable; admission
        # consults its over-budget verdict so a tenant that floods the
        # *retention* budget is bulk-shed at the boundary too.
        self.retained_table = retained_table
        self.enabled = True
        self.evictions = 0
        self._lock = threading.Lock()
        self._tenants: "OrderedDict[str, _TenantState]" = OrderedDict()
        # Demand accounting for the tick-driven pressure EMA.
        self._tick_t = float(clock())

    # -- internals -----------------------------------------------------

    @property
    def burst_bytes(self) -> float:
        if self.bytes_per_s <= 0:
            return 0.0
        return self.bytes_per_s * self.burst_s

    def _state(self, tenant: str, now: float) -> _TenantState:
        st = self._tenants.get(tenant)
        if st is not None:
            self._tenants.move_to_end(tenant)
            return st
        while len(self._tenants) >= self.max_tenants:
            # Evict the least-recently-offered tenant — but never the
            # default tenant, which anchors all legacy traffic.
            for victim in self._tenants:
                if victim != DEFAULT_TENANT:
                    break
            else:
                break
            del self._tenants[victim]
            self.evictions += 1
        st = _TenantState(now, self.burst_bytes)
        self._tenants[tenant] = st
        return st

    def _refill(self, st: _TenantState, now: float) -> None:
        if self.bytes_per_s <= 0:
            return
        dt = max(0.0, now - st.last_refill)
        st.last_refill = now
        st.tokens = min(self.burst_bytes,
                        st.tokens + dt * self.bytes_per_s)

    # -- admission -----------------------------------------------------

    def admit(self, tenant: str, n_bytes: int,
              cls: str = "bulk") -> tuple:
        """Charge ``tenant``'s bucket for ``n_bytes``; returns
        ``(admitted, retry_after_s)``. ``retry_after_s`` is 0.0 on
        admit, else this tenant's own refill horizon.
        """
        now = float(self.clock())
        with self._lock:
            st = self._state(tenant, now)
            st.offered += 1
            st.offered_bytes += int(n_bytes)
            if not self.enabled:
                st.admitted += 1
                return True, 0.0
            self._refill(st, now)
            over_retained = bool(
                self.retained_table is not None
                and self.retained_table.over_budget(tenant)
            )
            if cls == "error" and st.level < 3:
                # Error-class lifeline: mirrors global B3 semantics —
                # the signal about the outage rides through even when
                # the flooder's bucket is dry.
                st.admitted += 1
                if self.bytes_per_s > 0:
                    st.tokens = max(0.0, st.tokens - n_bytes)
                return True, 0.0
            if self.bytes_per_s > 0 and st.tokens < n_bytes:
                st.shed += 1
                if st.level < 2:
                    st.level = 2
                st.calm_ticks = 0
                return False, self._retry_locked(st, n_bytes)
            if over_retained and cls != "error":
                st.retained_shed += 1
                st.shed += 1
                if st.level < 2:
                    st.level = 2
                st.calm_ticks = 0
                return False, self._retry_locked(st, n_bytes)
            if st.level >= 3 and cls != "error":
                st.shed += 1
                st.calm_ticks = 0
                return False, self._retry_locked(st, n_bytes)
            st.admitted += 1
            if self.bytes_per_s > 0:
                st.tokens -= n_bytes
            return True, 0.0

    def note_retained(self, tenant: str, n_spans: int) -> None:
        """Dispatcher-side retained-spans accounting (thread-safe —
        called from the dispatcher thread at ack time). Forwards to the
        sampling tier's per-tenant budget table when one is attached.
        """
        now = float(self.clock())
        with self._lock:
            st = self._state(tenant, now)
            st.retained_spans += int(n_spans)
        rt = self.retained_table
        if rt is not None:
            rt.charge(tenant, n_spans)

    def retry_after_s(self, tenant: str, n_bytes: int = 0) -> float:
        """Per-tenant backoff guidance: this tenant's bucket-refill
        horizon scaled by its ladder level — NOT global load."""
        now = float(self.clock())
        with self._lock:
            st = self._tenants.get(tenant)
            if st is None:
                return 0.05
            self._refill(st, now)
            return self._retry_locked(st, n_bytes)

    def _retry_locked(self, st: _TenantState, n_bytes: int) -> float:
        if self.bytes_per_s > 0:
            deficit = max(0.0, float(n_bytes) - st.tokens)
            base = deficit / self.bytes_per_s if deficit else 0.05
        else:
            base = 0.05
        out = base * (1.0 + st.level)
        return min(30.0, max(0.05, out))

    # -- ladder tick ---------------------------------------------------

    def tick(self, dt_s: float = 1.0) -> None:
        """Demand-pressure EMA + exit hysteresis; call once per
        controller evaluation tick."""
        now = float(self.clock())
        dt = max(1e-6, float(dt_s))
        with self._lock:
            for st in self._tenants.values():
                offered_rate = st.offered_bytes / dt
                st.offered_bytes = 0
                if self.bytes_per_s > 0:
                    ratio = offered_rate / self.bytes_per_s
                else:
                    ratio = 0.0
                a = self.ema_alpha
                st.pressure = (1 - a) * st.pressure + a * ratio
                self._refill(st, now)
                # Enter fast: sustained demand at 2x the flood ratio is
                # an active flood — go essential-only for this tenant.
                if st.pressure >= 2.0 * self.flood_ratio:
                    st.level = 3
                    st.calm_ticks = 0
                    continue
                # Exit slow: one level per dwell of calm ticks, and
                # only once the bucket has refilled past half burst.
                refilled = (self.bytes_per_s <= 0
                            or st.tokens >= 0.5 * self.burst_bytes)
                if st.level > 0 and st.pressure < 1.0 and refilled:
                    st.calm_ticks += 1
                    if st.calm_ticks >= self.dwell_ticks:
                        st.level = 2 if st.level > 2 else 0
                        st.calm_ticks = 0
                else:
                    st.calm_ticks = 0

    # -- observability -------------------------------------------------

    def level_of(self, tenant: str) -> int:
        with self._lock:
            st = self._tenants.get(tenant)
            return st.level if st is not None else 0

    def counters(self) -> Dict[str, float]:
        """Flat counters for the windowed plane / metrics merge: global
        tallies plus ``tenantOffered_<slug>`` / ``tenantShed_<slug>``
        per live tenant (bounded by the LRU cap)."""
        with self._lock:
            out: Dict[str, float] = {
                "tenantTableSize": len(self._tenants),
                "tenantEvictions": self.evictions,
                "tenantShedTotal": sum(
                    st.shed for st in self._tenants.values()
                ),
                "tenantAdmittedTotal": sum(
                    st.admitted for st in self._tenants.values()
                ),
            }
            for name, st in self._tenants.items():
                slug = tenant_slug(name)
                out[f"tenantOffered_{slug}"] = st.offered
                out[f"tenantAdmitted_{slug}"] = st.admitted
                out[f"tenantShed_{slug}"] = st.shed
                out[f"tenantLevel_{slug}"] = st.level
            return out

    def status(self) -> Dict:
        """Nested dict for ``/statusz`` and the prometheus render."""
        now = float(self.clock())
        with self._lock:
            tenants = {}
            for name, st in self._tenants.items():
                self._refill(st, now)
                tenants[name] = {
                    "level": st.level,
                    "pressure": round(st.pressure, 4),
                    "offered": st.offered,
                    "admitted": st.admitted,
                    "shed": st.shed,
                    "retainedSpans": st.retained_spans,
                    "retainedShed": st.retained_shed,
                    "tokens": round(st.tokens, 1),
                }
            return {
                "enabled": self.enabled,
                "budgetBytesPerS": self.bytes_per_s,
                "burstS": self.burst_s,
                "maxTenants": self.max_tenants,
                "floodRatio": self.flood_ratio,
                "evictions": self.evictions,
                "tenants": tenants,
            }
