"""Adaptive tail-sampling tier (ISSUE 4).

The north star needs a principled overload answer: the throttle sheds
load by REJECTING batches, which loses exactly the error/outlier traces
an operator wants most. This tier instead samples RETENTION — sketches
(t-digest, HLL, link matrices) always see 100% of spans, so percentiles
and cardinality stay unbiased, while WAL / disk-archive / RAM-archive
persistence only keeps spans a deterministic verdict selects:

- every ERROR span is kept;
- every TAIL span is kept (duration >= the published per-(service,
  spanName) threshold, refreshed from the live t-digests);
- every span on a RARE dependency edge is kept (published (svc, rsvc)
  link count below ``sample_rare_min``);
- the rest keep with per-service probability ``rate/65536`` via a
  trace-affine salted hash — so a sampled trace is kept or dropped as
  a UNIT, and replays reproduce identical decisions.

Determinism is the design center: verdicts are a pure u32 function of
(span fields, published tables). The tables are host-authoritative —
the controller (controller.py) computes them on host and PUBLISHES them
by swapping the ``s_rate`` / ``s_tail`` / ``s_link`` state leaves under
the aggregator lock; the device only reads them. The host reference
sampler (reference.py) evaluates the same function over the same
published tables with numpy, so device and host verdicts are
bit-identical (the tier's parity oracle, tests/test_sampling.py), and a
crash-resume that restores the tables (snapshot + WAL ``sctl`` deltas)
reproduces byte-identical verdicts (tests/test_sampling_resume.py).
"""

from __future__ import annotations

# Salt folded into the trace-id hash before the keep threshold compare:
# decorrelates the sampling hash from the HLL register hash (both start
# from fmix32(trace_h)) so dropping hash-low traces cannot bias the
# cardinality sketch's register selection.
VERDICT_SALT = 0x53414D50  # "SAMP"

# rate fixed-point: keep probability = rate / RATE_ONE; the hash compare
# uses the TOP 16 bits of the mixed id, so RATE_ONE (> any h16) is
# keep-everything and 0 keeps only error/tail/rare spans.
RATE_ONE = 65536

from zipkin_tpu.sampling.controller import RateController  # noqa: E402
from zipkin_tpu.sampling.device import device_verdict  # noqa: E402
from zipkin_tpu.sampling.reference import HostSampler, host_verdict  # noqa: E402

__all__ = [
    "VERDICT_SALT",
    "RATE_ONE",
    "device_verdict",
    "HostSampler",
    "host_verdict",
    "RateController",
]
