"""Adaptive per-service rate controller for the sampling tier.

Closes the loop on a retained-spans/sec budget: each interval it reads
the host-exact seen/kept tallies, nudges every service's hash-keep rate
toward the budget's fair ratio, refreshes the per-key tail thresholds
from the live t-digests, and PUBLISHES the new tables — host reference
and device leaves swapped together under the aggregator lock, with a
sparse ``sctl`` WAL record logged at the same point of the batch stream
so crash-resume replays land the identical tables (and therefore the
identical verdicts) between the same two batches.

The controller itself runs free-floating host float math — determinism
does NOT depend on reproducing its decisions, only on replaying the
TABLES it published, which the sctl records carry exactly.

Under throttle pressure (``note_pressure``: a batch the admission
throttle rejected outright) the next interval tightens the effective
budget, so sustained overload degrades into lower sampling rates — the
graceful mode — instead of more rejections.

Tenant budgets (ISSUE 18): :class:`TenantBudgetTable` tracks per-tenant
retained-spans/sec token buckets, charged at dispatcher ack time (span
counts are only known post-parse) and consulted by the admission
chokepoint (``runtime/tenant.py``) so a tenant that retains beyond its
budget is shed at the door with tenant-scoped guidance while the GLOBAL
sampling budget — and every other tenant — is untouched. The table is
bounded (LRU, evictions counted) so a hostile tenant-id stream cannot
grow controller state.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from collections import OrderedDict

import numpy as np

from zipkin_tpu import obs
from zipkin_tpu.sampling import RATE_ONE

logger = logging.getLogger(__name__)


class TenantBudgetTable:
    """Per-tenant retained-spans/sec token buckets (ISSUE 18).

    One bucket per tenant over RETAINED spans — the durable cost a
    tenant imposes downstream of sampling — refilled at
    ``spans_per_s`` with ``spans_per_s * burst_s`` of burst headroom.
    ``charge`` deducts at dispatcher ack time and may drive a bucket
    negative (the spans are already retained; the debt throttles the
    NEXT admission decision); ``over_budget`` is the read-only probe
    the admission chokepoint consults before accepting more bytes from
    that tenant.

    Bounded: at most ``max_tenants`` rows, LRU-evicted (the "default"
    tenant is never evicted — it anchors legacy traffic), evictions
    counted — a hostile tenant-id stream cannot grow controller state.
    ``spans_per_s <= 0`` disables enforcement (``over_budget`` is
    always False) while still tallying per-tenant retained counts.
    """

    def __init__(
        self,
        spans_per_s: float = 0.0,
        burst_s: float = 2.0,
        max_tenants: int = 64,
        clock=time.monotonic,
    ) -> None:
        self.spans_per_s = float(spans_per_s)
        self.burst_s = float(burst_s)
        self.max_tenants = max(1, int(max_tenants))
        self.evictions = 0
        self._clock = clock
        self._lock = threading.Lock()
        # tenant -> [tokens, last_refill, retained_total]
        self._rows: "OrderedDict[str, list]" = OrderedDict()

    @property
    def enabled(self) -> bool:
        return self.spans_per_s > 0.0

    @property
    def burst_spans(self) -> float:
        return self.spans_per_s * self.burst_s

    def _row(self, tenant: str) -> list:
        row = self._rows.get(tenant)
        if row is None:
            while len(self._rows) >= self.max_tenants:
                victim = next(
                    (k for k in self._rows if k != "default"), None
                )
                if victim is None:
                    break
                self._rows.pop(victim)
                self.evictions += 1
            row = [self.burst_spans, self._clock(), 0]
            self._rows[tenant] = row
        else:
            self._rows.move_to_end(tenant)
        return row

    def _refill(self, row: list) -> None:
        now = self._clock()
        dt = now - row[1]
        if dt > 0:
            row[0] = min(self.burst_spans, row[0] + dt * self.spans_per_s)
            row[1] = now

    def charge(self, tenant: str, n_spans: int) -> bool:
        """Deduct ``n_spans`` retained spans from ``tenant``'s bucket;
        returns True while the tenant stays within budget. May drive
        the bucket negative — the debt gates future admission."""
        with self._lock:
            row = self._row(tenant)
            row[2] += int(n_spans)
            if not self.enabled:
                return True
            self._refill(row)
            row[0] -= float(n_spans)
            return row[0] >= 0.0

    def over_budget(self, tenant: str) -> bool:
        """Read-only probe: is this tenant's retained-spans bucket in
        debt right now? Never creates a row."""
        if not self.enabled:
            return False
        with self._lock:
            row = self._rows.get(tenant)
            if row is None:
                return False
            self._refill(row)
            return row[0] < 0.0

    def retained(self, tenant: str) -> int:
        with self._lock:
            row = self._rows.get(tenant)
            return int(row[2]) if row is not None else 0

    def counters(self) -> dict:
        with self._lock:
            return {
                "tenantBudgetTableSize": len(self._rows),
                "tenantBudgetEvictions": self.evictions,
                "tenantRetainedTotal": sum(
                    int(r[2]) for r in self._rows.values()
                ),
            }


class RateController:
    def __init__(
        self,
        store,
        budget_spans_per_sec: float,
        interval_s: float = 5.0,
        min_rate: int = 256,
        tail_quantile: float = 0.99,
        pressure_tighten: float = 0.7,
    ) -> None:
        self.store = store
        self.budget = float(budget_spans_per_sec)
        self.interval_s = float(interval_s)
        self.min_rate = int(min_rate)
        self.tail_quantile = float(tail_quantile)
        self.pressure_tighten = float(pressure_tighten)
        self.publishes = 0
        self.pressure_events = 0
        self._pressure_pending = 0
        self.last_utilization = 0.0
        # optional per-tenant retained-spans budgets (ISSUE 18); set by
        # server wiring so tenant counters ride this controller's export
        self.tenant_table: "TenantBudgetTable | None" = None
        self._thread = None
        self._stop = threading.Event()

    # -- throttle integration -------------------------------------------

    def note_pressure(self) -> None:
        """Record one admission-throttle rejection: the next tick treats
        the budget as tighter, shifting degradation from rejecting
        batches to sampling harder."""
        self.pressure_events += 1
        self._pressure_pending += 1

    # -- the control step ------------------------------------------------

    def tick(self, dt_s: float) -> bool:
        """One control interval over ``dt_s`` seconds of tallies; returns
        True when new tables were published. Safe to call from a test
        with a synthetic dt — nothing here reads the wall clock."""
        sampler = self.store.agg.sampler
        if sampler is None or dt_s <= 0:
            return False
        t0 = time.perf_counter()
        seen, kept = sampler.take_tallies()
        total_seen = int(seen.sum())
        total_kept = int(kept.sum())
        budget = self.budget
        if self._pressure_pending:
            budget *= self.pressure_tighten ** min(self._pressure_pending, 8)
            self._pressure_pending = 0
        budget_spans = budget * dt_s
        self.last_utilization = (
            total_kept / dt_s / self.budget if self.budget > 0 else 0.0
        )
        rate = sampler.rate.astype(np.float64)
        if total_seen > 0 and budget_spans > 0:
            ratio = min(1.0, budget_spans / total_seen)
            active = seen > 0
            kept_frac = np.maximum(kept / np.maximum(seen, 1), 1e-6)
            # proportional step toward each service keeping ~ratio of its
            # traffic, slew-limited so one noisy interval can't slam the
            # rate; error/tail/rare keeps count against kept_frac, so
            # services whose mandatory keeps already exceed the ratio
            # converge to the min_rate floor rather than oscillating
            factor = np.clip(ratio / kept_frac, 0.25, 4.0)
            rate = np.where(
                active,
                np.clip(rate * factor, self.min_rate, RATE_ONE),
                rate,
            )
        new_rate = np.rint(rate).astype(np.uint32)
        new_tail = self._tail_thresholds(sampler)
        new_link = sampler.link_snapshot()
        self._publish(sampler, new_rate, new_tail, new_link)
        obs.record("sampler_tick", time.perf_counter() - t0)
        return True

    def _tail_thresholds(self, sampler) -> np.ndarray:
        """Per-key u32 tail cut from the live t-digests: keys with
        traffic get ceil(q_tail); silent keys keep the unreachable
        sentinel so the tail clause can never fire for them."""
        q, counts = self.store.agg.quantiles(
            [self.tail_quantile], source="digest"
        )
        tail = sampler.tail.copy()
        have = counts > 0
        thr = np.ceil(np.maximum(q[:, 0], 1.0))
        tail[have] = np.minimum(thr[have], float(0xFFFFFFFF)).astype(np.uint32)
        return tail

    def _publish(self, sampler, rate, tail, link) -> None:
        agg = self.store.agg
        with agg.lock:
            delta = sampler.sctl_delta(rate, tail, link)
            if delta and agg.wal_hook is not None:
                # a zero-lane record at THIS point of the WAL stream:
                # replay applies the delta between the same batches the
                # live run published between, so every replayed verdict
                # reads the same tables the original run did
                empty = np.zeros((agg.n_shards, 11, 0), np.uint32)
                agg.wal_seq = agg.wal_hook(
                    empty, 0, 0, 0, None, extra={"sctl": delta}
                )
            sampler.set_tables(rate, tail, link)
            agg.set_sampler_tables(sampler.rate, sampler.tail, sampler.link)
            self.publishes += 1

    # -- background driver ----------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            last = time.monotonic()
            while not self._stop.wait(self.interval_s):
                now = time.monotonic()
                try:
                    self.tick(now - last)
                except Exception:  # pragma: no cover - keep the loop alive
                    logger.exception("sampling controller tick failed")
                last = now

        self._thread = threading.Thread(
            target=loop, name="sampling-controller", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=self.interval_s + 5)
        self._thread = None

    def counters(self) -> dict:
        """Scalar gauges merged into store.ingest_counters()."""
        out = {
            "samplerPublishes": self.publishes,
            "samplerPressure": self.pressure_events,
            "budgetUtilization": round(self.last_utilization, 6),
        }
        sampler = self.store.agg.sampler
        if sampler is not None:
            r = sampler.rate
            out["samplerRateMin"] = int(r.min()) / RATE_ONE
            out["samplerRateMean"] = float(r.mean()) / RATE_ONE
        if self.tenant_table is not None:
            out.update(self.tenant_table.counters())
        return out
