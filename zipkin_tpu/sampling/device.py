"""Device half of the sampling tier: the jit-safe verdict function.

Called from the ingest step (tpu/ingest.py) when ``config.sampling`` is
on. MUST stay bit-identical to :func:`zipkin_tpu.sampling.reference.
host_verdict` — same salt, same mix, same clip semantics, same operand
dtypes — that parity is the tier's oracle (tests/test_sampling.py).
"""

from __future__ import annotations

import jax.numpy as jnp

from zipkin_tpu.ops import hashing
from zipkin_tpu.sampling import VERDICT_SALT


def device_verdict(
    trace_h: jnp.ndarray,
    svc: jnp.ndarray,
    rsvc: jnp.ndarray,
    key: jnp.ndarray,
    dur: jnp.ndarray,
    has_dur: jnp.ndarray,
    err: jnp.ndarray,
    valid: jnp.ndarray,
    s_rate: jnp.ndarray,
    s_tail: jnp.ndarray,
    s_link: jnp.ndarray,
    rare_min: int,
) -> jnp.ndarray:
    """[n] bool keep verdicts — a pure u32 function of the span fields
    and the PUBLISHED tables, so replay with the same tables reproduces
    the same bits. The hash term is trace-affine (trace_h only): a
    rate-sampled trace is kept or dropped as a unit."""
    u = jnp.uint32
    h16 = hashing.fmix32(trace_h ^ u(VERDICT_SALT)) >> u(16)
    svc_c = jnp.clip(svc, 0, s_rate.shape[0] - 1)
    rsvc_c = jnp.clip(rsvc, 0, s_rate.shape[0] - 1)
    key_c = jnp.clip(key, 0, s_tail.shape[0] - 1)
    tail = has_dur & (dur >= s_tail[key_c])
    rare = (rsvc > 0) & (s_link[svc_c, rsvc_c] < u(rare_min))
    return valid & (err | tail | rare | (h16 < s_rate[svc_c]))
