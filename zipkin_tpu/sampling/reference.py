"""Host reference sampler: the authoritative, bit-exact verdict oracle.

Two jobs:

1. **Gate retention on host.** WAL records, the disk archive, and the
   RAM archive sample persist only spans whose verdict is keep. The
   verdict math here mirrors :func:`zipkin_tpu.sampling.device.
   device_verdict` operation-for-operation over the SAME published
   tables (``columnar._mix32`` is the proven numpy mirror of
   ``ops.hashing.fmix32``), so host gating and the device's recorded
   ``r_keep`` bits agree exactly — the tier's parity oracle.

2. **Feed the controller.** Every batch that reaches
   ``ShardedAggregator.ingest_fused`` (the funnel all ingest paths share
   — sync fast path, object path, MP dispatcher) is ``observe``d once:
   exact per-service seen/kept tallies plus the LIVE (svc, rsvc) edge
   counts the controller publishes from. The live counts never gate
   anything directly — verdicts read only the last PUBLISHED tables, on
   both host and device, which is what makes them reproducible.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

import numpy as np

from zipkin_tpu.sampling import RATE_ONE, VERDICT_SALT
from zipkin_tpu.tpu.columnar import SpanColumns, _hash2_np, _mix32


def host_verdict(
    trace_h: np.ndarray,
    svc: np.ndarray,
    rsvc: np.ndarray,
    key: np.ndarray,
    dur: np.ndarray,
    has_dur: np.ndarray,
    err: np.ndarray,
    valid: np.ndarray,
    rate: np.ndarray,
    tail: np.ndarray,
    link: np.ndarray,
    rare_min: int,
) -> np.ndarray:
    """numpy mirror of :func:`sampling.device.device_verdict` (keep the
    two in lockstep — the parity test fails on any divergence)."""
    h16 = _mix32(trace_h.astype(np.uint32) ^ np.uint32(VERDICT_SALT)) >> np.uint32(16)
    svc_c = np.clip(svc, 0, rate.shape[0] - 1).astype(np.int64)
    rsvc_c = np.clip(rsvc, 0, rate.shape[0] - 1).astype(np.int64)
    key_c = np.clip(key, 0, tail.shape[0] - 1).astype(np.int64)
    tail_hit = has_dur & (dur >= tail[key_c])
    rare = (rsvc > 0) & (link[svc_c, rsvc_c] < np.uint32(rare_min))
    return valid & (err | tail_hit | rare | (h16 < rate[svc_c]))


class HostSampler:
    """Published tables + live observations for one storage instance.

    Thread model: verdicts only READ the published table references
    (publish swaps whole arrays — a Python attribute store, atomic), so
    they take no lock. ``observe`` and the controller's table reads
    mutate shared tallies and serialize on ``self._lock``; the caller
    (``ingest_fused``) additionally holds the aggregator lock, which is
    what orders observations against table publishes.
    """

    def __init__(self, max_services: int, max_keys: int, rare_min: int = 4) -> None:
        self.rare_min = int(rare_min)
        # published tables — always swapped wholesale, never mutated in
        # place (except apply_sctl during single-threaded boot replay)
        self.rate = np.full(max_services, RATE_ONE, np.uint32)
        self.tail = np.full(max_keys, 0xFFFFFFFF, np.uint32)
        self.link = np.zeros((max_services, max_services), np.uint32)
        # live observations the controller publishes FROM
        self.link_live = np.zeros((max_services, max_services), np.uint64)
        self.seen_by_svc = np.zeros(max_services, np.int64)
        self.kept_by_svc = np.zeros(max_services, np.int64)
        self._lock = threading.Lock()

    # -- verdicts (pure reads of the published tables) -------------------

    def verdict_cols(self, cols: SpanColumns) -> np.ndarray:
        """[n] bool keep verdicts in SpanColumns lane order (gates the
        RAM/disk archive writes, which see the batch pre-routing)."""
        return host_verdict(
            cols.trace_h, cols.svc, cols.rsvc, cols.key, cols.dur,
            cols.has_dur, cols.err, cols.valid,
            self.rate, self.tail, self.link, self.rare_min,
        )

    def verdict_fused(self, fused: np.ndarray) -> np.ndarray:
        """[shards, per] bool keep verdicts over a routed wire image —
        the same pure function in the device's lane order (gates WAL
        persistence and is what the parity oracle compares to r_keep)."""
        f = np.asarray(fused)
        sr, kf = f[..., 9, :], f[..., 10, :]
        return host_verdict(
            f[..., 0, :],
            (sr >> np.uint32(16)).astype(np.int64),
            (sr & np.uint32(0xFFFF)).astype(np.int64),
            (kf >> np.uint32(8)).astype(np.int64),
            f[..., 7, :],
            (kf & np.uint32(8)) != 0,
            (kf & np.uint32(4)) != 0,
            (kf & np.uint32(1)) != 0,
            self.rate, self.tail, self.link, self.rare_min,
        )

    def gate_record(self, rec: tuple):
        """Gate one prebuilt disk-archive record (archive.parsed_record
        layout: payload, off, ln, tl0, tl1, th0, th1, svc, rsvc, name,
        key, ts_min, dur, err — GLOBAL vocab ids) down to its kept
        spans, compacting the raw-byte payload. Returns the filtered
        record, or None when nothing survives. The MP dispatcher's
        archive seam — worker-shipped records never pass through
        SpanColumns, so the verdict is recomputed from the index
        columns here. ``has_dur`` approximates as ``dur > 0``: the
        controller's tail thresholds are always >= 1, so the tail
        clause is unaffected and the verdict matches the cols path."""
        tl0, tl1, th0, th1 = rec[3], rec[4], rec[5], rec[6]
        trace_h = _hash2_np(_hash2_np(tl0, tl1), _hash2_np(th0, th1))
        dur = np.minimum(rec[12], 0xFFFFFFFF).astype(np.uint32)
        keep = host_verdict(
            trace_h,
            rec[7].astype(np.int64), rec[8].astype(np.int64),
            rec[10].astype(np.int64),
            dur, dur > 0, np.asarray(rec[13], bool),
            np.ones(len(rec[1]), bool),
            self.rate, self.tail, self.link, self.rare_min,
        )
        if bool(keep.all()):
            return rec
        idx = np.nonzero(keep)[0]
        if not len(idx):
            return None
        payload, off, ln = rec[0], rec[1], rec[2]
        parts = [bytes(payload[off[i] : off[i] + ln[i]]) for i in idx]
        new_ln = np.asarray(ln)[idx].astype(np.uint32)
        new_off = np.zeros(len(idx), np.uint32)
        pos = 0
        for j, p in enumerate(parts):
            new_off[j] = pos
            pos += len(p)
        rest = tuple(np.asarray(col)[idx] for col in rec[3:])
        return (b"".join(parts), new_off, new_ln) + rest

    # -- observations (once per batch, at the ingest_fused funnel) -------

    def observe(self, fused: np.ndarray, keep: np.ndarray) -> Tuple[int, int]:
        """Fold one routed batch's lanes into the live tallies; returns
        (seen, kept) span counts for the batch. Call exactly ONCE per
        batch — ``ingest_fused`` is the funnel every path goes through."""
        f = np.asarray(fused)
        sr, kf = f[..., 9, :], f[..., 10, :]
        valid = (kf & np.uint32(1)) != 0
        svc = np.clip(
            (sr >> np.uint32(16)).astype(np.int64)[valid],
            0, self.rate.shape[0] - 1,
        )
        rsvc = (sr & np.uint32(0xFFFF)).astype(np.int64)[valid]
        k = np.asarray(keep)[valid]
        with self._lock:
            e = rsvc > 0
            np.add.at(self.link_live, (svc[e], np.clip(rsvc[e], 0, self.rate.shape[0] - 1)), 1)
            np.add.at(self.seen_by_svc, svc, 1)
            np.add.at(self.kept_by_svc, svc, k.astype(np.int64))
        return int(valid.sum()), int(k.sum())

    def take_tallies(self) -> Tuple[np.ndarray, np.ndarray]:
        """(seen, kept) per-service counts since the last take; resets."""
        with self._lock:
            seen, kept = self.seen_by_svc.copy(), self.kept_by_svc.copy()
            self.seen_by_svc[:] = 0
            self.kept_by_svc[:] = 0
        return seen, kept

    def link_snapshot(self) -> np.ndarray:
        """u32 publishable copy of the live edge counts (clamped)."""
        with self._lock:
            return np.minimum(self.link_live, 0xFFFFFFFF).astype(np.uint32)

    # -- WAL compaction --------------------------------------------------

    def compact_fused(
        self, fused: np.ndarray, keep: np.ndarray, pad: int = 256
    ) -> Optional[Tuple[np.ndarray, int, int, int, tuple]]:
        """Repack a routed wire image down to its KEPT lanes (per-shard
        stable order, zero-padded to a ``pad`` multiple) — what the WAL
        persists instead of the full batch. Returns (fused', n_spans,
        n_dur, n_err, ts_range), or None when nothing was kept (the
        caller then skips the WAL record entirely)."""
        f = np.asarray(fused)
        k = np.asarray(keep)
        shards, rows, _ = f.shape
        counts = k.sum(axis=1)
        m = int(counts.max()) if counts.size else 0
        if m == 0:
            return None
        per2 = -(-m // pad) * pad
        out = np.zeros((shards, rows, per2), np.uint32)
        for s in range(shards):
            idx = np.nonzero(k[s])[0]
            out[s, :, : len(idx)] = f[s][:, idx]
        kf = out[:, 10, :]
        valid = (kf & np.uint32(1)) != 0
        ts = out[:, 8, :][valid]
        return (
            out,
            int(valid.sum()),
            int(((kf & np.uint32(8)) != 0).sum()),
            int(((kf & np.uint32(4)) != 0).sum()),
            (int(ts.min()), int(ts.max())) if ts.size else (0, 0),
        )

    # -- publish / restore ----------------------------------------------

    def sctl_delta(
        self, rate: np.ndarray, tail: np.ndarray, link: np.ndarray
    ) -> dict:
        """Sparse JSON-able diff of a new publish vs the current tables —
        the WAL ``sctl`` record payload. Replaying these deltas in order
        on top of snapshot-restored tables reconstructs the EXACT tables
        at every point of the batch stream, which is what makes
        post-resume verdicts byte-identical. Link diffs use flat [S*S]
        indices; real service graphs are sparse so they stay small."""
        d: dict = {}
        r = np.nonzero(rate != self.rate)[0]
        if len(r):
            d["r"] = [[int(i), int(rate[i])] for i in r]
        t = np.nonzero(tail != self.tail)[0]
        if len(t):
            d["t"] = [[int(i), int(tail[i])] for i in t]
        l = np.nonzero(link.ravel() != self.link.ravel())[0]
        if len(l):
            d["l"] = [[int(i), int(link.ravel()[i])] for i in l]
        return d

    def set_tables(
        self, rate: np.ndarray, tail: np.ndarray, link: np.ndarray
    ) -> None:
        """Swap in newly published tables (whole-array stores: verdict
        readers see either the old or the new publish, never a mix of a
        mutated array)."""
        self.rate = np.ascontiguousarray(rate, np.uint32)
        self.tail = np.ascontiguousarray(tail, np.uint32)
        self.link = np.ascontiguousarray(link, np.uint32)

    def apply_sctl(self, delta: dict) -> None:
        """Apply one replayed ``sctl`` WAL delta (boot-time, before the
        sampler gates anything — single-threaded by construction)."""
        rate, tail, link = self.rate.copy(), self.tail.copy(), self.link.copy()
        for i, v in delta.get("r", ()):
            rate[int(i)] = np.uint32(v)
        for i, v in delta.get("t", ()):
            tail[int(i)] = np.uint32(v)
        flat = link.ravel()
        for i, v in delta.get("l", ()):
            flat[int(i)] = np.uint32(v)
        self.set_tables(rate, tail, link)

    def restore_tables(
        self, s_rate: np.ndarray, s_tail: np.ndarray, s_link: np.ndarray
    ) -> None:
        """Seed the published tables from snapshot-restored state leaves
        (one shard's copy — the leaves are replicated by construction)
        and the live counts from the published link table. Edges
        observed after the last publish but before the crash are lost
        from link_live (the WAL logs verdict INPUTS, not every
        observation); the loss biases toward treating edges as rare,
        i.e. toward KEEPING spans — fail-open."""
        self.set_tables(s_rate, s_tail, s_link)
        with self._lock:
            self.link_live = self.link.astype(np.uint64)
            self.seen_by_svc[:] = 0
            self.kept_by_svc[:] = 0
