"""L4: the HTTP server exposing the Zipkin v2 API over any storage backend."""
