"""``python -m zipkin_tpu.server`` — boot from environment config.

Flags override the reference-named env vars (SURVEY.md §2.4 config row):
``--port`` beats ``QUERY_PORT``, ``--storage`` beats ``STORAGE_TYPE``.
"""

import argparse
import asyncio
import logging
import os

if __name__ == "__main__":
    parser = argparse.ArgumentParser(prog="zipkin_tpu.server")
    parser.add_argument(
        "--port", type=int, default=None,
        help="HTTP port (default: $QUERY_PORT or 9411)",
    )
    parser.add_argument(
        "--storage", default=None,
        help="storage backend: tpu|mem (default: $STORAGE_TYPE)",
    )
    parser.add_argument(
        "--resume-dir", default=None,
        help="durable state root: boot restores <dir>/snap, replays "
        "<dir>/wal, resumes transport offsets; new batches persist "
        "back under it (default: $TPU_RESUME_DIR)",
    )
    args = parser.parse_args()
    # env must be set before the app module builds its config
    if args.port is not None:
        os.environ["QUERY_PORT"] = str(args.port)
    if args.storage is not None:
        os.environ["STORAGE_TYPE"] = args.storage
    if args.resume_dir is not None:
        os.environ["TPU_RESUME_DIR"] = args.resume_dir

    from zipkin_tpu.server.app import run_server

    logging.basicConfig(level=logging.INFO)
    asyncio.run(run_server())
