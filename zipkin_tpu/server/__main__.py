"""``python -m zipkin_tpu.server`` — boot from environment config."""

import asyncio
import logging

from zipkin_tpu.server.app import run_server

if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    asyncio.run(run_server())
